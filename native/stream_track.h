// stream_track.h — per-stream incremental featurization + bounded
// stream table for the native engines.
//
// Everything the engines scored before this header was request-shaped:
// one feature row at exchange/stream completion. Long-lived h2/gRPC
// streams, WebSocket upgrades, and CONNECT tunnels carry most of their
// bytes AFTER the opening exchange, so they need a scoring key with
// stream lifetime. Both epoll engines embed the same two pieces:
//
// - StreamAccum: per-frame feature deltas (inter-frame gap EWMA +
//   deviation, bytes-per-DATA-frame EWMA + deviation, WINDOW_UPDATE
//   cadence, reset/flow-control anomaly count) in pure float32
//   arithmetic, mirrored BIT-IDENTICALLY by
//   linkerd_tpu.streams.tracker.StreamTracker (pinned by the parity
//   test; no FMA contraction on the default x86-64 flags).
//
// - StreamTable: bounded per-stream aggregates keyed by a 24-bit
//   stream key (float32-integer-exact, rides the feature row), with
//   the same amortized stalest-quarter LRU as tenant_guard.h's
//   TenantTable — hostile stream churn buys eviction work, never
//   memory. Live streams (inflight) are never evicted.
//
// Sampling cadence, hysteresis thresholds (enter/exit/quorum/dwell,
// the native mirror of control.state.HysteresisGovernor), and the
// actuation mode arrive from Python BEFORE start() via
// fp_set_stream_cfg / fph2_set_stream_cfg.

#pragma once

#include <math.h>
#include <stdint.h>
#include <stdio.h>

#include <algorithm>
#include <string>
#include <unordered_map>
#include <utility>
#include <vector>

namespace l5dstream {

// Feature-row kinds (row column 9). Request rows are 0 so the widened
// format stays backward-readable: old rows zero-fill the new columns.
constexpr int ROW_REQUEST = 0;
constexpr int ROW_STREAM = 1;  // h2 stream sample
constexpr int ROW_TUNNEL = 2;  // CONNECT / 101-upgrade byte tunnel

// Frame kinds fed to accum_frame.
constexpr int FRAME_DATA = 0;
constexpr int FRAME_WINDOW_UPDATE = 1;
constexpr int FRAME_ANOMALY = 2;  // RST / flow-control violation

// Stream keys ride feature rows folded to 24 bits so the value stays
// exact in float32; 0 is reserved for "not a stream row".
inline uint32_t fold_key(uint32_t k) {
    uint32_t f = k & 0xFFFFFFu;
    return f == 0 ? 1u : f;
}

// ---- per-frame accumulation ------------------------------------------------

// All EWMAs use alpha = 1/8 in plain float32 (mult then add, never
// fused): the Python mirror reproduces every intermediate rounding.
struct StreamAccum {
    float gap_ewma_ms = 0.0f;  // inter-frame gap EWMA
    float gap_dev_ms = 0.0f;   // mean-abs-deviation EWMA of the gap
    float bpf_ewma = 0.0f;     // bytes per DATA frame EWMA
    float bpf_dev = 0.0f;      // mean-abs-deviation EWMA of bytes/frame
    uint32_t frames = 0;       // every frame (DATA/WU/anomaly)
    uint32_t data_frames = 0;
    uint32_t wu_frames = 0;    // WINDOW_UPDATE cadence
    uint32_t anomalies = 0;    // resets + flow-control violations
    uint64_t bytes = 0;        // DATA payload bytes
};

inline void accum_frame(StreamAccum* a, int kind, float gap_ms,
                        float bytes) {
    a->frames++;
    if (a->frames == 1) {
        a->gap_ewma_ms = gap_ms;
    } else {
        const float d = gap_ms - a->gap_ewma_ms;
        a->gap_ewma_ms += 0.125f * d;
        a->gap_dev_ms += 0.125f * (fabsf(d) - a->gap_dev_ms);
    }
    if (kind == FRAME_DATA) {
        a->data_frames++;
        a->bytes += (uint64_t)bytes;
        if (a->data_frames == 1) {
            a->bpf_ewma = bytes;
        } else {
            const float db = bytes - a->bpf_ewma;
            a->bpf_ewma += 0.125f * db;
            a->bpf_dev += 0.125f * (fabsf(db) - a->bpf_dev);
        }
    } else if (kind == FRAME_WINDOW_UPDATE) {
        a->wu_frames++;
    } else {
        a->anomalies++;
    }
}

// ---- sampling + actuation config -------------------------------------------

struct StreamCfg {
    int enabled = 0;
    uint32_t sample_every = 8;        // frames between score samples
    uint64_t sample_min_gap_us = 10'000;
    size_t table_cap = 4096;
    // native hysteresis (control.state.HysteresisGovernor mirror):
    // score EWMA >= enter for `quorum` consecutive samples -> SICK;
    // <= exit for `quorum` consecutive samples -> healthy again.
    // dwell_us is the minimum hold after any transition.
    double enter = 0.8;
    double exit_ = 0.5;
    int quorum = 3;
    uint64_t dwell_us = 1'000'000;
    int action = 1;  // 0 = observe only, 1 = RST/close the sick stream
    // tunnel guard budgets (h1 engine byte tunnels): zero-activity
    // window and lifetime byte cap; 0 disables the individual cap.
    uint64_t tunnel_idle_us = 0;
    uint64_t tunnel_max_bytes = 0;
};

// Per-stream hysteresis state embedded in each engine's stream object.
struct StreamGov {
    float score_ewma = 0.0f;
    int streak = 0;
    bool sick = false;
    uint64_t transition_us = 0;
    uint32_t last_sample_frames = 0;
    uint64_t last_sample_us = 0;
};

// True when this sample is due (cadence + min-gap both satisfied).
inline bool sample_due(const StreamCfg& cfg, const StreamAccum& a,
                       const StreamGov& g, uint64_t now) {
    if (a.frames < g.last_sample_frames + cfg.sample_every) return false;
    return now - g.last_sample_us >= cfg.sample_min_gap_us;
}

// Feed one score observation; returns +1 on a healthy->sick
// transition, -1 on sick->healthy, 0 otherwise. Same split-threshold /
// consecutive-quorum / dwell semantics as HysteresisGovernor.observe.
inline int gov_observe(const StreamCfg& cfg, StreamGov* g, float score,
                       uint64_t now) {
    g->score_ewma += 0.25f * (score - g->score_ewma);
    const double level = (double)g->score_ewma;
    const bool held =
        g->transition_us != 0 && now - g->transition_us < cfg.dwell_us;
    if (!g->sick) {
        if (level >= cfg.enter) g->streak++;
        else g->streak = 0;
        if (g->streak >= cfg.quorum && !held) {
            g->sick = true;
            g->streak = 0;
            g->transition_us = now;
            return 1;
        }
    } else {
        if (level <= cfg.exit_) g->streak++;
        else g->streak = 0;
        if (g->streak >= cfg.quorum && !held) {
            g->sick = false;
            g->streak = 0;
            g->transition_us = now;
            return -1;
        }
    }
    return 0;
}

// ---- bounded stream table --------------------------------------------------

struct StreamStats {
    uint64_t samples = 0;
    uint64_t scored = 0;
    double score_ewma = 0.0;
    uint32_t frames = 0;
    uint64_t bytes = 0;
    int kind = ROW_STREAM;
    bool sick = false;
    int inflight = 0;  // 1 while the stream/tunnel is live
    uint64_t last_seen_us = 0;
};

// Same amortized stalest-quarter eviction as l5dtg::TenantTable;
// callers hold the engine mu.
struct StreamTable {
    std::unordered_map<uint32_t, StreamStats> map;
    size_t cap = 4096;
    uint64_t evicted = 0;
    // engine-wide actuation counters (mu-held like the map)
    uint64_t sick_transitions = 0;
    uint64_t rst_sent = 0;
    uint64_t tunnels_opened = 0;
    uint64_t tunnel_idle_closed = 0;
    uint64_t tunnel_bytes_closed = 0;

    StreamStats* get(uint32_t k, uint64_t now_us) {
        auto it = map.find(k);
        if (it != map.end()) {
            it->second.last_seen_us = now_us;
            return &it->second;
        }
        if (map.size() >= cap) evict(now_us);
        StreamStats& ss = map[k];
        ss.last_seen_us = now_us;
        return &ss;
    }

    StreamStats* peek(uint32_t k) {
        auto it = map.find(k);
        return it == map.end() ? nullptr : &it->second;
    }

    void observe(uint32_t k, int kind, float score, bool scored,
                 const StreamAccum& a, bool sick, uint64_t now_us) {
        StreamStats* ss = get(k, now_us);
        ss->samples++;
        ss->kind = kind;
        ss->frames = a.frames;
        ss->bytes = a.bytes;
        ss->sick = sick;
        if (scored) {
            ss->scored++;
            ss->score_ewma += 0.1 * ((double)score - ss->score_ewma);
        }
    }

    void evict(uint64_t now_us) {
        std::vector<std::pair<uint64_t, uint32_t>> ages;
        ages.reserve(map.size());
        for (auto& kv : map)
            if (kv.second.inflight <= 0)
                ages.push_back({kv.second.last_seen_us, kv.first});
        if (ages.empty()) return;
        size_t k = ages.size() / 4;
        if (k == 0) k = 1;
        std::nth_element(ages.begin(), ages.begin() + (long)(k - 1),
                         ages.end());
        uint64_t cutoff = ages[k - 1].first;
        size_t dropped = 0;
        for (auto it = map.begin(); it != map.end() && dropped < k;) {
            if (it->second.inflight <= 0 &&
                it->second.last_seen_us <= cutoff) {
                it = map.erase(it);
                dropped++;
            } else {
                ++it;
            }
        }
        evicted += dropped;
        (void)now_us;
    }
};

// ---- stats JSON ------------------------------------------------------------

// Full `{"streams":{...}}` document for /streams.json (caller holds
// the engine mu for the table).
inline void streams_json(const StreamTable& t, bool enabled,
                         std::string* s) {
    char tmp[320];
    snprintf(tmp, sizeof(tmp),
             "{\"enabled\":%s,\"count\":%zu,\"evicted\":%llu,"
             "\"sick_transitions\":%llu,\"rst_sent\":%llu,"
             "\"tunnels_opened\":%llu,\"tunnel_idle_closed\":%llu,"
             "\"tunnel_bytes_closed\":%llu,\"by_stream\":{",
             enabled ? "true" : "false", t.map.size(),
             (unsigned long long)t.evicted,
             (unsigned long long)t.sick_transitions,
             (unsigned long long)t.rst_sent,
             (unsigned long long)t.tunnels_opened,
             (unsigned long long)t.tunnel_idle_closed,
             (unsigned long long)t.tunnel_bytes_closed);
    *s += tmp;
    bool first = true;
    for (auto& kv : t.map) {
        snprintf(tmp, sizeof(tmp),
                 "%s\"%u\":{\"kind\":%d,\"samples\":%llu,"
                 "\"scored\":%llu,\"score_ewma\":%.6f,\"frames\":%u,"
                 "\"bytes\":%llu,\"sick\":%s,\"live\":%s}",
                 first ? "" : ",", kv.first, kv.second.kind,
                 (unsigned long long)kv.second.samples,
                 (unsigned long long)kv.second.scored,
                 kv.second.score_ewma, kv.second.frames,
                 (unsigned long long)kv.second.bytes,
                 kv.second.sick ? "true" : "false",
                 kv.second.inflight > 0 ? "true" : "false");
        *s += tmp;
        first = false;
    }
    *s += "}}";
}

}  // namespace l5dstream
