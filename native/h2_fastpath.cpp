// h2 fastpath: native HTTP/2 (h2c prior-knowledge) proxy data-plane
// engine for gRPC and generic h2 traffic.
//
// Same control/data split as the HTTP/1.1 engine (fastpath.cpp): the
// per-frame hot loop (preface -> SETTINGS -> HPACK-decode HEADERS ->
// route by :authority -> re-encode + forward frames with flow control)
// runs on one C++ epoll thread; Python stays the control plane and
// installs concrete routes via fph2_set_route, drains misses, stats and
// per-request feature rows. Parity anchors: the reference's h2 data
// plane (finagle/h2/.../netty4/Netty4StreamTransport.scala:1-690 stream
// state machine, Netty4ClientDispatcher/Netty4ServerDispatcher stream-id
// demux, H2.scala:29 SingletonPool — one multiplexed upstream connection
// per endpoint), RoutingFactory.scala:154-187 (identify->bind->dispatch).
//
// Scope: h2 over TLS (ALPN "h2") and h2c prior-knowledge on both
// sides, full HPACK (h2_core.h), both flow-control levels with bounded
// buffering AND receive-side enforcement, CONTINUATION, trailers,
// PING, RST propagation, GOAWAY-reconnect (refused streams replay when
// the request is still retained, mirroring BufferedStream.scala:29's
// retry-buffer idea), MAX_CONCURRENT_STREAMS queueing toward upstreams.
// TLS rides tls_engine.h/tls_shim.h (non-blocking memory BIOs: the
// loop owns the sockets, OpenSSL never sees an fd); h1->h2c upgrade
// stays on the Python path. Writes are coalesced per socket wakeup —
// frame producers mark a conn dirty and the loop flushes each dirty
// conn once per epoll round (one send() per burst, one TLS record
// batch per burst).

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "h2_core.h"
#include "scorer.h"
#include "stream_track.h"
#include "tenant_guard.h"
#include "tls_engine.h"

namespace {

using h2::Hdr;

constexpr int MAX_EVENTS = 256;
constexpr int LAT_BUCKETS = 28;
constexpr uint64_t ROUTE_WAIT_TIMEOUT_US = 2'000'000;
// an upstream conn no route references (endpoint churn orphaned it) is
// closed after this much stream-less idle time
constexpr uint64_t ORPHAN_IDLE_TIMEOUT_US = 60'000'000;
// our advertised windows (we are a proxy: accept generously, gate grants
// on how much we have buffered for the slower side)
constexpr int64_t OUR_STREAM_WIN = 4 << 20;
constexpr int64_t OUR_CONN_WIN = 16 << 20;
constexpr uint64_t STREAM_GRANT = 256 * 1024;
constexpr uint64_t CONN_GRANT = 1 << 20;
constexpr size_t PEND_HIGH = 2 << 20;      // per-stream buffered cap
constexpr size_t CONN_BUF_HIGH = 8 << 20;  // per-source-conn buffered cap
constexpr size_t OUT_HIGH = 1 << 20;       // stop pumping into a fat out-buf
constexpr size_t RETAIN_CAP = 64 * 1024;   // GOAWAY-replay request buffer
constexpr size_t PARKED_PEND_CAP = 1 << 20;
constexpr uint32_t MAX_FRAME_OK = 17000;   // tolerated frame size
// TLS handshake budget (see fastpath.cpp): mid-handshake past this
// window -> closed by the sweep, counted as a handshake failure
constexpr uint64_t TLS_HS_TIMEOUT_US = 5'000'000;

uint64_t now_us() {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1'000'000 + ts.tv_nsec / 1000;
}

void set_nodelay(int fd) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

void lower(std::string& s) {
    for (auto& c : s) if (c >= 'A' && c <= 'Z') c += 32;
}

struct RouteStats {
    uint64_t requests = 0, success = 0, f4xx = 0, f5xx = 0, conn_fail = 0;
    uint64_t lat_hist[LAT_BUCKETS] = {0};
    void record(int status, uint64_t lat_us) {
        requests++;
        if (status >= 500) f5xx++;
        else if (status >= 400) f4xx++;
        else success++;
        int b = 0;
        uint64_t v = lat_us;
        while (v > 1 && b < LAT_BUCKETS - 1) { v >>= 1; b++; }
        lat_hist[b]++;
    }
};

struct H2Conn;

struct Endpoint {
    uint32_t ip_be = 0;
    uint16_t port = 0;
    int inflight = 0;
    H2Conn* conn = nullptr;  // one multiplexed conn (SingletonPool parity)
};

struct Route {
    uint64_t id = 0;
    std::vector<Endpoint> eps;
    uint32_t next = 0;
    RouteStats stats;
    // in-data-plane scorer state (see fastpath.cpp / scorer.h)
    l5dscore::RouteFeat feat;
};

struct FeatureRow {
    float route_id, latency_ms, status, req_bytes, rsp_bytes, ts_s;
    // in-data-plane scoring result (scored 1.0 = engine evaluated the
    // native model; 0.0 rows fall back to the JAX tier in Python)
    float score, scored;
    // tenant hash folded to 24 bits (f32-integer-exact); 0 = no tenant
    float tenant;
    // stream-lifetime key: kind (0 request / 1 stream sample / 2 tunnel
    // sample), 24-bit stream key (0 = not a stream row), frame seq at
    // sample time — mid-stream rows repeat the same key with a growing
    // frame_seq so Python consumers can track a stream over its life
    float kind, stream, frame_seq;
};

struct PStream;

struct Engine {
    int epfd = -1;
    int wakefd = -1;
    // teardown: no replays / new upstreams. Deliberately NOT atomic:
    // l5d: ignore[atomics-ordering] — written only after pthread_join of the loop thread; never read concurrently
    bool shutting_down = false;
    // response HEADERS must start within this window once dispatched
    // (the h1 engine's EXCHANGE_TIMEOUT analog); streaming bodies are
    // unbounded. Atomic: set from the control thread.
    std::atomic<uint64_t> response_start_timeout_us{30'000'000};
    std::atomic<bool> running{true};
    pthread_t thread;
    bool thread_started = false;

    std::mutex mu;  // guards routes, misses, features
    std::unordered_map<std::string, Route> routes;
    uint64_t next_route_id = 1;
    std::deque<std::string> misses;
    std::vector<FeatureRow> features;
    size_t features_cap = 65536;
    uint64_t features_dropped = 0;
    // in-data-plane scorer: weight slab has its own (lock-free reader)
    // sync; score_stats is guarded by mu like the feature buffer.
    // `slab` is the slab this engine scores/publishes through — its own
    // embedded one by default, or (multi-worker sharding) one external
    // process-wide slab shared READ-ONLY by every worker's epoll thread
    // (fph2_attach_slab, called before fph2_start): one publish flips
    // the active buffer for all workers atomically.
    l5dscore::Slab scorer_slab;
    l5dscore::Slab* slab = &scorer_slab;
    l5dscore::ScoreStats score_stats;
    // tenant accounting + per-tenant quotas (guarded by mu); the
    // extraction mode and guard knobs are installed BEFORE fph2_start
    // (wrapper-asserted), so the loop thread reads them unlocked
    l5dtg::TenantTable tenants;
    l5dtg::QuotaMap quotas;
    l5dtg::TenantExtract tenant_ex;
    l5dtg::GuardCfg guard_cfg;
    l5dtg::GuardStats guard;
    // stream sentinel: cfg is installed BEFORE fph2_start (loop reads
    // it unlocked, like guard_cfg); the table and the pending-RST
    // queue (Python-side actuation) are guarded by mu
    l5dstream::StreamCfg stream_cfg;
    l5dstream::StreamTable stream_tab;
    std::vector<uint32_t> pending_rst;

    // loop-thread-only
    std::unordered_map<int, H2Conn*> conns;
    std::vector<int> listeners;
    // loop-thread-only stream-key index (Python RSTs address by key)
    std::unordered_map<uint32_t, PStream*> by_skey;
    uint32_t next_skey = 1;
    std::unordered_map<std::string, std::vector<PStream*>> parked;
    // write coalescing: conns with pending frames, flushed once per
    // epoll round (true only while the loop thread runs — outside it,
    // queue_flush degrades to an immediate flush)
    std::vector<H2Conn*> dirty;
    std::vector<H2Conn*> dirty_scratch;  // drain_dirty's batch buffer
    bool defer_ok = false;
    // TLS (installed from Python BEFORE fph2_start; loop-thread reads)
    l5dtls::Ctx* tls_srv = nullptr;
    l5dtls::Ctx* tls_cli = nullptr;
    bool tls_cli_verify = false;
    std::unordered_set<int> tls_listeners;
    l5dtls::TlsStats tls_stats;  // written by the loop thread under mu
    std::unordered_map<std::string, l5dtls::SSL_SESSION*> tls_sessions;
    // conns/streams closed mid-handler; freed at a safe point in the
    // loop so pointers held across a frame-handler call stay valid
    std::vector<H2Conn*> graveyard;
    std::vector<PStream*> stream_graveyard;
    std::atomic<uint64_t> accepted{0};
    uint64_t last_sweep_us = 0;
    // loop-thread-only defense state
    l5dtg::SourceTable sources;
    uint32_t hs_inflight = 0;  // accept-leg TLS handshakes in flight
    // one clock read per wakeup: loop_main stamps this right after
    // epoll_wait returns; every loop-thread timestamp consumer reads
    // the stamp (loop_now) instead of issuing its own clock_gettime
    uint64_t now_cache_us = now_us();
    // feature timestamps are relative to engine creation:
    // float32 seconds-since-boot quantizes to >60ms after
    // ~12 days of uptime, breaking inter-arrival math
    uint64_t t0_us = now_us();
};

struct H2Conn {
    enum class Kind { CLIENT, UPSTREAM };
    Kind kind = Kind::CLIENT;
    int fd = -1;
    std::string in;
    std::string out;
    bool want_write = false;
    bool paused = false;
    bool connecting = false;
    bool closing = false;
    bool dead = false;
    h2::Session s;
    std::unordered_map<uint32_t, PStream*> streams;  // by this side's id
    uint64_t buffered = 0;   // bytes read from this conn, pending forward
    uint32_t max_seen_id = 0;  // client conns: highest peer stream id
    // connection-plane defenses (client conns): control-frame flood
    // window (SETTINGS/PING/RST rapid-reset caps), header-block stall
    // budget (hb_start: CONTINUATION sequence open since then), and a
    // preface deadline for fresh conns that never speak
    uint64_t flood_window_start_us = 0;
    uint32_t rst_count = 0, ping_count = 0, settings_count = 0;
    uint64_t hb_start_us = 0;
    uint64_t preface_deadline_us = 0;
    bool hs_pending = false;  // counted in Engine::hs_inflight

    // upstream-only
    std::string route_key;
    uint64_t route_id = 0;
    uint32_t ep_ip_be = 0;
    uint16_t ep_port = 0;
    uint32_t next_stream_id = 1;
    uint32_t active_streams = 0;
    bool draining = false;  // GOAWAY received: no new streams
    std::deque<PStream*> pend_dispatch;
    // sweep bookkeeping: when this (upstream) conn last had no streams;
    // 0 while it has work
    uint64_t idle_since_us = 0;

    // TLS adapter (null = cleartext); `out` always holds wire bytes,
    // app plaintext stages in tls->plain_out until flush encrypts it
    l5dtls::TlsIo* tls = nullptr;
    bool flush_queued = false;  // on the engine's dirty list

    ~H2Conn() { delete tls; }
};

std::string* wbuf(H2Conn* c) {
    return c->tls != nullptr ? &c->tls->plain_out : &c->out;
}

size_t outsz(const H2Conn* c) {
    return c->out.size()
        + (c->tls != nullptr ? c->tls->plain_out.size() : 0);
}

// The loop thread's clock: one clock_gettime per wakeup (the loop_main
// stamp), not one per timestamp consumer. Hot-path code reads the
// stamp; cold/control-plane code keeps calling now_us() directly.
uint64_t loop_now(Engine* e) { return e->now_cache_us; }

struct PStream {
    H2Conn* cc = nullptr;
    uint32_t cid = 0;
    H2Conn* uc = nullptr;
    uint32_t uid = 0;
    std::string route_key;
    uint64_t route_id = 0;
    uint32_t ep_ip = 0;   // endpoint this stream's inflight count is on
    uint16_t ep_pt = 0;
    uint64_t t_start_us = 0;
    uint64_t req_b = 0, rsp_b = 0;
    int status = 0;
    // tenant isolation: the stream's tenant hash, whether it holds a
    // per-tenant inflight slot, and the zero-progress-body budget the
    // sweep enforces (0 = request already ended / not yet dispatched)
    uint32_t tenant = 0;
    bool tenant_counted = false;
    uint64_t body_progress_us = 0;

    // request retention for GOAWAY replay (BufferedStream parity)
    std::vector<Hdr> req_hdrs;
    std::string req_retain;
    bool retain_valid = true;
    bool replayed = false;  // one replay attempt only

    bool req_end_seen = false;   // END_STREAM from client observed
    bool req_hdrs_sent = false;  // HEADERS written upstream
    bool req_end_sent = false;   // END_STREAM written upstream
    bool rsp_started = false;    // final response HEADERS forwarded
    bool rsp_end_sent = false;   // END_STREAM written to client

    // request direction pending (client -> upstream)
    std::string u_pend;
    bool u_pend_end = false;
    std::vector<Hdr> u_trailers;
    bool u_has_trailers = false;
    int64_t u_swin = 0;
    // response direction pending (upstream -> client)
    std::string c_pend;
    bool c_pend_end = false;
    std::vector<Hdr> c_trailers;
    bool c_has_trailers = false;
    int64_t c_swin = 0;

    uint64_t c_runacked = 0, u_runacked = 0;  // recv not yet granted back
    // receive-side enforcement: how much each peer may still send on
    // this stream (our advertised initial window + grants − DATA seen);
    // negative = the peer overran our window -> FLOW_CONTROL_ERROR
    int64_t c_recv_win = 0, u_recv_win = 0;
    // stream sentinel: per-frame feature accumulation, native
    // hysteresis state, the 24-bit stream key feature rows carry, and
    // the specialist head pinned at first dispatch (srhash) — the
    // stream keeps scoring on the head it opened with even if the
    // route's hash is repointed mid-life
    l5dstream::StreamAccum acc;
    l5dstream::StreamGov gov;
    uint32_t skey = 0;  // 0 = stream tracking off
    uint32_t srhash = 0;
    bool sr_pinned = false;
    bool is_grpc = false;
    uint64_t last_frame_us = 0;
    bool parked = false;
    uint64_t park_deadline_us = 0;
    // finished: unlinked from both conns, awaiting graveyard free. Every
    // code path that holds a PStream* across a call that can finish
    // streams (flush_out -> conn_close chains) re-checks this flag; the
    // memory stays valid until the loop's safe point.
    bool closed = false;
};

void ep_mod(Engine* e, H2Conn* c) {
    epoll_event ev{};
    ev.events = (c->paused ? 0 : EPOLLIN)
        | (c->want_write ? EPOLLOUT : 0) | EPOLLRDHUP;
    ev.data.fd = c->fd;
    epoll_ctl(e->epfd, EPOLL_CTL_MOD, c->fd, &ev);
}

void ep_add(Engine* e, H2Conn* c) {
    epoll_event ev{};
    ev.events = (c->paused ? 0 : EPOLLIN)
        | (c->want_write ? EPOLLOUT : 0) | EPOLLRDHUP;
    ev.data.fd = c->fd;
    epoll_ctl(e->epfd, EPOLL_CTL_ADD, c->fd, &ev);
    e->conns[c->fd] = c;
}

void conn_close(Engine* e, H2Conn* c);

void tls_account(Engine* e, H2Conn* c, bool failed) {
    std::lock_guard<std::mutex> g(e->mu);
    l5dtls::account_handshake(c->tls, &e->tls_stats,
                              c->tls->sess->is_server, failed);
}

// A TLS handshake finished (either way): clear its sweep deadline and
// release its slot in the accept-leg churn-backpressure counter.
void hs_complete(Engine* e, H2Conn* c) {
    c->tls->hs_deadline_us = 0;
    // accept-leg conns cache their SNI here, once per handshake —
    // tenant extraction used to call server_sni() (shim call + string
    // alloc) on EVERY request stream of the conn
    if (c->tls->sess->is_server && c->tls->sni.empty())
        c->tls->sni = l5dtls::server_sni(c->tls->sess);
    if (c->hs_pending) {
        c->hs_pending = false;
        if (e->hs_inflight > 0) e->hs_inflight--;
    }
}

bool flush_out(Engine* e, H2Conn* c) {
    if (c->dead) return false;
    if (c->tls != nullptr) {
        bool was_hs = !c->tls->sess->hs_done;
        if (!l5dtls::encrypt_pending(c->tls, &c->out)) {
            tls_account(e, c, /*failed=*/was_hs);
            if (!c->out.empty())  // best effort: alert out
                (void)::send(c->fd, c->out.data(), c->out.size(),
                             MSG_NOSIGNAL);
            conn_close(e, c);
            return false;
        }
        if (was_hs && c->tls->sess->hs_done) {
            hs_complete(e, c);
            tls_account(e, c, false);
        }
    }
    while (!c->out.empty()) {
        ssize_t n = ::send(c->fd, c->out.data(), c->out.size(),
                           MSG_NOSIGNAL);
        if (n > 0) {
            c->out.erase(0, (size_t)n);
        } else if (n < 0 && errno == EINTR) {
            continue;  // signal during send: the conn is healthy, retry
        } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
            break;
        } else {
            conn_close(e, c);
            return false;
        }
    }
    if (c->out.empty() && c->closing &&
        (c->tls == nullptr || c->tls->plain_out.empty())) {
        if (c->tls != nullptr && c->tls->sess->hs_done &&
            !c->tls->shutdown_sent) {
            c->tls->shutdown_sent = true;
            l5dtls::shutdown(c->tls->sess, &c->out);
            while (!c->out.empty()) {
                ssize_t n = ::send(c->fd, c->out.data(), c->out.size(),
                                   MSG_NOSIGNAL);
                if (n <= 0) break;
                c->out.erase(0, (size_t)n);
            }
        }
        conn_close(e, c);
        return false;
    }
    bool ww = !c->out.empty();
    if (ww != c->want_write) {
        c->want_write = ww;
        ep_mod(e, c);
    }
    return true;
}

// Mark a conn for the end-of-wakeup flush pass. Frame producers call
// this instead of flushing inline, so a burst of frames (a whole read's
// worth of requests, grants, PING acks) leaves in ONE send() — and for
// TLS conns, one SSL_write batch — per socket wakeup. Outside the loop
// thread's run window (startup/shutdown) it degrades to an immediate
// flush so teardown writes still reach the wire.
void queue_flush(Engine* e, H2Conn* c) {
    if (c->dead) return;
    if (!e->defer_ok) {
        flush_out(e, c);
        return;
    }
    if (!c->flush_queued) {
        c->flush_queued = true;
        e->dirty.push_back(c);
    }
}

void pump_upstream(Engine* e, PStream* st);
void pump_client(Engine* e, PStream* st);

// Flush every dirty conn; when a flush frees room below the pump gate,
// resume the conn's streams (they stalled on OUT_HIGH) — which may mark
// more conns dirty, hence the bounded rounds + plain-flush tail.
void drain_dirty(Engine* e) {
    for (int round = 0; round < 8 && !e->dirty.empty(); round++) {
        // swap through a persistent scratch: the batch buffer used to
        // be a local vector, one heap allocation per wakeup
        e->dirty_scratch.clear();
        std::swap(e->dirty, e->dirty_scratch);
        for (H2Conn* c : e->dirty_scratch) {
            c->flush_queued = false;
            if (c->dead) continue;
            size_t before = outsz(c);
            if (!flush_out(e, c)) continue;
            if (before > OUT_HIGH && outsz(c) < OUT_HIGH) {
                // l5d: ignore[hot-alloc] — runs only on an OUT_HIGH→below watermark crossing (backpressure release), not in the steady state
                std::vector<PStream*> sts;
                sts.reserve(c->streams.size());
                for (auto& kv : c->streams) sts.push_back(kv.second);
                for (PStream* st : sts) {
                    if (c->dead) break;
                    if (st->closed) continue;
                    if (c->kind == H2Conn::Kind::CLIENT)
                        pump_client(e, st);
                    else
                        pump_upstream(e, st);
                }
            }
        }
    }
    while (!e->dirty.empty()) {  // close cascades only: flush, no pump
        e->dirty_scratch.clear();
        std::swap(e->dirty, e->dirty_scratch);
        for (H2Conn* c : e->dirty_scratch) {
            c->flush_queued = false;
            if (!c->dead) flush_out(e, c);
        }
    }
}

void push_feature(Engine* e, uint64_t route_id, uint64_t lat_us, int status,
                  uint64_t req_b, uint64_t rsp_b, float score, int scored,
                  int specialist, uint64_t score_ns, uint32_t tenant,
                  int kind = l5dstream::ROW_REQUEST, uint32_t skey = 0,
                  uint32_t fseq = 0) {
    std::lock_guard<std::mutex> g(e->mu);
    if (scored)
        e->score_stats.record(score_ns, specialist != 0);
    else
        e->score_stats.unscored++;
    // per-tenant aggregates ride the same mu hold as the feature push
    // (request rows only — a stream's tenant slot is settled when the
    // stream finishes, not per sample)
    if (tenant && kind == l5dstream::ROW_REQUEST)
        e->tenants.observe(tenant, status, score, scored != 0, loop_now(e));
    if (e->features.size() >= e->features_cap) {
        e->features_dropped++;
        return;
    }
    FeatureRow r;
    r.route_id = (float)route_id;
    r.latency_ms = (float)lat_us / 1000.0f;
    r.status = (float)status;
    r.req_bytes = (float)req_b;
    r.rsp_bytes = (float)rsp_b;
    r.ts_s = (float)((double)(loop_now(e) - e->t0_us) / 1e6);
    r.score = score;
    r.scored = scored ? 1.0f : 0.0f;
    r.tenant = l5dtg::tenant_feature(tenant);
    r.kind = (float)kind;
    r.stream = (float)skey;
    r.frame_seq = (float)fseq;
    e->features.push_back(r);
}

// Encode + write a header block, splitting into HEADERS/CONTINUATION at
// the peer's max frame size.
void write_headers(H2Conn* c, uint32_t stream_id,
                   const std::vector<Hdr>& headers, bool end_stream) {
    std::string block;
    c->s.enc.encode(headers, &block);
    size_t maxf = c->s.peer_max_frame;
    size_t off = 0;
    bool first = true;
    std::string* out = wbuf(c);
    do {
        size_t n = block.size() - off;
        if (n > maxf) n = maxf;
        bool last = off + n == block.size();
        uint8_t type = first ? h2::HEADERS : h2::CONTINUATION;
        uint8_t flags = 0;
        if (first && end_stream) flags |= h2::FLAG_END_STREAM;
        if (last) flags |= h2::FLAG_END_HEADERS;
        h2::write_frame(out, type, flags, stream_id, block.data() + off,
                        n);
        off += n;
        first = false;
    } while (off < block.size());
}

// Synthesized response to the client (no upstream involved).
void synth_response(Engine* e, H2Conn* cc, uint32_t cid, int status,
                    const char* errmsg) {
    char st[8];
    snprintf(st, sizeof(st), "%d", status);
    std::vector<Hdr> hs = {{":status", st}};
    if (errmsg) hs.push_back({"l5d-err", errmsg});
    hs.push_back({"content-length", "0"});
    write_headers(cc, cid, hs, true);
    queue_flush(e, cc);
}

void unregister_parked(Engine* e, PStream* st) {
    auto it = e->parked.find(st->route_key);
    if (it == e->parked.end()) return;
    auto& v = it->second;
    for (size_t i = 0; i < v.size(); i++)
        if (v[i] == st) { v.erase(v.begin() + i); break; }
    if (v.empty()) e->parked.erase(it);
}

void dispatch_from_queue(Engine* e, H2Conn* uc);

// Unlink + retire a stream. record=true adds route stats + a feature
// row. Idempotent; the PStream is freed later at the loop's safe point.
void finish_stream(Engine* e, PStream* st, bool record) {
    if (st->closed) return;
    st->closed = true;
    e->stream_graveyard.push_back(st);
    if (st->skey != 0) {
        e->by_skey.erase(st->skey);
        std::lock_guard<std::mutex> g(e->mu);
        l5dstream::StreamStats* ss = e->stream_tab.peek(st->skey);
        if (ss != nullptr && ss->inflight > 0) ss->inflight--;
    }
    if (st->parked) {
        unregister_parked(e, st);
        st->parked = false;
    }
    if (st->cc != nullptr) {
        st->cc->buffered -= st->u_pend.size();
        st->cc->streams.erase(st->cid);
    }
    H2Conn* uc = st->uc;
    if (uc != nullptr) {
        uc->buffered -= st->c_pend.size();
        if (st->uid) {
            uc->streams.erase(st->uid);
            if (uc->active_streams > 0) uc->active_streams--;
        } else {
            // still queued for dispatch on this conn
            for (size_t i = 0; i < uc->pend_dispatch.size(); i++)
                if (uc->pend_dispatch[i] == st) {
                    uc->pend_dispatch.erase(uc->pend_dispatch.begin()
                                            + (long)i);
                    break;
                }
        }
    }
    uint64_t lat = loop_now(e) - st->t_start_us;
    // in-data-plane scoring: feature prep (hash col + drift EWMA) rides
    // the same mu hold as the route stats; the dense forward runs
    // OUTSIDE mu against the slab's own reader protocol
    float feats[l5dscore::FEATURE_DIM];
    bool have_feats = false;
    uint32_t rhash = 0;
    {
        std::lock_guard<std::mutex> g(e->mu);
        if (st->tenant_counted) {
            st->tenant_counted = false;
            l5dtg::TenantStats* ts = e->tenants.peek(st->tenant);
            if (ts != nullptr && ts->inflight > 0) ts->inflight--;
        }
        auto it = e->routes.find(st->route_key);
        if (it != e->routes.end() && it->second.id == st->route_id) {
            if (record) it->second.stats.record(st->status, lat);
            if (record) {
                l5dscore::RouteFeat& rf = it->second.feat;
                const float lat_ms = (float)lat / 1000.0f;
                const float drift =
                    l5dscore::feat_drift_update(&rf, lat_ms);
                if (rf.col >= 0 &&
                    l5dscore::slab_has_weights(e->slab)) {
                    l5dscore::featurize(lat_ms, st->status,
                                        (float)st->req_b,
                                        (float)st->rsp_b, rf.col,
                                        rf.sign, drift, feats);
                    have_feats = true;
                    rhash = rf.rhash;
                }
            }
            if (st->ep_ip)
                for (auto& ep : it->second.eps)
                    if (ep.ip_be == st->ep_ip && ep.port == st->ep_pt &&
                        ep.inflight > 0) {
                        ep.inflight--;
                        break;
                    }
        }
    }
    if (record) {
        float score = 0.0f;
        int scored = 0, specialist = 0;
        uint64_t score_ns = 0;
        if (have_feats) {
            const uint64_t t0 = l5dscore::now_ns();
            // per-route head select: the bank serves this route's
            // specialist when one is published, else the base model
            const int rc = l5dscore::slab_score_route(
                e->slab, rhash, rhash != 0, feats, &score);
            if (rc >= 0) {
                scored = 1;
                specialist = rc;
                score_ns = l5dscore::now_ns() - t0;
            }
        }
        push_feature(e, st->route_id, lat, st->status, st->req_b,
                     st->rsp_b, score, scored, specialist, score_ns,
                     st->tenant);
    }
    if (uc != nullptr && !uc->dead) dispatch_from_queue(e, uc);
}

// ---- stream sentinel (in-plane mid-stream scoring + actuation) ----

// Shed a sick stream: gRPC streams get proper UNAVAILABLE trailers
// (grpc-status 14 — the client sees a clean, retryable status) when
// the response channel is still usable; everything else gets
// RST_STREAM. The upstream leg is always CANCELed.
void shed_stream(Engine* e, PStream* st, const char* why) {
    if (st->closed) return;
    {
        std::lock_guard<std::mutex> g(e->mu);
        e->stream_tab.rst_sent++;
    }
    if (st->cc != nullptr && !st->cc->dead) {
        if (st->is_grpc && !st->rsp_end_sent) {
            std::vector<Hdr> tr;
            if (!st->rsp_started)  // trailers-only response
                tr.push_back({":status", "200"});
            tr.push_back({"grpc-status", "14"});  // UNAVAILABLE
            tr.push_back({"grpc-message", why});
            write_headers(st->cc, st->cid, tr, true);
            st->rsp_end_sent = true;
        } else {
            h2::write_rst(wbuf(st->cc), st->cid, h2::ENHANCE_YOUR_CALM);
        }
        queue_flush(e, st->cc);
    }
    if (st->uc != nullptr && st->uid && !st->uc->dead) {
        h2::write_rst(wbuf(st->uc), st->uid, h2::CANCEL);
        queue_flush(e, st->uc);
    }
    if (st->status == 0) st->status = 503;
    finish_stream(e, st, true);
}

// Score one mid-stream sample and run the native hysteresis governor.
// The dense forward runs OUTSIDE mu against the slab reader protocol,
// same as the request path in finish_stream.
void sample_stream(Engine* e, PStream* st, uint64_t now) {
    st->gov.last_sample_frames = st->acc.frames;
    st->gov.last_sample_us = now;
    float score = 0.0f;
    int scored = 0, specialist = 0;
    uint64_t score_ns = 0;
    if (l5dscore::slab_has_weights(e->slab)) {
        float feats[l5dscore::FEATURE_DIM];
        l5dscore::featurize_stream(
            st->acc.gap_ewma_ms, st->acc.bpf_ewma, (float)st->acc.bytes,
            st->acc.gap_dev_ms, st->acc.anomalies, -1, 0.0f, feats);
        const uint64_t t0 = l5dscore::now_ns();
        const int rc = l5dscore::slab_score_route(
            e->slab, st->srhash, st->srhash != 0, feats, &score);
        if (rc >= 0) {
            scored = 1;
            specialist = rc;
            score_ns = l5dscore::now_ns() - t0;
        }
    }
    const int trans = scored
        ? l5dstream::gov_observe(e->stream_cfg, &st->gov, score, now)
        : 0;
    push_feature(e, st->route_id,
                 (uint64_t)(st->acc.gap_ewma_ms * 1000.0f),
                 st->gov.sick ? 503 : 0, st->req_b, st->rsp_b, score,
                 scored, specialist, score_ns, st->tenant,
                 l5dstream::ROW_STREAM, st->skey, st->acc.frames);
    {
        std::lock_guard<std::mutex> g(e->mu);
        e->stream_tab.observe(st->skey, l5dstream::ROW_STREAM, score,
                              scored != 0, st->acc, st->gov.sick, now);
        if (trans > 0) e->stream_tab.sick_transitions++;
    }
    if (trans > 0 && e->stream_cfg.action != 0)
        shed_stream(e, st, "stream shed by sentinel");
}

// One frame observed on a tracked stream: accumulate the feature
// deltas and sample/score on the configured cadence. May finish the
// stream (actuation) — callers must re-check st->closed.
void note_frame(Engine* e, PStream* st, int kind, size_t nbytes) {
    if (st->skey == 0 || st->closed) return;
    const uint64_t now = loop_now(e);
    const float gap_ms = st->last_frame_us != 0
        ? (float)(now - st->last_frame_us) / 1000.0f : 0.0f;
    st->last_frame_us = now;
    l5dstream::accum_frame(&st->acc, kind, gap_ms, (float)nbytes);
    if (l5dstream::sample_due(e->stream_cfg, st->acc, st->gov, now))
        sample_stream(e, st, now);
}

// Python-side actuation: RST requests queue under mu and drain here on
// the loop thread (fph2_rst_stream wakes the loop via the eventfd).
void drain_pending_rst(Engine* e) {
    // l5d: ignore[hot-alloc] — default-constructed vector allocates nothing; swap() steals the queued buffer, and RST actuation is control-plane cadence, not per-request
    std::vector<uint32_t> keys;
    {
        std::lock_guard<std::mutex> g(e->mu);
        if (e->pending_rst.empty()) return;
        keys.swap(e->pending_rst);
    }
    for (uint32_t k : keys) {
        auto it = e->by_skey.find(k);
        if (it != e->by_skey.end())
            shed_stream(e, it->second, "stream shed by sentinel");
    }
}

// ---- flow-control grants (we only re-open our receive windows when the
// slower side has drained what we buffered: bounded memory) ----

void conn_grant(Engine* e, H2Conn* c) {
    if (c->s.recv_unacked >= CONN_GRANT && c->buffered < CONN_BUF_HIGH) {
        h2::write_window_update(wbuf(c), 0, (uint32_t)c->s.recv_unacked);
        c->s.recv_win += (int64_t)c->s.recv_unacked;
        c->s.recv_unacked = 0;
        queue_flush(e, c);
    }
}

// Grant stream-level window back to the producer conn for stream st.
// from_client: data arrived on cc (buffered in u_pend), else on uc.
void stream_grant(Engine* e, PStream* st, bool from_client) {
    if (st->closed) return;
    if (from_client) {
        if (st->cc != nullptr && st->c_runacked >= STREAM_GRANT &&
            st->u_pend.size() < PEND_HIGH && !st->req_end_seen) {
            h2::write_window_update(wbuf(st->cc), st->cid,
                                    (uint32_t)st->c_runacked);
            st->c_recv_win += (int64_t)st->c_runacked;
            st->c_runacked = 0;
            queue_flush(e, st->cc);
        }
    } else {
        if (st->uc != nullptr && st->uid && st->u_runacked >= STREAM_GRANT
            && st->c_pend.size() < PEND_HIGH) {
            h2::write_window_update(wbuf(st->uc), st->uid,
                                    (uint32_t)st->u_runacked);
            st->u_recv_win += (int64_t)st->u_runacked;
            st->u_runacked = 0;
            queue_flush(e, st->uc);
        }
    }
}

// ---- forwarding pumps ----

// Send buffered request bytes upstream as windows allow.
void pump_upstream(Engine* e, PStream* st) {
    if (st->closed) return;
    H2Conn* uc = st->uc;
    if (uc == nullptr || !st->req_hdrs_sent || st->req_end_sent) return;
    if (outsz(uc) > OUT_HIGH) return;  // re-pumped on flush drain
    while (!st->u_pend.empty() && st->u_swin > 0 && uc->s.send_win > 0) {
        size_t n = st->u_pend.size();
        if ((int64_t)n > st->u_swin) n = (size_t)st->u_swin;
        if ((int64_t)n > uc->s.send_win) n = (size_t)uc->s.send_win;
        if (n > uc->s.peer_max_frame) n = uc->s.peer_max_frame;
        bool end = st->u_pend_end && !st->u_has_trailers &&
                   n == st->u_pend.size();
        h2::write_frame(wbuf(uc), h2::DATA,
                        end ? h2::FLAG_END_STREAM : 0, st->uid,
                        st->u_pend.data(), n);
        st->u_pend.erase(0, n);
        st->u_swin -= (int64_t)n;
        uc->s.send_win -= (int64_t)n;
        if (st->cc != nullptr) st->cc->buffered -= n;
        if (end) st->req_end_sent = true;
        if (outsz(uc) > OUT_HIGH) break;
    }
    if (st->u_pend.empty() && !st->req_end_sent) {
        if (st->u_has_trailers) {
            write_headers(uc, st->uid, st->u_trailers, true);
            st->req_end_sent = true;
        } else if (st->u_pend_end) {
            h2::write_frame(wbuf(uc), h2::DATA, h2::FLAG_END_STREAM,
                            st->uid, nullptr, 0);
            st->req_end_sent = true;
        }
    }
    queue_flush(e, uc);
    // a degraded (immediate) flush can conn_close(uc) -> finish/replay
    if (st->closed) return;
    if (st->cc != nullptr) {
        stream_grant(e, st, true);
        conn_grant(e, st->cc);
    }
}

// Send buffered response bytes to the client; finishes the stream when
// END_STREAM has been forwarded.
void pump_client(Engine* e, PStream* st) {
    if (st->closed) return;
    H2Conn* cc = st->cc;
    if (cc == nullptr || st->rsp_end_sent) return;
    if (outsz(cc) > OUT_HIGH) return;
    while (!st->c_pend.empty() && st->c_swin > 0 && cc->s.send_win > 0) {
        size_t n = st->c_pend.size();
        if ((int64_t)n > st->c_swin) n = (size_t)st->c_swin;
        if ((int64_t)n > cc->s.send_win) n = (size_t)cc->s.send_win;
        if (n > cc->s.peer_max_frame) n = cc->s.peer_max_frame;
        bool end = st->c_pend_end && !st->c_has_trailers &&
                   n == st->c_pend.size();
        h2::write_frame(wbuf(cc), h2::DATA,
                        end ? h2::FLAG_END_STREAM : 0, st->cid,
                        st->c_pend.data(), n);
        st->c_pend.erase(0, n);
        st->c_swin -= (int64_t)n;
        cc->s.send_win -= (int64_t)n;
        if (st->uc != nullptr) st->uc->buffered -= n;
        if (end) st->rsp_end_sent = true;
        if (outsz(cc) > OUT_HIGH) break;
    }
    if (st->c_pend.empty() && !st->rsp_end_sent) {
        if (st->c_has_trailers) {
            write_headers(cc, st->cid, st->c_trailers, true);
            st->rsp_end_sent = true;
        } else if (st->c_pend_end) {
            h2::write_frame(wbuf(cc), h2::DATA, h2::FLAG_END_STREAM,
                            st->cid, nullptr, 0);
            st->rsp_end_sent = true;
        }
    }
    queue_flush(e, cc);
    // a degraded (immediate) flush can conn_close(cc) -> finish st
    if (st->closed) return;
    if (st->uc != nullptr) {
        stream_grant(e, st, false);
        conn_grant(e, st->uc);
    }
    if (st->rsp_end_sent) finish_stream(e, st, true);
}

// ---- upstream dispatch ----

void stash_upstream_session(Engine* e, H2Conn* up) {
    if (up->tls == nullptr || up->kind != H2Conn::Kind::UPSTREAM) return;
    l5dtls::stash_session(
        &e->tls_sessions,
        l5dtls::session_key(up->ep_ip_be, up->ep_port, up->tls->sni),
        up->tls->sess);
}

H2Conn* mk_upstream(Engine* e, const std::string& route_key,
                    uint64_t route_id, uint32_t ip_be, uint16_t port) {
    int fd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) return nullptr;
    set_nodelay(fd);
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_addr.s_addr = ip_be;
    sa.sin_port = htons(port);
    int rc = ::connect(fd, (sockaddr*)&sa, sizeof(sa));
    if (rc < 0 && errno != EINPROGRESS) {
        ::close(fd);
        return nullptr;
    }
    H2Conn* c = new H2Conn();
    c->kind = H2Conn::Kind::UPSTREAM;
    c->fd = fd;
    c->connecting = (rc < 0);
    c->want_write = c->connecting;
    c->route_key = route_key;
    c->route_id = route_id;
    c->ep_ip_be = ip_be;
    c->ep_port = port;
    if (e->tls_cli != nullptr) {
        // originate TLS (SNI/verify name = the route authority), with
        // the endpoint's cached session offered for resumption
        l5dtls::SSL_SESSION* resume = nullptr;
        auto it = e->tls_sessions.find(
            l5dtls::session_key(ip_be, port, route_key));
        if (it != e->tls_sessions.end()) resume = it->second;
        l5dtls::Sess* s = l5dtls::new_session(
            e->tls_cli, route_key.c_str(), e->tls_cli_verify, resume);
        if (s != nullptr) {
            c->tls = new l5dtls::TlsIo();
            c->tls->sess = s;
            c->tls->sni = route_key;
            c->tls->hs_deadline_us = loop_now(e) + TLS_HS_TIMEOUT_US;
        }
    }
    // client preface + our SETTINGS + a big connection window
    wbuf(c)->append(h2::PREFACE, h2::PREFACE_LEN);
    h2::write_settings(wbuf(c),
                       {{h2::S_HEADER_TABLE_SIZE, 4096},
                        {h2::S_INITIAL_WINDOW_SIZE,
                         (uint32_t)OUR_STREAM_WIN},
                        {h2::S_MAX_FRAME_SIZE, h2::DEFAULT_MAX_FRAME}},
                       false);
    h2::write_window_update(wbuf(c), 0,
                            (uint32_t)(OUR_CONN_WIN - h2::DEFAULT_WINDOW));
    c->s.recv_win = OUR_CONN_WIN;
    ep_add(e, c);
    if (!c->connecting) queue_flush(e, c);
    return c;
}

// Open the upstream side of st on conn uc: allocate a stream id, send the
// (re-encoded) request headers, then pump any buffered body.
void send_request_headers(Engine* e, PStream* st, H2Conn* uc) {
    st->uc = uc;
    st->uid = uc->next_stream_id;
    uc->next_stream_id += 2;
    uc->streams[st->uid] = st;
    uc->active_streams++;
    st->u_swin = uc->s.peer_init_win;
    st->u_recv_win = OUR_STREAM_WIN;  // what we advertised upstream
    st->req_hdrs_sent = true;
    bool end = st->req_end_seen && st->u_pend.empty() &&
               !st->u_has_trailers;
    write_headers(uc, st->uid, st->req_hdrs, end);
    if (end) st->req_end_sent = true;
    // queue the flush HERE, not just in pump_upstream: for an empty-body
    // request pump_upstream early-returns on req_end_sent and the
    // HEADERS would otherwise sit in wbuf until some other frame flushes
    // this conn
    queue_flush(e, uc);
    if (st->closed) return;  // a degraded flush can close uc underneath
    pump_upstream(e, st);
}

void dispatch_from_queue(Engine* e, H2Conn* uc) {
    while (!uc->pend_dispatch.empty() && !uc->draining &&
           uc->active_streams < uc->s.peer_max_streams) {
        PStream* st = uc->pend_dispatch.front();
        uc->pend_dispatch.pop_front();
        send_request_headers(e, st, uc);
    }
}

int pick_endpoint(Route& r) {
    size_t n = r.eps.size();
    if (n == 0) return -1;
    if (n == 1) return 0;
    size_t a = r.next++ % n;
    size_t b = r.next % n;
    return (int)(r.eps[a].inflight <= r.eps[b].inflight ? a : b);
}

// Route + attach st to an upstream conn. Returns false when no route /
// endpoint exists (caller decides to park or fail).
bool dispatch_stream(Engine* e, PStream* st) {
    if (e->shutting_down) return false;
    H2Conn* uc = nullptr;
    uint64_t route_id = 0;
    uint32_t ip_be = 0;
    uint16_t port = 0;
    bool found = false;
    {
        std::lock_guard<std::mutex> g(e->mu);
        auto it = e->routes.find(st->route_key);
        if (it != e->routes.end()) {
            Route& r = it->second;
            int idx = pick_endpoint(r);
            if (idx >= 0) {
                found = true;
                Endpoint& ep = r.eps[(size_t)idx];
                route_id = r.id;
                ip_be = ep.ip_be;
                port = ep.port;
                ep.inflight++;
                // specialist-head pinning: the stream scores on the
                // head its route served at open, for its whole life
                if (st->skey != 0 && !st->sr_pinned) {
                    st->srhash = r.feat.rhash;
                    st->sr_pinned = true;
                }
                if (ep.conn != nullptr && !ep.conn->draining &&
                    !ep.conn->closing && !ep.conn->dead)
                    uc = ep.conn;
            }
        }
    }
    if (!found) return false;
    st->route_id = route_id;
    st->ep_ip = ip_be;
    st->ep_pt = port;
    if (uc == nullptr) {
        uc = mk_upstream(e, st->route_key, route_id, ip_be, port);
        if (uc == nullptr) {
            std::lock_guard<std::mutex> g(e->mu);
            auto it = e->routes.find(st->route_key);
            if (it != e->routes.end()) {
                it->second.stats.conn_fail++;
                for (auto& ep : it->second.eps)
                    if (ep.ip_be == ip_be && ep.port == port &&
                        ep.inflight > 0)
                        ep.inflight--;
            }
            st->status = 502;
            st->ep_ip = 0;  // inflight already decremented above
            if (st->cc != nullptr)
                synth_response(e, st->cc, st->cid, 502, "connect");
            finish_stream(e, st, true);
            return true;  // handled (as a failure)
        }
        std::lock_guard<std::mutex> g(e->mu);
        auto it = e->routes.find(st->route_key);
        if (it != e->routes.end() && it->second.id == route_id)
            for (auto& ep : it->second.eps)
                if (ep.ip_be == ip_be && ep.port == port) {
                    ep.conn = uc;
                    break;
                }
    }
    if (uc->active_streams >= uc->s.peer_max_streams) {
        st->uc = uc;  // queued on this conn (uid stays 0)
        uc->pend_dispatch.push_back(st);
        return true;
    }
    send_request_headers(e, st, uc);
    return true;
}

void unpark_route(Engine* e, const std::string& host) {
    auto it = e->parked.find(host);
    if (it == e->parked.end()) return;
    std::vector<PStream*> waiters;
    waiters.swap(it->second);
    e->parked.erase(it);
    for (PStream* st : waiters) {
        if (st->closed) continue;
        st->parked = false;
        if (!dispatch_stream(e, st)) {
            st->status = 400;
            if (st->cc != nullptr)
                synth_response(e, st->cc, st->cid, 400, "no route");
            finish_stream(e, st, false);
        }
    }
}

// Detach an upstream conn from its endpoint slot (so new streams open a
// fresh conn). Safe to call repeatedly.
void clear_endpoint_slot(Engine* e, H2Conn* uc) {
    std::lock_guard<std::mutex> g(e->mu);
    auto it = e->routes.find(uc->route_key);
    if (it == e->routes.end()) return;
    for (auto& ep : it->second.eps)
        if (ep.conn == uc) ep.conn = nullptr;
}

// Undo the endpoint inflight increment for a stream being re-routed.
void release_inflight(Engine* e, PStream* st) {
    if (!st->ep_ip) return;
    std::lock_guard<std::mutex> g(e->mu);
    auto it = e->routes.find(st->route_key);
    if (it != e->routes.end() && it->second.id == st->route_id)
        for (auto& ep : it->second.eps)
            if (ep.ip_be == st->ep_ip && ep.port == st->ep_pt &&
                ep.inflight > 0) {
                ep.inflight--;
                break;
            }
    st->ep_ip = 0;
    st->ep_pt = 0;
}

// Reset a stream back to undispatched and retry it once (GOAWAY-refused
// or upstream death with the request still fully retained).
bool replay_stream(Engine* e, PStream* st) {
    if (e->shutting_down || st->closed || !st->retain_valid ||
        st->rsp_started || st->replayed || st->cc == nullptr)
        return false;
    st->replayed = true;
    release_inflight(e, st);
    st->uc = nullptr;
    st->uid = 0;
    st->req_hdrs_sent = false;
    st->req_end_sent = false;
    if (st->cc != nullptr) st->cc->buffered -= st->u_pend.size();
    st->u_pend = st->req_retain;
    if (st->cc != nullptr) st->cc->buffered += st->u_pend.size();
    st->u_pend_end = st->req_end_seen && !st->u_has_trailers;
    return dispatch_stream(e, st);
}

void conn_close(Engine* e, H2Conn* c) {
    if (c->dead) return;
    c->dead = true;
    if (c->hs_pending) {
        c->hs_pending = false;
        if (e->hs_inflight > 0) e->hs_inflight--;
    }
    e->graveyard.push_back(c);
    if (c->fd >= 0) {
        stash_upstream_session(e, c);
        epoll_ctl(e->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
        e->conns.erase(c->fd);
        ::close(c->fd);
        c->fd = -1;
    }
    // collect streams first: finish_stream mutates c->streams
    std::vector<PStream*> sts;
    sts.reserve(c->streams.size());
    for (auto& kv : c->streams) sts.push_back(kv.second);
    if (c->kind == H2Conn::Kind::CLIENT) {
        for (PStream* st : sts) {
            st->cc = nullptr;  // conn is gone
            if (st->uc != nullptr && st->uid)
                h2::write_rst(wbuf(st->uc), st->uid, h2::CANCEL);
            H2Conn* uc = st->uc;
            finish_stream(e, st, false);
            if (uc != nullptr) queue_flush(e, uc);
        }
    } else {
        clear_endpoint_slot(e, c);
        std::vector<PStream*> queued(c->pend_dispatch.begin(),
                                     c->pend_dispatch.end());
        c->pend_dispatch.clear();
        for (PStream* st : queued) {
            st->uc = nullptr;
            release_inflight(e, st);
            if (!dispatch_stream(e, st)) {
                st->status = 502;
                if (st->cc != nullptr)
                    synth_response(e, st->cc, st->cid, 502, "upstream");
                finish_stream(e, st, true);
            }
        }
        for (PStream* st : sts) {
            st->uc = nullptr;  // conn is gone; don't unlink via it
            if (replay_stream(e, st)) continue;
            st->status = 502;
            if (st->cc != nullptr) {
                if (st->rsp_started) {
                    h2::write_rst(wbuf(st->cc), st->cid,
                                  h2::INTERNAL_ERROR);
                    queue_flush(e, st->cc);
                } else {
                    synth_response(e, st->cc, st->cid, 502, "upstream");
                }
            }
            finish_stream(e, st, true);
        }
    }
    c->streams.clear();
}

void conn_error(Engine* e, H2Conn* c, uint32_t code) {
    if (c->dead) return;
    h2::write_goaway(wbuf(c), c->max_seen_id, code);
    flush_out(e, c);  // immediate: the conn closes right below
    conn_close(e, c);
}

// Control-frame flood cap (per client conn per guard window). Returns
// true while within budget; over budget the conn is killed with
// ENHANCE_YOUR_CALM (GOAWAY) — the CVE-2023-44487 rapid-reset defense
// when the counter is the RST one.
bool flood_ok(Engine* e, H2Conn* c, uint32_t* counter, uint32_t cap,
              bool rapid_reset) {
    if (cap == 0) return true;
    uint64_t now = loop_now(e);
    if (now - c->flood_window_start_us > e->guard_cfg.flood_window_us) {
        c->flood_window_start_us = now;
        c->rst_count = c->ping_count = c->settings_count = 0;
    }
    (*counter)++;
    if (*counter <= cap) return true;
    (rapid_reset ? e->guard.rapid_reset_closed : e->guard.flood_closed)
        .fetch_add(1, std::memory_order_relaxed);
    conn_error(e, c, h2::ENHANCE_YOUR_CALM);
    return false;
}

// ---- frame handlers ----

const std::string* find_hdr(const std::vector<Hdr>& hs, const char* name) {
    for (auto& h : hs)
        if (h.first == name) return &h.second;
    return nullptr;
}

void apply_settings(Engine* e, H2Conn* c, const uint8_t* p, size_t len) {
    int64_t old_init = c->s.peer_init_win;
    for (size_t off = 0; off + 6 <= len; off += 6) {
        uint16_t id = (uint16_t)((p[off] << 8) | p[off + 1]);
        uint32_t v = h2::get_u32(p + off + 2);
        switch (id) {
        case h2::S_HEADER_TABLE_SIZE:
            c->s.enc.set_max_table_size(v);
            break;
        case h2::S_INITIAL_WINDOW_SIZE:
            if (v > 0x7FFFFFFFu) {
                // RFC 7540 §6.5.2: values above 2^31-1 MUST be treated
                // as a connection error of type FLOW_CONTROL_ERROR
                conn_error(e, c, h2::FLOW_CONTROL_ERROR);
                return;
            }
            c->s.peer_init_win = (int64_t)v;
            break;
        case h2::S_MAX_FRAME_SIZE:
            if (v >= 16384 && v <= (1u << 24) - 1) c->s.peer_max_frame = v;
            break;
        case h2::S_MAX_CONCURRENT_STREAMS:
            c->s.peer_max_streams = v;
            break;
        default:
            break;
        }
    }
    // §6.9.2: a changed INITIAL_WINDOW_SIZE adjusts every open stream's
    // remaining send window by the delta
    int64_t delta = c->s.peer_init_win - old_init;
    if (delta != 0) {
        for (auto& kv : c->streams) {
            if (c->kind == H2Conn::Kind::CLIENT)
                kv.second->c_swin += delta;
            else
                kv.second->u_swin += delta;
        }
    }
    h2::write_settings(wbuf(c), {}, true);  // ACK
    queue_flush(e, c);
    if (c->dead) return;
    if (delta > 0) {
        std::vector<PStream*> sts;
        for (auto& kv : c->streams) sts.push_back(kv.second);
        for (PStream* st : sts) {
            if (c->dead) return;
            if (st->closed) continue;
            if (c->kind == H2Conn::Kind::CLIENT) pump_client(e, st);
            else pump_upstream(e, st);
        }
    }
    if (c->kind == H2Conn::Kind::UPSTREAM) dispatch_from_queue(e, c);
}

// A complete (HEADERS..CONTINUATION) block arrived on a CLIENT conn.
void client_headers_complete(Engine* e, H2Conn* c) {
    uint32_t sid = c->s.hb_stream;
    uint8_t flags = c->s.hb_flags;
    std::vector<Hdr> hs;
    if (!c->s.dec.decode((const uint8_t*)c->s.hb_buf.data(),
                         c->s.hb_buf.size(), &hs)) {
        conn_error(e, c, h2::COMPRESSION_ERROR);
        return;
    }
    auto it = c->streams.find(sid);
    if (it != c->streams.end()) {
        // trailers from the client
        PStream* st = it->second;
        st->req_end_seen = true;
        st->u_has_trailers = true;
        st->u_trailers = std::move(hs);
        st->retain_valid = false;  // trailers aren't retained for replay
        pump_upstream(e, st);
        return;
    }
    if ((sid & 1) == 0 || sid == 0) {
        conn_error(e, c, h2::PROTOCOL_ERROR);
        return;
    }
    if (sid <= c->max_seen_id) {
        // §5.1.1: a client stream id never goes backwards — this id was
        // either closed here or implicitly closed idle, so reuse is
        // illegal. RST it (the block was decoded above, HPACK state is
        // intact) rather than killing every other stream on the conn.
        h2::write_rst(wbuf(c), sid, h2::STREAM_CLOSED);
        queue_flush(e, c);
        return;
    }
    c->max_seen_id = sid;
    // stream-concurrency cap: we advertised MAX_CONCURRENT_STREAMS in
    // our SETTINGS; a peer opening beyond the guard cap is refused
    // (REFUSED_STREAM: retry-safe, nothing was processed)
    if (e->guard_cfg.max_streams_per_conn != 0 &&
        c->streams.size() >= e->guard_cfg.max_streams_per_conn) {
        h2::write_rst(wbuf(c), sid, h2::REFUSED_STREAM);
        queue_flush(e, c);
        return;
    }
    const std::string* auth = find_hdr(hs, ":authority");
    if (auth == nullptr) auth = find_hdr(hs, "host");
    std::string key = auth != nullptr ? *auth : "";
    size_t colon = key.find(':');
    if (colon != std::string::npos) key.resize(colon);
    lower(key);
    if (key.empty()) {
        synth_response(e, c, sid, 400, "no authority");
        return;
    }
    if (!l5dtls::valid_authority(key)) {
        // reject before the authority reaches routing, parked maps, or
        // the stats JSON — it is untrusted wire input
        synth_response(e, c, sid, 400, "bad authority");
        return;
    }
    // tenant identity + in-data-plane quota enforcement (h2 names are
    // lowercase on the wire; sheds are RST_STREAM REFUSED_STREAM —
    // retry-safe, the stream was never admitted)
    uint32_t tenant = 0;
    switch (e->tenant_ex.kind) {
    case 1: {
        const std::string* tv = find_hdr(hs, e->tenant_ex.header.c_str());
        if (tv != nullptr && !tv->empty())
            tenant = l5dtg::tenant_hash(tv->data(), tv->size());
        break;
    }
    case 2: {
        const std::string* pv = find_hdr(hs, ":path");
        if (pv != nullptr)
            tenant = l5dtg::hash_path_segment(*pv, e->tenant_ex.segment);
        break;
    }
    case 3:
        if (c->tls != nullptr) {
            // SNI cached at handshake completion (hs_complete)
            const std::string& sni = c->tls->sni;
            if (!sni.empty())
                tenant = l5dtg::tenant_hash(sni.data(), sni.size());
        }
        break;
    default:
        break;
    }
    bool tenant_counted = false;
    if (tenant) {
        bool over = false;
        {
            std::lock_guard<std::mutex> g(e->mu);
            l5dtg::TenantStats* ts = e->tenants.get(tenant, loop_now(e));
            int q = e->quotas.limit_of(tenant);
            if (q >= 0 && ts->inflight >= q) {
                ts->shed++;
                over = true;
            } else {
                ts->inflight++;
                tenant_counted = true;
            }
        }
        if (over) {
            e->guard.tenant_shed.fetch_add(1, std::memory_order_relaxed);
            h2::write_rst(wbuf(c), sid, h2::REFUSED_STREAM);
            queue_flush(e, c);
            return;
        }
    }
    PStream* st = new PStream();
    st->cc = c;
    st->cid = sid;
    st->route_key = key;
    st->tenant = tenant;
    st->tenant_counted = tenant_counted;
    st->t_start_us = loop_now(e);
    // zero-progress-body budget: armed only while the request body is
    // still open (cleared when END_STREAM is seen)
    if (!(flags & h2::FLAG_END_STREAM) &&
        e->guard_cfg.body_stall_budget_us != 0)
        st->body_progress_us = st->t_start_us;
    st->c_swin = c->s.peer_init_win;
    st->c_recv_win = OUR_STREAM_WIN;  // what our SETTINGS advertised
    st->req_end_seen = (flags & h2::FLAG_END_STREAM) != 0;
    st->u_pend_end = st->req_end_seen;
    hs.push_back({"via", "1.1 linkerd-tpu"});
    st->req_hdrs = std::move(hs);
    for (auto& h : st->req_hdrs) st->req_b += h.first.size()
                                     + h.second.size();
    // stream sentinel: enroll the stream under a fresh 24-bit key; the
    // specialist head pins at first dispatch (sr_pinned)
    if (e->stream_cfg.enabled) {
        const std::string* ct = find_hdr(st->req_hdrs, "content-type");
        st->is_grpc = ct != nullptr &&
            ct->compare(0, 16, "application/grpc") == 0;
        uint32_t k = l5dstream::fold_key(e->next_skey++);
        for (int tries = 0;
             e->by_skey.count(k) != 0 && tries < 4; tries++)
            k = l5dstream::fold_key(e->next_skey++);
        st->skey = k;
        st->last_frame_us = st->t_start_us;
        e->by_skey[k] = st;
        std::lock_guard<std::mutex> g(e->mu);
        l5dstream::StreamStats* ss =
            e->stream_tab.get(k, st->t_start_us);
        ss->inflight = 1;
        ss->kind = l5dstream::ROW_STREAM;
    }
    c->streams[sid] = st;
    if (dispatch_stream(e, st)) return;
    // no route yet: surface the miss and park (same dance as the h1
    // engine's WAIT_ROUTE, fastpath.cpp)
    st->parked = true;
    st->park_deadline_us = loop_now(e) + ROUTE_WAIT_TIMEOUT_US;
    e->parked[key].push_back(st);
    {
        std::lock_guard<std::mutex> g(e->mu);
        e->misses.push_back(key);
    }
}

// A complete header block arrived on an UPSTREAM conn (response headers,
// informational headers, or trailers).
void upstream_headers_complete(Engine* e, H2Conn* c) {
    uint32_t sid = c->s.hb_stream;
    uint8_t flags = c->s.hb_flags;
    std::vector<Hdr> hs;
    if (!c->s.dec.decode((const uint8_t*)c->s.hb_buf.data(),
                         c->s.hb_buf.size(), &hs)) {
        conn_error(e, c, h2::COMPRESSION_ERROR);
        return;
    }
    auto it = c->streams.find(sid);
    if (it == c->streams.end()) return;
    PStream* st = it->second;
    bool end = (flags & h2::FLAG_END_STREAM) != 0;
    if (!st->rsp_started) {
        const std::string* status = find_hdr(hs, ":status");
        int code = status != nullptr ? atoi(status->c_str()) : 0;
        if (code >= 100 && code < 200) {
            // informational: forward and keep waiting for the real one
            if (st->cc != nullptr) {
                write_headers(st->cc, st->cid, hs, false);
                queue_flush(e, st->cc);
            }
            return;
        }
        st->rsp_started = true;
        st->status = code;
        st->retain_valid = false;  // response begun: no more replay
        for (auto& h : hs) st->rsp_b += h.first.size() + h.second.size();
        if (st->cc != nullptr) {
            write_headers(st->cc, st->cid, hs, end);
            if (end) st->rsp_end_sent = true;
            queue_flush(e, st->cc);
        } else {
            st->rsp_end_sent = end;
        }
        if (end) finish_stream(e, st, true);
        return;
    }
    // trailers (gRPC: grpc-status rides here)
    for (auto& h : hs) st->rsp_b += h.first.size() + h.second.size();
    st->c_has_trailers = true;
    st->c_trailers = std::move(hs);
    st->c_pend_end = true;  // trailers always end the stream
    pump_client(e, st);
}

void handle_client_frame(Engine* e, H2Conn* c, uint8_t type, uint8_t flags,
                         uint32_t sid, const uint8_t* p, size_t len) {
    if (c->s.in_headers && type != h2::CONTINUATION) {
        conn_error(e, c, h2::PROTOCOL_ERROR);
        return;
    }
    switch (type) {
    case h2::HEADERS: {
        size_t off, n;
        if (uint32_t err = h2::strip_payload(flags, true, p, len, &off,
                                             &n)) {
            conn_error(e, c, err);
            return;
        }
        c->s.hb_buf.assign((const char*)(p + off), n);
        c->s.hb_stream = sid;
        c->s.hb_flags = flags;
        if (flags & h2::FLAG_END_HEADERS) {
            client_headers_complete(e, c);
        } else {
            c->s.in_headers = true;
            // slowloris: an open CONTINUATION sequence has a budget
            c->hb_start_us = loop_now(e);
        }
        break;
    }
    case h2::CONTINUATION: {
        if (!c->s.in_headers || sid != c->s.hb_stream) {
            conn_error(e, c, h2::PROTOCOL_ERROR);
            return;
        }
        c->s.hb_buf.append((const char*)p, len);
        if (c->s.hb_buf.size() > 256 * 1024) {
            conn_error(e, c, h2::ENHANCE_YOUR_CALM);
            return;
        }
        if (flags & h2::FLAG_END_HEADERS) {
            c->s.in_headers = false;
            c->hb_start_us = 0;
            client_headers_complete(e, c);
        }
        break;
    }
    case h2::DATA: {
        // receive-side enforcement first: the whole payload (padding
        // included) consumes our advertised windows, and overrunning
        // them is a FLOW_CONTROL_ERROR (RFC 7540 §6.9)
        c->s.recv_win -= (int64_t)len;
        if (c->s.recv_win < 0) {
            conn_error(e, c, h2::FLOW_CONTROL_ERROR);
            return;
        }
        c->s.recv_unacked += len;  // padding counts toward flow control
        auto it = c->streams.find(sid);
        if (it == c->streams.end()) {
            conn_grant(e, c);  // closed stream: keep the conn window open
            return;
        }
        PStream* st = it->second;
        st->c_recv_win -= (int64_t)len;
        if (st->c_recv_win < 0) {
            // stream-level overrun: RST this stream, spare the conn
            note_frame(e, st, l5dstream::FRAME_ANOMALY, 0);
            if (st->closed) return;  // sentinel already shed it
            h2::write_rst(wbuf(c), sid, h2::FLOW_CONTROL_ERROR);
            queue_flush(e, c);
            if (st->uc != nullptr && st->uid) {
                h2::write_rst(wbuf(st->uc), st->uid, h2::CANCEL);
                queue_flush(e, st->uc);
            }
            finish_stream(e, st, false);
            return;
        }
        size_t off, n;
        if (uint32_t err = h2::strip_payload(flags, false, p, len, &off,
                                             &n)) {
            conn_error(e, c, err);
            return;
        }
        st->c_runacked += len;
        st->req_b += n;
        if (st->body_progress_us != 0 && n > 0)
            st->body_progress_us = loop_now(e);
        st->u_pend.append((const char*)(p + off), n);
        c->buffered += n;
        if (st->retain_valid) {
            if (st->req_retain.size() + n > RETAIN_CAP) {
                st->retain_valid = false;
                st->req_retain.clear();
            } else {
                st->req_retain.append((const char*)(p + off), n);
            }
        }
        if (flags & h2::FLAG_END_STREAM) {
            st->req_end_seen = true;
            st->u_pend_end = true;
            st->body_progress_us = 0;  // body complete: budget disarmed
        }
        if (st->parked && st->u_pend.size() > PARKED_PEND_CAP) {
            h2::write_rst(wbuf(c), sid, h2::ENHANCE_YOUR_CALM);
            queue_flush(e, c);
            finish_stream(e, st, false);
            return;
        }
        pump_upstream(e, st);
        if (!c->dead) {
            if (!st->closed) note_frame(e, st, l5dstream::FRAME_DATA, n);
            if (!st->closed) stream_grant(e, st, true);
            conn_grant(e, c);
        }
        break;
    }
    case h2::WINDOW_UPDATE: {
        if (len < 4) { conn_error(e, c, h2::FRAME_SIZE_ERROR); return; }
        uint32_t inc = h2::get_u32(p) & 0x7FFFFFFF;
        if (sid == 0) {
            c->s.send_win += inc;
            std::vector<PStream*> sts;
            for (auto& kv : c->streams)
                if (!kv.second->c_pend.empty() || kv.second->c_pend_end)
                    sts.push_back(kv.second);
            for (PStream* st : sts) {
                if (c->dead) return;
                if (st->closed) continue;
                pump_client(e, st);
            }
        } else {
            auto it = c->streams.find(sid);
            if (it != c->streams.end()) {
                PStream* st = it->second;
                st->c_swin += inc;
                pump_client(e, st);
                if (!c->dead && !st->closed)
                    note_frame(e, st, l5dstream::FRAME_WINDOW_UPDATE, 0);
            }
        }
        break;
    }
    case h2::SETTINGS:
        if (sid != 0 || len % 6) {
            conn_error(e, c, h2::FRAME_SIZE_ERROR);
            return;
        }
        if (!flood_ok(e, c, &c->settings_count,
                      e->guard_cfg.settings_burst, false))
            return;
        if (!(flags & h2::FLAG_ACK)) apply_settings(e, c, p, len);
        break;
    case h2::PING:
        if (len != 8) { conn_error(e, c, h2::FRAME_SIZE_ERROR); return; }
        if (!flood_ok(e, c, &c->ping_count, e->guard_cfg.ping_burst,
                      false))
            return;
        if (!(flags & h2::FLAG_ACK)) {
            h2::write_frame(wbuf(c), h2::PING, h2::FLAG_ACK, 0,
                            (const char*)p, 8);
            queue_flush(e, c);
        }
        break;
    case h2::RST_STREAM: {
        if (len < 4) { conn_error(e, c, h2::FRAME_SIZE_ERROR); return; }
        // rapid-reset cap (CVE-2023-44487): a client opening streams
        // and immediately cancelling them burns header-decode + routing
        // + upstream work per stream while keeping its own concurrency
        // at zero — cap client RSTs per window, then GOAWAY the conn
        if (!flood_ok(e, c, &c->rst_count, e->guard_cfg.rst_burst, true))
            return;
        auto it = c->streams.find(sid);
        if (it != c->streams.end()) {
            PStream* st = it->second;
            note_frame(e, st, l5dstream::FRAME_ANOMALY, 0);
            if (st->closed) break;  // sentinel already shed it
            if (st->uc != nullptr && st->uid) {
                h2::write_rst(wbuf(st->uc), st->uid, h2::CANCEL);
                queue_flush(e, st->uc);
            }
            finish_stream(e, st, false);
        }
        break;
    }
    case h2::GOAWAY:
        c->draining = true;
        break;
    case h2::PRIORITY:
    default:
        break;  // ignored
    }
}

void handle_upstream_frame(Engine* e, H2Conn* c, uint8_t type,
                           uint8_t flags, uint32_t sid, const uint8_t* p,
                           size_t len) {
    if (c->s.in_headers && type != h2::CONTINUATION) {
        conn_error(e, c, h2::PROTOCOL_ERROR);
        return;
    }
    switch (type) {
    case h2::HEADERS: {
        size_t off, n;
        if (uint32_t err = h2::strip_payload(flags, true, p, len, &off,
                                             &n)) {
            conn_error(e, c, err);
            return;
        }
        c->s.hb_buf.assign((const char*)(p + off), n);
        c->s.hb_stream = sid;
        c->s.hb_flags = flags;
        if (flags & h2::FLAG_END_HEADERS) {
            upstream_headers_complete(e, c);
        } else {
            c->s.in_headers = true;
        }
        break;
    }
    case h2::CONTINUATION:
        if (!c->s.in_headers || sid != c->s.hb_stream) {
            conn_error(e, c, h2::PROTOCOL_ERROR);
            return;
        }
        c->s.hb_buf.append((const char*)p, len);
        if (c->s.hb_buf.size() > 256 * 1024) {
            conn_error(e, c, h2::ENHANCE_YOUR_CALM);
            return;
        }
        if (flags & h2::FLAG_END_HEADERS) {
            c->s.in_headers = false;
            upstream_headers_complete(e, c);
        }
        break;
    case h2::DATA: {
        c->s.recv_win -= (int64_t)len;
        if (c->s.recv_win < 0) {
            conn_error(e, c, h2::FLOW_CONTROL_ERROR);
            return;
        }
        c->s.recv_unacked += len;
        auto it = c->streams.find(sid);
        if (it == c->streams.end()) {
            conn_grant(e, c);
            return;
        }
        PStream* st = it->second;
        st->u_recv_win -= (int64_t)len;
        if (st->u_recv_win < 0) {
            h2::write_rst(wbuf(c), sid, h2::FLOW_CONTROL_ERROR);
            queue_flush(e, c);
            st->status = 502;
            if (st->cc != nullptr) {
                if (st->rsp_started) {
                    h2::write_rst(wbuf(st->cc), st->cid,
                                  h2::INTERNAL_ERROR);
                    queue_flush(e, st->cc);
                } else {
                    synth_response(e, st->cc, st->cid, 502,
                                   "upstream flow");
                }
            }
            finish_stream(e, st, true);
            return;
        }
        size_t off, n;
        if (uint32_t err = h2::strip_payload(flags, false, p, len, &off,
                                             &n)) {
            conn_error(e, c, err);
            return;
        }
        st->u_runacked += len;
        st->rsp_b += n;
        st->c_pend.append((const char*)(p + off), n);
        c->buffered += n;
        if (flags & h2::FLAG_END_STREAM) st->c_pend_end = true;
        pump_client(e, st);
        if (!c->dead) {
            if (!st->closed) note_frame(e, st, l5dstream::FRAME_DATA, n);
            conn_grant(e, c);
        }
        break;
    }
    case h2::WINDOW_UPDATE: {
        if (len < 4) { conn_error(e, c, h2::FRAME_SIZE_ERROR); return; }
        uint32_t inc = h2::get_u32(p) & 0x7FFFFFFF;
        if (sid == 0) {
            c->s.send_win += inc;
            std::vector<PStream*> sts;
            for (auto& kv : c->streams)
                if (!kv.second->u_pend.empty() || kv.second->u_pend_end ||
                    kv.second->u_has_trailers)
                    sts.push_back(kv.second);
            for (PStream* st : sts) {
                if (c->dead) return;
                if (st->closed) continue;
                pump_upstream(e, st);
            }
        } else {
            auto it = c->streams.find(sid);
            if (it != c->streams.end()) {
                it->second->u_swin += inc;
                pump_upstream(e, it->second);
            }
        }
        break;
    }
    case h2::SETTINGS:
        if (sid != 0 || len % 6) {
            conn_error(e, c, h2::FRAME_SIZE_ERROR);
            return;
        }
        if (!(flags & h2::FLAG_ACK)) apply_settings(e, c, p, len);
        break;
    case h2::PING:
        if (len != 8) { conn_error(e, c, h2::FRAME_SIZE_ERROR); return; }
        if (!(flags & h2::FLAG_ACK)) {
            h2::write_frame(wbuf(c), h2::PING, h2::FLAG_ACK, 0,
                            (const char*)p, 8);
            queue_flush(e, c);
        }
        break;
    case h2::RST_STREAM: {
        if (len < 4) { conn_error(e, c, h2::FRAME_SIZE_ERROR); return; }
        uint32_t code = h2::get_u32(p);
        auto it = c->streams.find(sid);
        if (it != c->streams.end()) {
            PStream* st = it->second;
            if (code == h2::REFUSED_STREAM) {
                // RFC 7540 §8.1.4: REFUSED_STREAM guarantees no
                // processing happened — safe to replay. The common
                // cause is the race where we dispatched a burst before
                // the server's MAX_CONCURRENT_STREAMS SETTINGS arrived;
                // by now they have, so the retry queues on the slot.
                c->streams.erase(st->uid);
                if (c->active_streams > 0) c->active_streams--;
                // reconcile the buffered counter now: with uc nulled,
                // finish_stream's subtraction is unreachable and the
                // leak would eventually pin the conn window shut
                c->buffered -= st->c_pend.size();
                st->c_pend.clear();
                st->uc = nullptr;  // unlinked here; stays null on failure
                st->uid = 0;
                bool replayed = replay_stream(e, st);
                // the freed slot must wake queued dispatches on THIS
                // conn — the replay may have routed elsewhere, and
                // finish_stream's wakeup sees uc == nullptr
                dispatch_from_queue(e, c);
                if (replayed) break;
            }
            st->status = 502;
            if (st->cc != nullptr) {
                if (st->rsp_started || st->rsp_end_sent) {
                    h2::write_rst(wbuf(st->cc), st->cid, code);
                    queue_flush(e, st->cc);
                } else {
                    synth_response(e, st->cc, st->cid, 502, "upstream rst");
                }
            }
            finish_stream(e, st, true);
        }
        break;
    }
    case h2::GOAWAY: {
        // reconnect semantics: this conn takes no new streams; streams
        // the server never processed (uid > last_id) replay on a fresh
        // conn when the request is still retained, else the client gets
        // REFUSED_STREAM (safely retryable per RFC 7540 §8.1.4)
        if (len < 8) { conn_error(e, c, h2::FRAME_SIZE_ERROR); return; }
        uint32_t last_id = h2::get_u32(p) & 0x7FFFFFFF;
        c->draining = true;
        clear_endpoint_slot(e, c);
        std::vector<PStream*> refused;
        for (auto& kv : c->streams)
            if (kv.first > last_id) refused.push_back(kv.second);
        for (PStream* st : refused) {
            c->streams.erase(st->uid);
            if (c->active_streams > 0) c->active_streams--;
            // reconcile buffered before nulling uc (same invariant as
            // the REFUSED_STREAM path): finish_stream can't reach it
            c->buffered -= st->c_pend.size();
            st->c_pend.clear();
            st->uc = nullptr;
            st->uid = 0;
            if (replay_stream(e, st)) continue;
            if (st->cc != nullptr) {
                h2::write_rst(wbuf(st->cc), st->cid, h2::REFUSED_STREAM);
                queue_flush(e, st->cc);
            }
            finish_stream(e, st, false);
        }
        std::vector<PStream*> queued(c->pend_dispatch.begin(),
                                     c->pend_dispatch.end());
        c->pend_dispatch.clear();
        for (PStream* st : queued) {
            st->uc = nullptr;
            release_inflight(e, st);
            if (!dispatch_stream(e, st)) {
                if (st->cc != nullptr)
                    synth_response(e, st->cc, st->cid, 502, "upstream");
                finish_stream(e, st, true);
            }
        }
        if (c->streams.empty()) conn_close(e, c);
        break;
    }
    case h2::PRIORITY:
    default:
        break;
    }
}

void process_in(Engine* e, H2Conn* c) {
    size_t pos = 0;
    if (c->kind == H2Conn::Kind::CLIENT && !c->s.preface_seen) {
        if (c->in.size() < h2::PREFACE_LEN) return;
        if (memcmp(c->in.data(), h2::PREFACE, h2::PREFACE_LEN) != 0) {
            conn_close(e, c);
            return;
        }
        c->s.preface_seen = true;
        c->preface_deadline_us = 0;
        pos = h2::PREFACE_LEN;
    }
    while (!c->dead && c->in.size() - pos >= 9) {
        const uint8_t* h = (const uint8_t*)c->in.data() + pos;
        uint32_t len = ((uint32_t)h[0] << 16) | ((uint32_t)h[1] << 8)
            | h[2];
        uint8_t type = h[3];
        uint8_t flags = h[4];
        uint32_t sid = h2::get_u32(h + 5) & 0x7FFFFFFF;
        if (len > MAX_FRAME_OK) {
            conn_error(e, c, h2::FRAME_SIZE_ERROR);
            return;
        }
        if (c->in.size() - pos < 9 + (size_t)len) break;
        if (c->kind == H2Conn::Kind::CLIENT)
            handle_client_frame(e, c, type, flags, sid, h + 9, len);
        else
            handle_upstream_frame(e, c, type, flags, sid, h + 9, len);
        if (c->dead) return;
        pos += 9 + (size_t)len;
    }
    if (pos) c->in.erase(0, pos);
}

void on_readable(Engine* e, H2Conn* c) {
    char buf[64 * 1024];
    for (;;) {
        if (c->dead) return;
        ssize_t n = ::recv(c->fd, buf, sizeof(buf), 0);
        if (n < 0) {
            if (errno == EINTR) continue;  // signal, not a dead conn
            if (errno == EAGAIN || errno == EWOULDBLOCK) return;
            conn_close(e, c);
            return;
        }
        if (n == 0) {
            conn_close(e, c);
            return;
        }
        int tls_rc = 0;
        if (c->tls != nullptr) {
            bool was_hs = !c->tls->sess->hs_done;
            tls_rc = l5dtls::ingest(c->tls, buf, (size_t)n, &c->in,
                                    &c->out);
            if (tls_rc < 0) {
                tls_account(e, c, was_hs);
                if (!c->out.empty())  // let the TLS alert out
                    (void)::send(c->fd, c->out.data(), c->out.size(),
                                 MSG_NOSIGNAL);
                conn_close(e, c);
                return;
            }
            if (was_hs && c->tls->sess->hs_done) {
                hs_complete(e, c);
                tls_account(e, c, false);
            }
            queue_flush(e, c);  // handshake records / tickets / staged
        } else {
            c->in.append(buf, (size_t)n);
        }
        process_in(e, c);
        if (tls_rc == 1 && !c->dead) {  // clean TLS shutdown
            conn_close(e, c);
            return;
        }
    }
}

void on_listener(Engine* e, int lfd) {
    bool tls = e->tls_srv != nullptr && e->tls_listeners.count(lfd) > 0;
    for (;;) {
        sockaddr_in peer{};
        socklen_t plen = sizeof(peer);
        int fd = ::accept4(lfd, (sockaddr*)&peer, &plen, SOCK_NONBLOCK);
        if (fd < 0) {
            if (errno == EINTR) continue;  // don't drop the pending conn
            return;
        }
        uint64_t now = loop_now(e);
        // per-source accept throttle: churn floods are shed at accept
        if (peer.sin_family == AF_INET &&
            !e->sources.allow(peer.sin_addr.s_addr, e->guard_cfg, now)) {
            e->guard.accept_throttled.fetch_add(
                1, std::memory_order_relaxed);
            ::close(fd);
            continue;
        }
        // handshake-churn backpressure: shed new TLS conns while too
        // many handshakes are in flight (see fastpath.cpp)
        if (tls && e->guard_cfg.max_hs_inflight != 0 &&
            e->hs_inflight >= e->guard_cfg.max_hs_inflight) {
            e->guard.hs_churn_shed.fetch_add(
                1, std::memory_order_relaxed);
            ::close(fd);
            continue;
        }
        set_nodelay(fd);
        H2Conn* c = new H2Conn();
        c->kind = H2Conn::Kind::CLIENT;
        c->fd = fd;
        // slowloris: a fresh conn must complete its client preface
        // within the header budget (TLS conns get the handshake budget
        // on top — the sweep enforces both independently)
        if (e->guard_cfg.header_budget_us != 0)
            c->preface_deadline_us =
                now + e->guard_cfg.header_budget_us
                + (tls ? TLS_HS_TIMEOUT_US : 0);
        if (tls) {
            l5dtls::Sess* s = l5dtls::new_session(e->tls_srv, nullptr,
                                                  false, nullptr);
            if (s == nullptr) {
                ::close(fd);
                delete c;
                continue;
            }
            c->tls = new l5dtls::TlsIo();
            c->tls->sess = s;
            c->tls->hs_deadline_us = now + TLS_HS_TIMEOUT_US;
            c->hs_pending = true;
            e->hs_inflight++;
        }
        // server preface: SETTINGS + a big connection window (staged as
        // plaintext on TLS conns; write_plain holds it until hs_done)
        h2::write_settings(wbuf(c),
                           {{h2::S_HEADER_TABLE_SIZE, 4096},
                            {h2::S_MAX_CONCURRENT_STREAMS, 1024},
                            {h2::S_INITIAL_WINDOW_SIZE,
                             (uint32_t)OUR_STREAM_WIN},
                            {h2::S_MAX_FRAME_SIZE, h2::DEFAULT_MAX_FRAME}},
                           false);
        h2::write_window_update(wbuf(c), 0, (uint32_t)(OUR_CONN_WIN
                                                       - h2::DEFAULT_WINDOW));
        c->s.recv_win = OUR_CONN_WIN;
        ep_add(e, c);
        queue_flush(e, c);
        e->accepted.fetch_add(1, std::memory_order_relaxed);
    }
}

void sweep(Engine* e) {
    uint64_t now = loop_now(e);
    if (now - e->last_sweep_us < 500'000) return;
    e->last_sweep_us = now;
    // TLS handshake budget: a peer still mid-handshake past its window
    // is a handshake failure and must not pin a conn slot (the loop
    // never blocks on TLS, so only the sweep can reclaim these)
    std::vector<H2Conn*> hs_expired;
    for (auto& kv : e->conns) {
        H2Conn* c = kv.second;
        if (c->tls != nullptr && c->tls->hs_deadline_us != 0 &&
            now > c->tls->hs_deadline_us)
            hs_expired.push_back(c);
    }
    for (H2Conn* c : hs_expired) {
        tls_account(e, c, /*failed=*/true);
        conn_close(e, c);
    }
    // slowloris sweeps: (a) fresh conns that never completed the
    // client preface, (b) conns stalled mid header block
    // (CONTINUATION started, END_HEADERS never arrived)
    std::vector<H2Conn*> loris;
    for (auto& kv : e->conns) {
        H2Conn* c = kv.second;
        if (c->kind != H2Conn::Kind::CLIENT || c->dead) continue;
        if (c->preface_deadline_us != 0 && now > c->preface_deadline_us) {
            loris.push_back(c);
        } else if (e->guard_cfg.header_budget_us != 0 &&
                   c->s.in_headers && c->hb_start_us != 0 &&
                   now - c->hb_start_us >
                       e->guard_cfg.header_budget_us) {
            loris.push_back(c);
        }
    }
    for (H2Conn* c : loris) {
        e->guard.slowloris_closed.fetch_add(1, std::memory_order_relaxed);
        conn_close(e, c);
    }
    // zero-progress request bodies: RST the stalled stream (both
    // sides), spare the conn — a trickling uploader must not pin an
    // upstream stream slot indefinitely
    if (e->guard_cfg.body_stall_budget_us != 0) {
        std::vector<PStream*> stalls;
        for (auto& kv : e->conns) {
            H2Conn* c = kv.second;
            if (c->kind != H2Conn::Kind::CLIENT || c->dead) continue;
            for (auto& skv : c->streams) {
                PStream* st = skv.second;
                if (st->body_progress_us != 0 && !st->req_end_seen &&
                    now - st->body_progress_us >
                        e->guard_cfg.body_stall_budget_us)
                    stalls.push_back(st);
            }
        }
        for (PStream* st : stalls) {
            if (st->closed) continue;
            e->guard.body_stall_closed.fetch_add(
                1, std::memory_order_relaxed);
            if (st->cc != nullptr && !st->cc->dead) {
                h2::write_rst(wbuf(st->cc), st->cid,
                              h2::ENHANCE_YOUR_CALM);
                queue_flush(e, st->cc);
            }
            if (st->uc != nullptr && st->uid && !st->uc->dead) {
                h2::write_rst(wbuf(st->uc), st->uid, h2::CANCEL);
                queue_flush(e, st->uc);
            }
            finish_stream(e, st, false);
        }
    }
    std::vector<PStream*> expired;
    for (auto& kv : e->parked)
        for (PStream* st : kv.second)
            if (now > st->park_deadline_us) expired.push_back(st);
    for (PStream* st : expired) {
        if (st->closed) continue;
        if (st->cc != nullptr)
            synth_response(e, st->cc, st->cid, 400, "no route");
        finish_stream(e, st, false);
    }
    // Response-START timeout (h1 engine's EXCHANGE_TIMEOUT analog): a
    // dispatched stream whose backend hasn't produced response HEADERS
    // within the window gets a 504. Gated on !rsp_started so long-lived
    // streaming responses (gRPC watches) are untouched.
    std::vector<PStream*> stalled;
    for (auto& kv : e->conns) {
        H2Conn* c = kv.second;
        if (c->kind != H2Conn::Kind::CLIENT) continue;
        for (auto& skv : c->streams) {
            PStream* st = skv.second;
            if (!st->parked && !st->rsp_started && st->t_start_us &&
                now - st->t_start_us >
                    e->response_start_timeout_us.load(
                        std::memory_order_relaxed))
                stalled.push_back(st);
        }
    }
    for (PStream* st : stalled) {
        if (st->closed) continue;
        if (st->uc != nullptr && st->uid) {
            h2::write_rst(wbuf(st->uc), st->uid, h2::CANCEL);
            queue_flush(e, st->uc);
        }
        st->status = 504;
        if (st->cc != nullptr && !st->cc->dead)
            synth_response(e, st->cc, st->cid, 504, "response timeout");
        finish_stream(e, st, true);
    }
    // Endpoint churn orphans upstream conns: a route update that drops
    // an endpoint clears nothing here, so a conn with no streams and no
    // route slot referencing it would live until the peer closes.
    // (Referenced idle conns are the warm SingletonPool — kept.)
    std::vector<H2Conn*> orphans;
    for (auto& kv : e->conns) {
        H2Conn* c = kv.second;
        if (c->kind != H2Conn::Kind::UPSTREAM || c->dead) continue;
        if (!c->streams.empty() || !c->pend_dispatch.empty()) {
            c->idle_since_us = 0;
            continue;
        }
        if (c->idle_since_us == 0) {
            c->idle_since_us = now;
            continue;
        }
        if (now - c->idle_since_us < ORPHAN_IDLE_TIMEOUT_US) continue;
        bool referenced = false;
        {
            std::lock_guard<std::mutex> g(e->mu);
            auto it = e->routes.find(c->route_key);
            if (it != e->routes.end())
                for (auto& ep : it->second.eps)
                    if (ep.conn == c) {
                        referenced = true;
                        break;
                    }
        }
        if (!referenced) {
            orphans.push_back(c);
        } else {
            // still the endpoint's warm conn: re-stamp so the locked
            // route lookup runs at most once per timeout window
            c->idle_since_us = now;
        }
    }
    for (H2Conn* c : orphans) conn_close(e, c);
}

void drain_graveyard(Engine* e) {
    for (H2Conn* c : e->graveyard) delete c;
    e->graveyard.clear();
    for (PStream* st : e->stream_graveyard) delete st;
    e->stream_graveyard.clear();
}

void* loop_main(void* arg) {
    Engine* e = (Engine*)arg;
    epoll_event evs[MAX_EVENTS];
    e->defer_ok = true;  // frame producers may now coalesce writes
    while (e->running.load(std::memory_order_relaxed)) {
        int n = epoll_wait(e->epfd, evs, MAX_EVENTS, 250);
        // ONE clock read per wakeup: everything this round
        // timestamps (deadlines, latency, features) reads this
        e->now_cache_us = now_us();
        for (int i = 0; i < n; i++) {
            int fd = evs[i].data.fd;
            uint32_t ev = evs[i].events;
            if (fd == e->wakefd) {
                uint64_t v;
                ssize_t r = ::read(e->wakefd, &v, sizeof(v));
                (void)r;
                // l5d: ignore[hot-alloc] — wakefd branch: runs only on a control-plane route-update wakeup, not per request
                std::vector<std::string> hosts;
                {
                    std::lock_guard<std::mutex> g(e->mu);
                    for (auto& kv : e->parked)
                        if (e->routes.count(kv.first))
                            hosts.push_back(kv.first);
                }
                for (auto& h : hosts) unpark_route(e, h);
                continue;
            }
            bool is_listener = false;
            for (int lfd : e->listeners)
                if (lfd == fd) {
                    is_listener = true;
                    break;
                }
            if (is_listener) {
                on_listener(e, fd);
                continue;
            }
            auto it = e->conns.find(fd);
            if (it == e->conns.end()) continue;
            H2Conn* c = it->second;
            if (ev & (EPOLLHUP | EPOLLERR)) {
                conn_close(e, c);
                continue;
            }
            if (ev & EPOLLOUT) {
                if (c->kind == H2Conn::Kind::UPSTREAM && c->connecting) {
                    int err = 0;
                    socklen_t sl = sizeof(err);
                    getsockopt(c->fd, SOL_SOCKET, SO_ERROR, &err, &sl);
                    if (err != 0) {
                        conn_close(e, c);
                        continue;
                    }
                    c->connecting = false;
                }
                size_t before = outsz(c);
                if (!flush_out(e, c)) continue;
                if (outsz(c) < before) {
                    // room freed: resume streams stalled on OUT_HIGH
                    // l5d: ignore[hot-alloc] — runs only when a blocked EPOLLOUT flush frees buffer room (backpressure release), not in the steady state
                    std::vector<PStream*> sts;
                    for (auto& kv : c->streams) sts.push_back(kv.second);
                    for (PStream* st : sts) {
                        if (c->dead) break;
                        if (st->closed) continue;
                        if (c->kind == H2Conn::Kind::CLIENT)
                            pump_client(e, st);
                        else
                            pump_upstream(e, st);
                    }
                }
            }
            if ((ev & (EPOLLIN | EPOLLRDHUP)) && !c->dead)
                on_readable(e, c);
        }
        drain_pending_rst(e);
        sweep(e);
        // ONE coalesced flush per wakeup: every frame produced this
        // round (requests, grants, PING acks, synth responses) leaves
        // in a single send()/TLS-record batch per conn
        drain_dirty(e);
        drain_graveyard(e);
    }
    drain_dirty(e);          // teardown frames (GOAWAYs) still flush
    e->defer_ok = false;     // shutdown-path writes go straight out
    return nullptr;
}

}  // namespace

extern "C" {

void* fph2_create() {
    Engine* e = new Engine();
    e->epfd = epoll_create1(0);
    e->wakefd = eventfd(0, EFD_NONBLOCK);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = e->wakefd;
    epoll_ctl(e->epfd, EPOLL_CTL_ADD, e->wakefd, &ev);
    return e;
}

int fph2_start(void* ep) {
    Engine* e = (Engine*)ep;
    if (e->thread_started) return 0;
    if (pthread_create(&e->thread, nullptr, loop_main, e) != 0) return -1;
    e->thread_started = true;
    return 0;
}

static int fph2_listen_impl(Engine* e, const char* ip, int port,
                            int reuseport) {
    int fd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) return -1;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    if (reuseport)
        setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, ip, &sa.sin_addr) != 1) {
        ::close(fd);
        return -1;
    }
    if (bind(fd, (sockaddr*)&sa, sizeof(sa)) < 0 || listen(fd, 1024) < 0) {
        ::close(fd);
        return -1;
    }
    socklen_t sl = sizeof(sa);
    getsockname(fd, (sockaddr*)&sa, &sl);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = fd;
    epoll_ctl(e->epfd, EPOLL_CTL_ADD, fd, &ev);
    e->listeners.push_back(fd);
    return (int)ntohs(sa.sin_port);
}

int fph2_listen(void* ep, const char* ip, int port) {
    return fph2_listen_impl((Engine*)ep, ip, port, 0);
}

// SO_REUSEPORT variant for multi-core sharding: N worker engines each
// bind the SAME ip:port and the kernel distributes connections (see
// fp_listen_shared in fastpath.cpp for the full contract).
int fph2_listen_shared(void* ep, const char* ip, int port) {
    return fph2_listen_impl((Engine*)ep, ip, port, 1);
}

// 1 when the OpenSSL runtime could be dlopen'd (TLS termination /
// origination available), else 0.
int fph2_tls_runtime_available() { return l5dtls::available() ? 1 : 0; }

// Install the accept-leg TLS context (cert/key PEM + ALPN preference
// CSV, e.g. "h2"). Call BEFORE fph2_start. Returns 0, or -1 with the
// OpenSSL error text in err.
int fph2_set_tls(void* ep, const char* cert, const char* key,
                 const char* alpn, char* err, size_t errcap) {
    Engine* e = (Engine*)ep;
    std::string why;
    l5dtls::Ctx* c = l5dtls::server_ctx(cert, key, alpn, &why);
    if (c == nullptr) {
        if (err != nullptr && errcap > 0) {
            snprintf(err, errcap, "%s", why.c_str());
        }
        return -1;
    }
    l5dtls::free_ctx(e->tls_srv);
    e->tls_srv = c;
    return 0;
}

// Like fph2_listen, but connections accepted on this listener terminate
// TLS (requires fph2_set_tls first).
int fph2_listen_tls(void* ep, const char* ip, int port) {
    Engine* e = (Engine*)ep;
    if (e->tls_srv == nullptr) return -1;
    int got = fph2_listen(ep, ip, port);
    if (got >= 0) e->tls_listeners.insert(e->listeners.back());
    return got;
}

// TLS + SO_REUSEPORT (see fph2_listen_shared).
int fph2_listen_tls_shared(void* ep, const char* ip, int port) {
    Engine* e = (Engine*)ep;
    if (e->tls_srv == nullptr) return -1;
    int got = fph2_listen_shared(ep, ip, port);
    if (got >= 0) e->tls_listeners.insert(e->listeners.back());
    return got;
}

// Originate TLS to every upstream endpoint (the router-wide client.tls
// block). verify=0 skips chain/hostname validation
// (tls.disableValidation parity); ca_path, when set, replaces the
// default trust roots. Call BEFORE fph2_start.
int fph2_set_client_tls(void* ep, const char* alpn, int verify,
                        const char* ca_path, char* err, size_t errcap) {
    Engine* e = (Engine*)ep;
    std::string why;
    l5dtls::Ctx* c = l5dtls::client_ctx(alpn, verify != 0, ca_path, &why);
    if (c == nullptr) {
        if (err != nullptr && errcap > 0) {
            snprintf(err, errcap, "%s", why.c_str());
        }
        return -1;
    }
    l5dtls::free_ctx(e->tls_cli);
    e->tls_cli = c;
    e->tls_cli_verify = verify != 0;
    return 0;
}

int fph2_set_route(void* ep, const char* host, const char* endpoints) {
    Engine* e = (Engine*)ep;
    std::vector<Endpoint> eps;
    const char* p = endpoints;
    while (p && *p) {
        while (*p == ' ') p++;
        if (!*p) break;
        const char* colon = strchr(p, ':');
        if (!colon) break;
        std::string ip(p, (size_t)(colon - p));
        int port = atoi(colon + 1);
        Endpoint epnt{};
        if (inet_pton(AF_INET, ip.c_str(), &epnt.ip_be) == 1 &&
            port > 0 && port < 65536) {
            epnt.port = (uint16_t)port;
            eps.push_back(epnt);
        }
        const char* sp = strchr(colon, ' ');
        if (!sp) break;
        p = sp + 1;
    }
    std::string key(host);
    lower(key);
    {
        std::lock_guard<std::mutex> g(e->mu);
        auto it = e->routes.find(key);
        if (it == e->routes.end()) {
            Route r;
            r.id = e->next_route_id++;
            r.eps = std::move(eps);
            e->routes.emplace(std::move(key), std::move(r));
        } else {
            Route& r = it->second;
            for (auto& ne : eps)
                for (auto& oe : r.eps)
                    if (oe.ip_be == ne.ip_be && oe.port == ne.port) {
                        ne.inflight = oe.inflight;
                        ne.conn = oe.conn;
                    }
            r.eps = std::move(eps);
        }
    }
    uint64_t v = 1;
    ssize_t r = ::write(e->wakefd, &v, sizeof(v));
    (void)r;
    return 0;
}

void fph2_set_response_timeout_ms(void* ep, long ms) {
    Engine* e = (Engine*)ep;
    if (ms < 1) return;  // 0/negative would 504 everything / wrap
    e->response_start_timeout_us.store((uint64_t)ms * 1000,
                                       std::memory_order_relaxed);
}

int fph2_remove_route(void* ep, const char* host) {
    Engine* e = (Engine*)ep;
    std::string key(host);
    lower(key);
    std::lock_guard<std::mutex> g(e->mu);
    return e->routes.erase(key) ? 0 : -1;
}

long fph2_drain_misses(void* ep, char* buf, size_t cap) {
    Engine* e = (Engine*)ep;
    std::lock_guard<std::mutex> g(e->mu);
    size_t used = 0;
    long count = 0;
    while (!e->misses.empty()) {
        const std::string& h = e->misses.front();
        if (used + h.size() + 2 > cap) break;
        memcpy(buf + used, h.data(), h.size());
        used += h.size();
        buf[used++] = '\n';
        e->misses.pop_front();
        count++;
    }
    buf[used] = 0;
    return count;
}

long fph2_stats_json(void* ep, char* buf, size_t cap) {
    Engine* e = (Engine*)ep;
    std::string s = "{\"routes\":{";
    std::lock_guard<std::mutex> g(e->mu);
    bool first = true;
    for (auto& kv : e->routes) {
        RouteStats& st = kv.second.stats;
        char tmp[256];
        s += first ? "\"" : ",\"";
        l5dtls::json_escape(kv.first, &s);  // keys came off the wire
        snprintf(tmp, sizeof(tmp),
                 "\":{\"id\":%llu,\"requests\":%llu,\"success\":%llu,"
                 "\"f4xx\":%llu,\"f5xx\":%llu,\"conn_fail\":%llu,"
                 "\"hist\":[",
                 (unsigned long long)kv.second.id,
                 (unsigned long long)st.requests,
                 (unsigned long long)st.success,
                 (unsigned long long)st.f4xx,
                 (unsigned long long)st.f5xx,
                 (unsigned long long)st.conn_fail);
        s += tmp;
        for (int i = 0; i < LAT_BUCKETS; i++) {
            if (i) s += ",";
            snprintf(tmp, sizeof(tmp), "%llu",
                     (unsigned long long)st.lat_hist[i]);
            s += tmp;
        }
        s += "]}";
        first = false;
    }
    char tail[512];
    l5dtls::TlsStats& t = e->tls_stats;
    snprintf(tail, sizeof(tail),
             "},\"accepted\":%llu,\"features_dropped\":%llu,"
             "\"tls\":{\"handshakes\":%llu,\"failures\":%llu,"
             "\"resumed\":%llu,\"alpn_h2\":%llu,\"alpn_http1\":%llu,"
             "\"upstream_handshakes\":%llu,\"upstream_resumed\":%llu,"
             "\"upstream_failures\":%llu,\"enabled\":%s,"
             "\"client_enabled\":%s},",
             (unsigned long long)e->accepted.load(
                 std::memory_order_relaxed),
             (unsigned long long)e->features_dropped,
             (unsigned long long)t.handshakes,
             (unsigned long long)t.failures,
             (unsigned long long)t.resumed,
             (unsigned long long)t.alpn_h2,
             (unsigned long long)t.alpn_http1,
             (unsigned long long)t.up_handshakes,
             (unsigned long long)t.up_resumed,
             (unsigned long long)t.up_failures,
             e->tls_srv != nullptr ? "true" : "false",
             e->tls_cli != nullptr ? "true" : "false");
    s += tail;
    l5dtg::tenants_json(e->tenants, e->quotas, &s);
    s += ",";
    l5dtg::guard_json(e->guard, &s);
    s += ",";
    l5dscore::stats_json(*e->slab, e->score_stats, &s);
    s += "}";
    if (s.size() + 1 > cap) return -2;
    memcpy(buf, s.data(), s.size());
    buf[s.size()] = 0;
    return (long)s.size();
}

long fph2_drain_features(void* ep, float* buf, long cap_rows) {
    Engine* e = (Engine*)ep;
    std::lock_guard<std::mutex> g(e->mu);
    long n = (long)e->features.size();
    if (n > cap_rows) n = cap_rows;
    for (long i = 0; i < n; i++)
        memcpy(buf + i * (sizeof(FeatureRow) / sizeof(float)),
               &e->features[(size_t)i], sizeof(FeatureRow));
    e->features.erase(e->features.begin(), e->features.begin() + n);
    return n;
}

// See fp_set_route_feature / fp_set_route_hash / fp_publish_weights /
// fp_publish_delta (fastpath.cpp) for the contract; this is the h2
// engine's identical control surface.
int fph2_set_route_feature(void* ep, const char* host, int col,
                           float sign) {
    Engine* e = (Engine*)ep;
    std::string key(host);
    lower(key);
    std::lock_guard<std::mutex> g(e->mu);
    auto it = e->routes.find(key);
    if (it == e->routes.end()) return -1;
    it->second.feat.col = col;
    it->second.feat.sign = sign;
    return 0;
}

int fph2_set_route_hash(void* ep, const char* host, unsigned int rhash) {
    Engine* e = (Engine*)ep;
    std::string key(host);
    lower(key);
    std::lock_guard<std::mutex> g(e->mu);
    auto it = e->routes.find(key);
    if (it == e->routes.end()) return -1;
    it->second.feat.rhash = rhash;
    return 0;
}

int fph2_publish_weights(void* ep, const uint8_t* blob, size_t len,
                         char* err, size_t errcap) {
    Engine* e = (Engine*)ep;
    l5dscore::Bank b;
    if (!l5dscore::parse_bank_blob(blob, len, &b, err, errcap))
        return -1;
    if (b.base.in_dim != l5dscore::FEATURE_DIM) {
        l5dscore::fail(err, errcap,
                       "weight blob in_dim does not match engine "
                       "FEATURE_DIM");
        return -1;
    }
    l5dscore::slab_install(e->slab, std::move(b));
    return 0;
}

int fph2_publish_delta(void* ep, const uint8_t* blob, size_t len,
                       char* err, size_t errcap) {
    Engine* e = (Engine*)ep;
    l5dscore::Delta d;
    if (!l5dscore::parse_delta_blob(blob, len, &d, err, errcap))
        return -1;
    if (!l5dscore::slab_apply_delta(e->slab, d, err, errcap)) return -1;
    return 0;
}

// Score/publish through an EXTERNAL shared weight slab — the
// multi-worker sharding seam (see fp_attach_slab in fastpath.cpp for
// the full contract). Call BEFORE fph2_start; NULL restores the
// embedded slab.
int fph2_attach_slab(void* ep, void* slab) {
    Engine* e = (Engine*)ep;
    if (e->thread_started) return -1;
    e->slab = slab != nullptr ? (l5dscore::Slab*)slab : &e->scorer_slab;
    return 0;
}

// Tenant extraction / quotas / guard knobs: the h2 engine's identical
// control surface (see fp_set_tenant / fp_set_tenant_quota /
// fp_set_guard in fastpath.cpp for the contract).
int fph2_set_tenant(void* ep, int kind, const char* header, int segment) {
    Engine* e = (Engine*)ep;
    if (kind < 0 || kind > 3) return -1;
    e->tenant_ex.kind = kind;
    e->tenant_ex.header = header != nullptr ? header : "";
    lower(e->tenant_ex.header);
    e->tenant_ex.segment = segment;
    return 0;
}

int fph2_set_tenant_quota(void* ep, unsigned int hash, int limit) {
    Engine* e = (Engine*)ep;
    std::lock_guard<std::mutex> g(e->mu);
    return e->quotas.set(hash, limit);
}

int fph2_set_guard(void* ep, long header_budget_ms, long body_stall_ms,
                   long accept_burst, long accept_window_ms,
                   long max_hs_inflight, long tenant_cap) {
    Engine* e = (Engine*)ep;
    if (header_budget_ms < 0 || body_stall_ms < 0 || accept_burst < 0 ||
        accept_window_ms < 1 || max_hs_inflight < 0 || tenant_cap < 1)
        return -1;
    e->guard_cfg.header_budget_us = (uint64_t)header_budget_ms * 1000;
    e->guard_cfg.body_stall_budget_us = (uint64_t)body_stall_ms * 1000;
    e->guard_cfg.accept_burst = (uint32_t)accept_burst;
    e->guard_cfg.accept_window_us = (uint64_t)accept_window_ms * 1000;
    e->guard_cfg.max_hs_inflight = (uint32_t)max_hs_inflight;
    std::lock_guard<std::mutex> g(e->mu);
    e->tenants.cap = (size_t)tenant_cap;
    return 0;
}

// h2-only flood caps (per client conn per window); 0 disables one cap.
int fph2_set_flood_guard(void* ep, long max_streams, long rst_burst,
                         long ping_burst, long settings_burst,
                         long window_ms) {
    Engine* e = (Engine*)ep;
    if (max_streams < 0 || rst_burst < 0 || ping_burst < 0 ||
        settings_burst < 0 || window_ms < 1)
        return -1;
    e->guard_cfg.max_streams_per_conn = (uint32_t)max_streams;
    e->guard_cfg.rst_burst = (uint32_t)rst_burst;
    e->guard_cfg.ping_burst = (uint32_t)ping_burst;
    e->guard_cfg.settings_burst = (uint32_t)settings_burst;
    e->guard_cfg.flood_window_us = (uint64_t)window_ms * 1000;
    return 0;
}

// Stream sentinel config: sampling cadence + native hysteresis knobs
// (enter/exit/quorum/dwell mirror control.state.HysteresisGovernor) +
// actuation mode (0 observe, 1 RST). Call BEFORE fph2_start, like the
// guard knobs — the loop thread reads the cfg unlocked.
int fph2_set_stream_cfg(void* ep, long enabled, long sample_every,
                        long min_gap_ms, long table_cap, double enter,
                        double exitv, long quorum, long dwell_ms,
                        long action) {
    Engine* e = (Engine*)ep;
    if (e->thread_started) return -1;
    if (sample_every < 1 || min_gap_ms < 0 || table_cap < 1 ||
        quorum < 1 || dwell_ms < 0 || action < 0 || action > 1)
        return -1;
    if (enabled && !(0.0 < exitv && exitv < enter && enter <= 1.0))
        return -1;
    e->stream_cfg.enabled = enabled ? 1 : 0;
    e->stream_cfg.sample_every = (uint32_t)sample_every;
    e->stream_cfg.sample_min_gap_us = (uint64_t)min_gap_ms * 1000;
    e->stream_cfg.enter = enter;
    e->stream_cfg.exit_ = exitv;
    e->stream_cfg.quorum = (int)quorum;
    e->stream_cfg.dwell_us = (uint64_t)dwell_ms * 1000;
    e->stream_cfg.action = (int)action;
    std::lock_guard<std::mutex> g(e->mu);
    e->stream_tab.cap = (size_t)table_cap;
    return 0;
}

// /streams.json: the bounded stream table + actuation counters.
long fph2_streams_json(void* ep, char* buf, size_t cap) {
    Engine* e = (Engine*)ep;
    std::string s;
    {
        std::lock_guard<std::mutex> g(e->mu);
        l5dstream::streams_json(e->stream_tab,
                                e->stream_cfg.enabled != 0, &s);
    }
    if (s.size() + 1 > cap) return -2;
    memcpy(buf, s.data(), s.size());
    buf[s.size()] = 0;
    return (long)s.size();
}

// Python-side mid-stream actuation: queue an RST for the stream with
// this 24-bit key (as carried in feature-row column 10) and wake the
// loop. Unknown/already-finished keys are a no-op.
int fph2_rst_stream(void* ep, unsigned int skey) {
    Engine* e = (Engine*)ep;
    if (skey == 0) return -1;
    {
        std::lock_guard<std::mutex> g(e->mu);
        e->pending_rst.push_back((uint32_t)skey);
    }
    uint64_t v = 1;
    ssize_t r = ::write(e->wakefd, &v, sizeof(v));
    (void)r;
    return 0;
}

void fph2_shutdown(void* ep) {
    Engine* e = (Engine*)ep;
    e->running.store(false);
    uint64_t v = 1;
    ssize_t r = ::write(e->wakefd, &v, sizeof(v));
    (void)r;
    if (e->thread_started) pthread_join(e->thread, nullptr);
    // set only after the loop thread is joined (no concurrent reader):
    // the conn_close cascade below must not replay streams onto fresh
    // upstream conns that would then leak
    e->shutting_down = true;
    std::vector<H2Conn*> cs;
    for (auto& kv : e->conns) cs.push_back(kv.second);
    for (H2Conn* c : cs) conn_close(e, c);
    drain_graveyard(e);
    for (int lfd : e->listeners) ::close(lfd);
    for (auto& kv : e->tls_sessions) l5dtls::free_ssl_session(kv.second);
    l5dtls::free_ctx(e->tls_srv);
    l5dtls::free_ctx(e->tls_cli);
    ::close(e->wakefd);
    ::close(e->epfd);
    delete e;
}

}  // extern "C"
