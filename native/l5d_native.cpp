// l5d_native: hot-path codecs for the linkerd_tpu proxy.
//
// The reference offloads its transport hot path to native code (Netty's
// epoll transport + boringssl, project/Deps.scala:24); here the analogous
// hot spots in the asyncio data plane are HPACK Huffman coding (every h2
// header block) and HTTP/1 head parsing (every proxied request). Exposed
// as a plain C ABI consumed via ctypes — no pybind11 dependency.
//
// Build: python native/build.py   (emits linkerd_tpu/native/libl5d_native.so)

#include <cstdint>
#include <cstring>
#include <cstddef>

#include <vector>

#include "huffman_table.h"  // generated from hpack.py: HUFF_CODES/HUFF_BITS
#include "scorer.h"         // in-data-plane anomaly scorer (l5dscore::)
#include "stream_track.h"   // per-stream accumulation (l5dstream::)
#include "tenant_guard.h"   // tenant hashing (l5dtg::)

namespace {

// ---- huffman decode tree (RFC 7541 appendix B) ----------------------------
// Node: children[2] -> index; sym >= 0 at leaves; built once, lazily.
struct Node {
    int32_t child[2];
    int32_t sym;
};

Node g_tree[1024];
int g_tree_size = 0;
bool g_tree_built = false;

int new_node() {
    int i = g_tree_size++;
    g_tree[i].child[0] = g_tree[i].child[1] = -1;
    g_tree[i].sym = -1;
    return i;
}

void build_tree() {
    if (g_tree_built) return;
    g_tree_size = 0;
    new_node();  // root = 0
    for (int sym = 0; sym < 257; sym++) {
        uint32_t code = HUFF_CODES[sym];
        int bits = HUFF_BITS[sym];
        int node = 0;
        for (int b = bits - 1; b >= 0; b--) {
            int bit = (code >> b) & 1;
            if (g_tree[node].child[bit] < 0)
                g_tree[node].child[bit] = new_node();
            node = g_tree[node].child[bit];
        }
        g_tree[node].sym = sym;
    }
    g_tree_built = true;
}

}  // namespace

extern "C" {

// Decode HPACK-huffman `in` into `out` (cap `out_cap`).
// Returns decoded length, -1 on malformed input, -2 if out_cap too small.
long l5d_huffman_decode(const uint8_t* in, size_t in_len,
                        uint8_t* out, size_t out_cap) {
    build_tree();
    size_t out_len = 0;
    int node = 0;
    // RFC 7541 §5.2 padding check, mirroring hpack.py exactly: count ALL
    // bits since the last emitted symbol and whether every one was a 1.
    int pad_bits = 0;
    bool pad_ones = true;
    for (size_t i = 0; i < in_len; i++) {
        uint8_t byte = in[i];
        for (int b = 7; b >= 0; b--) {
            int bit = (byte >> b) & 1;
            pad_bits++;
            pad_ones = pad_ones && bit == 1;
            node = g_tree[node].child[bit];
            if (node < 0) return -1;
            int sym = g_tree[node].sym;
            if (sym >= 0) {
                if (sym == 256) return -1;  // EOS in data is an error
                if (out_len >= out_cap) return -2;
                out[out_len++] = (uint8_t)sym;
                node = 0;
                pad_bits = 0;
                pad_ones = true;
            }
        }
    }
    // leftover bits must be a strict EOS prefix: fewer than 8, all ones
    if (pad_bits >= 8 || !pad_ones) return -1;
    return (long)out_len;
}

// Encode `in` with HPACK huffman. Returns encoded length, -2 if cap small.
long l5d_huffman_encode(const uint8_t* in, size_t in_len,
                        uint8_t* out, size_t out_cap) {
    uint64_t acc = 0;
    int acc_bits = 0;
    size_t out_len = 0;
    for (size_t i = 0; i < in_len; i++) {
        uint32_t code = HUFF_CODES[in[i]];
        int bits = HUFF_BITS[in[i]];
        acc = (acc << bits) | code;
        acc_bits += bits;
        while (acc_bits >= 8) {
            if (out_len >= out_cap) return -2;
            out[out_len++] = (uint8_t)(acc >> (acc_bits - 8));
            acc_bits -= 8;
        }
    }
    if (acc_bits > 0) {
        if (out_len >= out_cap) return -2;
        // pad with EOS prefix (1-bits)
        out[out_len++] = (uint8_t)((acc << (8 - acc_bits))
                                   | ((1u << (8 - acc_bits)) - 1));
    }
    return (long)out_len;
}

// ---- HTTP/1 head parser ----------------------------------------------------
// Parses "METHOD SP URI SP VERSION CRLF (name: value CRLF)* CRLF" from buf.
// Fills `spans` with byte offsets: [m_off,m_len, u_off,u_len, v_off,v_len,
// then per header: n_off,n_len, val_off,val_len ...].
// Returns number of headers (>=0), or -1 malformed, -2 too many headers.
// `len` must be the exact length of the head INCLUDING the final CRLFCRLF
// (caller finds the boundary; asyncio readuntil does this for free).
//
// Strictness matches the pure-Python codec's smuggling defences:
// tokens are line-bounded (no CRLF injection through the URI), control
// characters in the request line are rejected, obs-fold continuation
// lines are rejected, header names must be whitespace/CTL-free, and
// every line obeys the same MAX_LINE as the Python path.

static const size_t MAX_LINE_BYTES = 8 * 1024;  // == codec.MAX_LINE

// whitespace trimmed from header-value edges; matches python str.strip()
// for chars that can appear inside a line (no \r\n by construction)
static inline bool is_ows(char c) {
    return c == ' ' || c == '\t' || c == '\f' || c == '\v';
}

long l5d_parse_http1_head(const char* buf, size_t len,
                          int32_t* spans, size_t max_headers) {
    // request line, bounded by the FIRST newline; lines MUST end CRLF
    // (bare-LF acceptance would make this parser disagree with the
    // pure-Python one — a request-smuggling vector)
    const char* nl = (const char*)memchr(buf, '\n', len);
    if (!nl) return -1;
    size_t rl_end = (size_t)(nl - buf);
    if (rl_end == 0 || buf[rl_end - 1] != '\r') return -1;
    rl_end--;
    if (rl_end > MAX_LINE_BYTES) return -1;
    for (size_t i = 0; i < rl_end; i++)
        if ((uint8_t)buf[i] < 0x20) return -1;  // CTLs incl. \t
    const char* sp1 = (const char*)memchr(buf, ' ', rl_end);
    if (!sp1) return -1;
    size_t m_len = (size_t)(sp1 - buf);
    size_t u_off = m_len + 1;
    const char* sp2 = (const char*)memchr(buf + u_off, ' ', rl_end - u_off);
    if (!sp2) return -1;
    size_t u_len = (size_t)(sp2 - buf) - u_off;
    size_t v_off = u_off + u_len + 1;
    // exactly three tokens: no further space inside the version
    if (memchr(buf + v_off, ' ', rl_end - v_off)) return -1;
    if (m_len == 0 || u_len == 0 || rl_end == v_off) return -1;
    spans[0] = 0; spans[1] = (int32_t)m_len;
    spans[2] = (int32_t)u_off; spans[3] = (int32_t)u_len;
    spans[4] = (int32_t)v_off; spans[5] = (int32_t)(rl_end - v_off);
    size_t pos = (size_t)(nl - buf) + 1;

    size_t n = 0;
    while (pos < len) {
        const char* line_end = (const char*)memchr(buf + pos, '\n',
                                                   len - pos);
        if (!line_end) return -1;  // every line must end CRLF
        size_t end = (size_t)(line_end - buf);
        if (end == pos || buf[end - 1] != '\r') return -1;
        size_t trimmed_end = end - 1;
        if (trimmed_end - pos > MAX_LINE_BYTES) return -1;
        if (trimmed_end == pos) break;  // blank CRLF line: end of head
        // obs-fold continuation lines are a smuggling vector: reject
        if (buf[pos] == ' ' || buf[pos] == '\t') return -1;
        const char* colon = (const char*)memchr(buf + pos, ':',
                                                trimmed_end - pos);
        if (!colon) return -1;
        size_t n_off = pos;
        size_t n_len = (size_t)(colon - buf) - pos;
        if (n_len == 0) return -1;
        // header names: no whitespace or CTLs anywhere
        for (size_t i = n_off; i < n_off + n_len; i++) {
            uint8_t c = (uint8_t)buf[i];
            if (c <= 0x20 || c == 0x7f) return -1;
        }
        size_t val_off = (size_t)(colon - buf) + 1;
        while (val_off < trimmed_end && is_ows(buf[val_off])) val_off++;
        size_t val_end = trimmed_end;
        while (val_end > val_off && is_ows(buf[val_end - 1])) val_end--;
        if (n >= max_headers) return -2;
        spans[6 + n * 4 + 0] = (int32_t)n_off;
        spans[6 + n * 4 + 1] = (int32_t)n_len;
        spans[6 + n * 4 + 2] = (int32_t)val_off;
        spans[6 + n * 4 + 3] = (int32_t)(val_end - val_off);
        n++;
        pos = (size_t)(line_end - buf) + 1;
    }
    return (long)n;
}

// ---- tenant identity -------------------------------------------------------

// FNV-1a 32-bit tenant hash — the exact function both engines apply to
// extracted tenant ids (parity surface for
// linkerd_tpu.router.tenancy.tenant_hash; pinned by the parity test).
unsigned int l5d_tenant_hash(const char* s, size_t n) {
    return l5dtg::tenant_hash(s, n);
}

// ---- in-data-plane scorer: engine-independent eval + slab handles ----------
// The engines embed their own slabs (fp_publish_weights /
// fph2_publish_weights); these entry points exist for the parity tests,
// the hot-swap concurrency tests, and the bench's standalone evaluator
// measurements — same code paths (scorer.h), no engine required.

// The C featurizer's feature width (must equal models.features.FEATURE_DIM;
// pinned by tests/test_native_scorer.py).
int l5d_score_feature_dim() { return l5dscore::FEATURE_DIM; }

// Parse + validate a weight blob (v1 model, v2 specialist bank, or a
// delta patch — discriminated by magic); writes a small JSON
// description. Returns JSON length, or -1 invalid (err text in buffer).
long l5d_score_blob_info(const uint8_t* blob, size_t len, char* out,
                         size_t cap) {
    char err[256];
    if (len >= 8 && memcmp(blob, "L5DWTD01", 8) == 0) {
        l5dscore::Delta d;
        if (!l5dscore::parse_delta_blob(blob, len, &d, err,
                                        sizeof(err))) {
            snprintf(out, cap, "%s", err);
            return -1;
        }
        int n = snprintf(out, cap,
                         "{\"format\":3,\"base_generation\":%u,"
                         "\"new_generation\":%u,\"ops\":%d}",
                         d.base_generation, d.new_generation,
                         (int)d.ops.size());
        return (long)n;
    }
    l5dscore::Bank b;
    if (!l5dscore::parse_bank_blob(blob, len, &b, err, sizeof(err))) {
        snprintf(out, cap, "%s", err);
        return -1;
    }
    const l5dscore::Model& m = b.base;
    const int fmt = (len >= 8 && memcmp(blob, "L5DWTS02", 8) == 0)
                        ? 2 : 1;
    int n = snprintf(out, cap,
                     "{\"format\":%d,\"version\":%u,\"crc\":%u,"
                     "\"quant\":%u,\"in_dim\":%d,\"n_enc\":%d,"
                     "\"n_dec\":%d,\"n_cls\":%d,\"recon_weight\":%.6f,"
                     "\"generation\":%u,\"heads\":%d}",
                     fmt, m.version, m.crc, m.quant, m.in_dim, m.n_enc,
                     m.n_dec, m.n_cls, (double)m.recon_weight,
                     b.generation, (int)b.heads.size());
    return (long)n;
}

// Score n already-featurized rows (x: [n, dim] f32, dim must equal the
// blob's in_dim). Accepts v1 blobs AND v2 banks (scored on the base
// model). Returns n, or -1 on a bad blob / dim mismatch.
long l5d_score_eval(const uint8_t* blob, size_t len, const float* x,
                    long n, long dim, float* out, char* err,
                    size_t errcap) {
    l5dscore::Bank b;
    if (!l5dscore::parse_bank_blob(blob, len, &b, err, errcap))
        return -1;
    const l5dscore::Model& m = b.base;
    if (dim != m.in_dim) {
        l5dscore::fail(err, errcap, "feature dim != blob in_dim");
        return -1;
    }
    for (long i = 0; i < n; i++)
        out[i] = l5dscore::eval_model(m, x + (size_t)i * m.in_dim);
    return n;
}

// Score n featurized rows through the bank's head for `route_hash`
// (base model when the bank carries no such head). `specialist_out`
// (nullable) receives 1 when a head served. The engine-independent
// parity surface for per-route bank selection.
long l5d_score_eval_route(const uint8_t* blob, size_t len,
                          unsigned int route_hash, const float* x,
                          long n, long dim, float* out,
                          int* specialist_out, char* err,
                          size_t errcap) {
    l5dscore::Bank b;
    if (!l5dscore::parse_bank_blob(blob, len, &b, err, errcap))
        return -1;
    if (dim != b.base.in_dim) {
        l5dscore::fail(err, errcap, "feature dim != blob in_dim");
        return -1;
    }
    const l5dscore::Model* head = b.select(route_hash);
    const l5dscore::Model& m = head != nullptr ? *head : b.base;
    if (specialist_out != nullptr)
        *specialist_out = head != nullptr ? 1 : 0;
    for (long i = 0; i < n; i++)
        out[i] = l5dscore::eval_model(m, x + (size_t)i * m.in_dim);
    return n;
}

// Score n RAW engine rows ([n, 12] f32 FeatureRow layout; only columns
// 1..4 are read) through the in-engine featurizer: per-row dst-hash
// (cols/signs) and pre-update drift come from the caller, so tests can
// drive the exact per-route state the engines hold. feat_out (nullable,
// [n, FEATURE_DIM]) receives the encoded features for parity checks.
long l5d_score_eval_raw(const uint8_t* blob, size_t len,
                        const float* rows, long n, const int32_t* cols,
                        const float* signs, const float* drifts,
                        float* scores_out, float* feat_out, char* err,
                        size_t errcap) {
    l5dscore::Model m;
    if (!l5dscore::parse_blob(blob, len, &m, err, errcap)) return -1;
    if (m.in_dim != l5dscore::FEATURE_DIM) {
        l5dscore::fail(err, errcap, "blob in_dim != FEATURE_DIM");
        return -1;
    }
    float feats[l5dscore::FEATURE_DIM];
    for (long i = 0; i < n; i++) {
        const float* r = rows + (size_t)i * 12;
        l5dscore::featurize(r[1], (int)r[2], r[3], r[4], cols[i],
                            signs[i], drifts[i], feats);
        if (feat_out != nullptr)
            memcpy(feat_out + (size_t)i * l5dscore::FEATURE_DIM, feats,
                   sizeof(feats));
        scores_out[i] = l5dscore::eval_model(m, feats);
    }
    return n;
}

// Standalone slab handle: the hot-swap machinery without an engine.
void* l5d_slab_create() { return new l5dscore::Slab(); }

int l5d_slab_publish(void* slab, const uint8_t* blob, size_t len,
                     char* err, size_t errcap) {
    l5dscore::Bank b;
    if (!l5dscore::parse_bank_blob(blob, len, &b, err, errcap))
        return -1;
    // l5d_slab_score strides rows by FEATURE_DIM, so (like the
    // engines' publish) a valid blob with any other in_dim must be
    // rejected here — not read out of bounds at eval time
    if (b.base.in_dim != l5dscore::FEATURE_DIM) {
        l5dscore::fail(err, errcap,
                       "weight blob in_dim does not match featurizer "
                       "FEATURE_DIM");
        return -1;
    }
    l5dscore::slab_install((l5dscore::Slab*)slab, std::move(b));
    return 0;
}

// Apply a per-route delta patch to the slab's ACTIVE bank (same
// double-buffered reader-recheck discipline as a full publish; one
// flip covers every attached engine/worker). Rejected on a parse
// failure, a generation-fence mismatch, or a remove of an absent head.
int l5d_slab_publish_delta(void* slab, const uint8_t* blob, size_t len,
                           char* err, size_t errcap) {
    l5dscore::Delta d;
    if (!l5dscore::parse_delta_blob(blob, len, &d, err, errcap))
        return -1;
    if (!l5dscore::slab_apply_delta((l5dscore::Slab*)slab, d, err,
                                    errcap))
        return -1;
    return 0;
}

// Score n featurized rows via the slab; -1 = no weights published.
long l5d_slab_score(void* slab, const float* x, long n, float* out) {
    l5dscore::Slab* s = (l5dscore::Slab*)slab;
    for (long i = 0; i < n; i++) {
        if (!l5dscore::slab_score(
                s, x + (size_t)i * l5dscore::FEATURE_DIM, out + i))
            return -1;
    }
    return n;
}

// Score n featurized rows via the slab with per-route head selection;
// `specialist_out` (nullable, [n]) gets 1 where a head served. -1 = no
// weights published.
long l5d_slab_score_route(void* slab, unsigned int route_hash,
                          const float* x, long n, float* out,
                          int* specialist_out) {
    l5dscore::Slab* s = (l5dscore::Slab*)slab;
    for (long i = 0; i < n; i++) {
        const int rc = l5dscore::slab_score_route(
            s, route_hash, true, x + (size_t)i * l5dscore::FEATURE_DIM,
            out + i);
        if (rc < 0) return -1;
        if (specialist_out != nullptr) specialist_out[i] = rc;
    }
    return n;
}

long l5d_slab_stats(void* slab, char* out, size_t cap) {
    l5dscore::Slab* s = (l5dscore::Slab*)slab;
    int n = snprintf(out, cap,
                     "{\"version\":%u,\"crc\":%u,\"generation\":%u,"
                     "\"heads\":%u,\"swaps\":%llu,\"delta_swaps\":%llu,"
                     "\"retries\":%llu}",
                     s->version.load(std::memory_order_relaxed),
                     s->crc.load(std::memory_order_relaxed),
                     s->generation.load(std::memory_order_relaxed),
                     s->n_heads.load(std::memory_order_relaxed),
                     (unsigned long long)s->swaps.load(
                         std::memory_order_relaxed),
                     (unsigned long long)s->delta_swaps.load(
                         std::memory_order_relaxed),
                     (unsigned long long)s->retries.load(
                         std::memory_order_relaxed));
    return (long)n;
}

void l5d_slab_free(void* slab) { delete (l5dscore::Slab*)slab; }

// Deterministic valid test blob (the stress drivers' generator, exposed
// so tests can exercise publish/score without a JAX-side export).
long l5d_score_test_blob(uint8_t* out, size_t cap, uint32_t version,
                         int quant, uint32_t seed) {
    std::vector<uint8_t> v;
    l5dscore::build_test_blob(&v, version, quant, seed);
    if (v.size() > cap) return -2;
    memcpy(out, v.data(), v.size());
    return (long)v.size();
}

// Deterministic v2 bank blob: seeded base + n_heads specialists keyed
// 1000+k (the heads' route hashes, ascending).
long l5d_score_test_bank(uint8_t* out, size_t cap, uint32_t generation,
                         int quant, uint32_t seed, uint32_t n_heads) {
    std::vector<uint8_t> v;
    l5dscore::build_test_bank_blob(&v, generation, quant, seed, n_heads);
    if (v.size() > cap) return -2;
    memcpy(out, v.data(), v.size());
    return (long)v.size();
}

// Drive l5dstream::accum_frame over a frame trace — the parity anchor
// for linkerd_tpu.streams.tracker.StreamTracker (the Python h2 path
// must reproduce the engines' per-frame float32 arithmetic
// bit-for-bit, like the featurizer parity test). kinds[i] is
// FRAME_DATA/FRAME_WINDOW_UPDATE/FRAME_ANOMALY, gaps_ms/sizes the
// per-frame inter-arrival gap and DATA payload size. out must hold 9
// floats: [gap_ewma_ms, gap_dev_ms, bpf_ewma, bpf_dev, frames,
// data_frames, wu_frames, anomalies, bytes].
long l5d_stream_accum(const int* kinds, const float* gaps_ms,
                      const float* sizes, long n, float* out) {
    if (n < 0) return -1;
    l5dstream::StreamAccum a;
    for (long i = 0; i < n; i++) {
        if (kinds[i] < 0 || kinds[i] > 2) return -1;
        l5dstream::accum_frame(&a, kinds[i], gaps_ms[i], sizes[i]);
    }
    out[0] = a.gap_ewma_ms;
    out[1] = a.gap_dev_ms;
    out[2] = a.bpf_ewma;
    out[3] = a.bpf_dev;
    out[4] = (float)a.frames;
    out[5] = (float)a.data_frames;
    out[6] = (float)a.wu_frames;
    out[7] = (float)a.anomalies;
    out[8] = (float)a.bytes;
    return 0;
}

// Deterministic delta patch: one seeded upsert (or remove) at
// route_hash, fenced on base_gen -> new_gen.
long l5d_score_test_delta(uint8_t* out, size_t cap, uint32_t base_gen,
                          uint32_t new_gen, uint32_t route_hash,
                          int quant, uint32_t seed, int remove) {
    std::vector<uint8_t> v;
    l5dscore::build_test_delta_blob(&v, base_gen, new_gen, route_hash,
                                    quant, seed, remove != 0);
    if (v.size() > cap) return -2;
    memcpy(out, v.data(), v.size());
    return (long)v.size();
}

}  // extern "C"
