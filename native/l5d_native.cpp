// l5d_native: hot-path codecs for the linkerd_tpu proxy.
//
// The reference offloads its transport hot path to native code (Netty's
// epoll transport + boringssl, project/Deps.scala:24); here the analogous
// hot spots in the asyncio data plane are HPACK Huffman coding (every h2
// header block) and HTTP/1 head parsing (every proxied request). Exposed
// as a plain C ABI consumed via ctypes — no pybind11 dependency.
//
// Build: python native/build.py   (emits linkerd_tpu/native/libl5d_native.so)

#include <cstdint>
#include <cstring>
#include <cstddef>

#include "huffman_table.h"  // generated from hpack.py: HUFF_CODES/HUFF_BITS

namespace {

// ---- huffman decode tree (RFC 7541 appendix B) ----------------------------
// Node: children[2] -> index; sym >= 0 at leaves; built once, lazily.
struct Node {
    int32_t child[2];
    int32_t sym;
};

Node g_tree[1024];
int g_tree_size = 0;
bool g_tree_built = false;

int new_node() {
    int i = g_tree_size++;
    g_tree[i].child[0] = g_tree[i].child[1] = -1;
    g_tree[i].sym = -1;
    return i;
}

void build_tree() {
    if (g_tree_built) return;
    g_tree_size = 0;
    new_node();  // root = 0
    for (int sym = 0; sym < 257; sym++) {
        uint32_t code = HUFF_CODES[sym];
        int bits = HUFF_BITS[sym];
        int node = 0;
        for (int b = bits - 1; b >= 0; b--) {
            int bit = (code >> b) & 1;
            if (g_tree[node].child[bit] < 0)
                g_tree[node].child[bit] = new_node();
            node = g_tree[node].child[bit];
        }
        g_tree[node].sym = sym;
    }
    g_tree_built = true;
}

}  // namespace

extern "C" {

// Decode HPACK-huffman `in` into `out` (cap `out_cap`).
// Returns decoded length, -1 on malformed input, -2 if out_cap too small.
long l5d_huffman_decode(const uint8_t* in, size_t in_len,
                        uint8_t* out, size_t out_cap) {
    build_tree();
    size_t out_len = 0;
    int node = 0;
    // RFC 7541 §5.2 padding check, mirroring hpack.py exactly: count ALL
    // bits since the last emitted symbol and whether every one was a 1.
    int pad_bits = 0;
    bool pad_ones = true;
    for (size_t i = 0; i < in_len; i++) {
        uint8_t byte = in[i];
        for (int b = 7; b >= 0; b--) {
            int bit = (byte >> b) & 1;
            pad_bits++;
            pad_ones = pad_ones && bit == 1;
            node = g_tree[node].child[bit];
            if (node < 0) return -1;
            int sym = g_tree[node].sym;
            if (sym >= 0) {
                if (sym == 256) return -1;  // EOS in data is an error
                if (out_len >= out_cap) return -2;
                out[out_len++] = (uint8_t)sym;
                node = 0;
                pad_bits = 0;
                pad_ones = true;
            }
        }
    }
    // leftover bits must be a strict EOS prefix: fewer than 8, all ones
    if (pad_bits >= 8 || !pad_ones) return -1;
    return (long)out_len;
}

// Encode `in` with HPACK huffman. Returns encoded length, -2 if cap small.
long l5d_huffman_encode(const uint8_t* in, size_t in_len,
                        uint8_t* out, size_t out_cap) {
    uint64_t acc = 0;
    int acc_bits = 0;
    size_t out_len = 0;
    for (size_t i = 0; i < in_len; i++) {
        uint32_t code = HUFF_CODES[in[i]];
        int bits = HUFF_BITS[in[i]];
        acc = (acc << bits) | code;
        acc_bits += bits;
        while (acc_bits >= 8) {
            if (out_len >= out_cap) return -2;
            out[out_len++] = (uint8_t)(acc >> (acc_bits - 8));
            acc_bits -= 8;
        }
    }
    if (acc_bits > 0) {
        if (out_len >= out_cap) return -2;
        // pad with EOS prefix (1-bits)
        out[out_len++] = (uint8_t)((acc << (8 - acc_bits))
                                   | ((1u << (8 - acc_bits)) - 1));
    }
    return (long)out_len;
}

// ---- HTTP/1 head parser ----------------------------------------------------
// Parses "METHOD SP URI SP VERSION CRLF (name: value CRLF)* CRLF" from buf.
// Fills `spans` with byte offsets: [m_off,m_len, u_off,u_len, v_off,v_len,
// then per header: n_off,n_len, val_off,val_len ...].
// Returns number of headers (>=0), or -1 malformed, -2 too many headers.
// `len` must be the exact length of the head INCLUDING the final CRLFCRLF
// (caller finds the boundary; asyncio readuntil does this for free).
//
// Strictness matches the pure-Python codec's smuggling defences:
// tokens are line-bounded (no CRLF injection through the URI), control
// characters in the request line are rejected, obs-fold continuation
// lines are rejected, header names must be whitespace/CTL-free, and
// every line obeys the same MAX_LINE as the Python path.

static const size_t MAX_LINE_BYTES = 8 * 1024;  // == codec.MAX_LINE

// whitespace trimmed from header-value edges; matches python str.strip()
// for chars that can appear inside a line (no \r\n by construction)
static inline bool is_ows(char c) {
    return c == ' ' || c == '\t' || c == '\f' || c == '\v';
}

long l5d_parse_http1_head(const char* buf, size_t len,
                          int32_t* spans, size_t max_headers) {
    // request line, bounded by the FIRST newline; lines MUST end CRLF
    // (bare-LF acceptance would make this parser disagree with the
    // pure-Python one — a request-smuggling vector)
    const char* nl = (const char*)memchr(buf, '\n', len);
    if (!nl) return -1;
    size_t rl_end = (size_t)(nl - buf);
    if (rl_end == 0 || buf[rl_end - 1] != '\r') return -1;
    rl_end--;
    if (rl_end > MAX_LINE_BYTES) return -1;
    for (size_t i = 0; i < rl_end; i++)
        if ((uint8_t)buf[i] < 0x20) return -1;  // CTLs incl. \t
    const char* sp1 = (const char*)memchr(buf, ' ', rl_end);
    if (!sp1) return -1;
    size_t m_len = (size_t)(sp1 - buf);
    size_t u_off = m_len + 1;
    const char* sp2 = (const char*)memchr(buf + u_off, ' ', rl_end - u_off);
    if (!sp2) return -1;
    size_t u_len = (size_t)(sp2 - buf) - u_off;
    size_t v_off = u_off + u_len + 1;
    // exactly three tokens: no further space inside the version
    if (memchr(buf + v_off, ' ', rl_end - v_off)) return -1;
    if (m_len == 0 || u_len == 0 || rl_end == v_off) return -1;
    spans[0] = 0; spans[1] = (int32_t)m_len;
    spans[2] = (int32_t)u_off; spans[3] = (int32_t)u_len;
    spans[4] = (int32_t)v_off; spans[5] = (int32_t)(rl_end - v_off);
    size_t pos = (size_t)(nl - buf) + 1;

    size_t n = 0;
    while (pos < len) {
        const char* line_end = (const char*)memchr(buf + pos, '\n',
                                                   len - pos);
        if (!line_end) return -1;  // every line must end CRLF
        size_t end = (size_t)(line_end - buf);
        if (end == pos || buf[end - 1] != '\r') return -1;
        size_t trimmed_end = end - 1;
        if (trimmed_end - pos > MAX_LINE_BYTES) return -1;
        if (trimmed_end == pos) break;  // blank CRLF line: end of head
        // obs-fold continuation lines are a smuggling vector: reject
        if (buf[pos] == ' ' || buf[pos] == '\t') return -1;
        const char* colon = (const char*)memchr(buf + pos, ':',
                                                trimmed_end - pos);
        if (!colon) return -1;
        size_t n_off = pos;
        size_t n_len = (size_t)(colon - buf) - pos;
        if (n_len == 0) return -1;
        // header names: no whitespace or CTLs anywhere
        for (size_t i = n_off; i < n_off + n_len; i++) {
            uint8_t c = (uint8_t)buf[i];
            if (c <= 0x20 || c == 0x7f) return -1;
        }
        size_t val_off = (size_t)(colon - buf) + 1;
        while (val_off < trimmed_end && is_ows(buf[val_off])) val_off++;
        size_t val_end = trimmed_end;
        while (val_end > val_off && is_ows(buf[val_end - 1])) val_end--;
        if (n >= max_headers) return -2;
        spans[6 + n * 4 + 0] = (int32_t)n_off;
        spans[6 + n * 4 + 1] = (int32_t)n_len;
        spans[6 + n * 4 + 2] = (int32_t)val_off;
        spans[6 + n * 4 + 3] = (int32_t)(val_end - val_off);
        n++;
        pos = (size_t)(line_end - buf) + 1;
    }
    return (long)n;
}

}  // extern "C"
