// h2bench: out-of-process gRPC echo server and closed-loop load
// generator for benchmarking the h2 data plane (BASELINE config 2).
//
// The wrk/nginx analog for gRPC: the router under test sits between
// `h2bench serve` (echo backend) and `h2bench load` (fixed-concurrency
// closed-loop client), so the bench measures the ROUTER's saturation,
// not a Python client/server stack self-measured in-process (round-3
// VERDICT weak #6). Reuses the proxy's frame + HPACK codec (h2_core.h).
//
// Usage:
//   h2bench serve <port>
//   h2bench load <ip> <port> <authority> <concurrency> <seconds> [paysz]
// Both print one JSON line on exit (serve: on SIGTERM/SIGINT).

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <algorithm>
#include <deque>
#include <atomic>
#include <string>
#include <unordered_map>
#include <vector>

#include "h2_core.h"
#include "tls_shim.h"

namespace h2bench {

using h2::Hdr;

std::atomic<int> g_stop{0};
void on_sig(int) { g_stop.store(1, std::memory_order_relaxed); }

uint64_t now_us() {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1'000'000 + ts.tv_nsec / 1000;
}

void set_nodelay(int fd) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

constexpr int64_t BIG_WIN = 64 << 20;

struct Conn {
    int fd = -1;
    std::string in, out;
    h2::Session s;
    bool want_write = false;
    // TLS client leg (h1loadtls / loadtls): c->out holds plaintext,
    // cipher_out is what actually hits the socket
    l5dtls::Sess* tls = nullptr;
    std::string cipher_out;
    // serve: per-stream request byte accumulation
    std::unordered_map<uint32_t, std::string> req_data;
    // load: streams in flight + completion accounting
    std::unordered_map<uint32_t, uint64_t> start_us;
    uint32_t next_id = 1;
    uint64_t recv_since_grant = 0;
};

// Shared TLS client context for the load modes. Validation is off: the
// bench measures throughput against a self-signed fixture, and the
// router under test never requests a client cert.
l5dtls::Ctx* g_tls_client = nullptr;

bool tls_client_init(const char* alpn_csv) {
    if (!l5dtls::available()) {
        fprintf(stderr, "h2bench: TLS runtime unavailable: %s\n",
                l5dtls::load_error());
        return false;
    }
    std::string err;
    g_tls_client = l5dtls::client_ctx(alpn_csv, /*verify=*/false,
                                      nullptr, &err);
    if (g_tls_client == nullptr) {
        fprintf(stderr, "h2bench: client ctx: %s\n", err.c_str());
        return false;
    }
    return true;
}

// Encrypt whatever plaintext is queued (a no-op while the handshake is
// in flight — write_plain drives it) and push ciphertext to the socket.
// Returns false on a dead connection.
bool tls_flush_bytes(int fd, l5dtls::Sess* t, std::string* plain_out,
                     std::string* cipher_out) {
    if (!plain_out->empty()) {
        long n = l5dtls::write_plain(t, plain_out->data(),
                                     plain_out->size(), cipher_out);
        if (n < 0) return false;
        if (n > 0) plain_out->erase(0, (size_t)n);
    }
    while (!cipher_out->empty()) {
        ssize_t n = ::send(fd, cipher_out->data(), cipher_out->size(),
                           MSG_NOSIGNAL);
        if (n > 0) cipher_out->erase(0, (size_t)n);
        else if (n < 0 && errno == EINTR)
            continue;  // signal during send: retry
        else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK))
            break;
        else
            return false;
    }
    return true;
}

bool flush_conn(int epfd, Conn* c) {
    if (c->tls != nullptr) {
        if (!tls_flush_bytes(c->fd, c->tls, &c->out, &c->cipher_out))
            return false;
        // EPOLLOUT only while ciphertext is stuck in the socket buffer;
        // plaintext blocked on the handshake drains via EPOLLIN pumps
        bool ww = !c->cipher_out.empty();
        if (ww != c->want_write) {
            c->want_write = ww;
            epoll_event ev{};
            ev.events = EPOLLIN | (ww ? EPOLLOUT : 0);
            ev.data.fd = c->fd;
            epoll_ctl(epfd, EPOLL_CTL_MOD, c->fd, &ev);
        }
        return true;
    }
    while (!c->out.empty()) {
        ssize_t n = ::send(c->fd, c->out.data(), c->out.size(),
                           MSG_NOSIGNAL);
        if (n > 0) {
            c->out.erase(0, (size_t)n);
        } else if (n < 0 && errno == EINTR) {
            continue;  // signal during send: retry
        } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
            break;
        } else {
            return false;
        }
    }
    bool ww = !c->out.empty();
    if (ww != c->want_write) {
        c->want_write = ww;
        epoll_event ev{};
        ev.events = EPOLLIN | (ww ? EPOLLOUT : 0);
        ev.data.fd = c->fd;
        epoll_ctl(epfd, EPOLL_CTL_MOD, c->fd, &ev);
    }
    return true;
}

void conn_grant(Conn* c) {
    if (c->recv_since_grant > (1 << 20)) {
        h2::write_window_update(&c->out, 0,
                                (uint32_t)c->recv_since_grant);
        c->recv_since_grant = 0;
    }
}

// ---------------- serve mode ----------------

struct ServeStats {
    uint64_t requests = 0, conns = 0;
};

// gRPC-shaped echo: 200 headers, DATA = the request bytes verbatim
// (already a gRPC-framed message), then grpc-status 0 trailers.
void serve_respond(Conn* c, uint32_t sid, const std::string& body) {
    std::string block;
    c->s.enc.encode({{":status", "200"},
                     {"content-type", "application/grpc"}},
                    &block);
    h2::write_frame(&c->out, h2::HEADERS, h2::FLAG_END_HEADERS, sid,
                    block.data(), block.size());
    size_t off = 0;
    do {
        size_t n = std::min(body.size() - off,
                            (size_t)c->s.peer_max_frame);
        h2::write_frame(&c->out, h2::DATA, 0, sid, body.data() + off, n);
        off += n;
    } while (off < body.size());
    block.clear();
    c->s.enc.encode({{"grpc-status", "0"}}, &block);
    h2::write_frame(&c->out, h2::HEADERS,
                    h2::FLAG_END_HEADERS | h2::FLAG_END_STREAM, sid,
                    block.data(), block.size());
}

void serve_handle_frame(Conn* c, uint8_t type, uint8_t flags, uint32_t sid,
                        const uint8_t* p, size_t len, ServeStats* stats) {
    switch (type) {
    case h2::HEADERS: {
        size_t off, n;
        if (h2::strip_payload(flags, true, p, len, &off, &n)) return;
        std::vector<Hdr> hs;
        c->s.dec.decode(p + off, n, &hs);  // keep HPACK state in sync
        c->req_data[sid];                  // open the stream
        if (flags & h2::FLAG_END_STREAM) {
            // no body: echo empty
            stats->requests++;
            serve_respond(c, sid, std::string());
            c->req_data.erase(sid);
        }
        break;
    }
    case h2::DATA: {
        c->s.recv_unacked += len;
        c->recv_since_grant += len;
        auto it = c->req_data.find(sid);
        if (it != c->req_data.end())
            it->second.append((const char*)p, len);
        conn_grant(c);
        if (flags & h2::FLAG_END_STREAM && it != c->req_data.end()) {
            stats->requests++;
            serve_respond(c, sid, it->second);
            c->req_data.erase(it);
        }
        break;
    }
    case h2::SETTINGS:
        if (!(flags & h2::FLAG_ACK)) {
            for (size_t o = 0; o + 6 <= len; o += 6) {
                uint16_t id = (uint16_t)((p[o] << 8) | p[o + 1]);
                uint32_t v = h2::get_u32(p + o + 2);
                if (id == h2::S_HEADER_TABLE_SIZE)
                    c->s.enc.set_max_table_size(v);
                else if (id == h2::S_MAX_FRAME_SIZE && v >= 16384)
                    c->s.peer_max_frame = v;
            }
            h2::write_settings(&c->out, {}, true);
        }
        break;
    case h2::PING:
        if (!(flags & h2::FLAG_ACK) && len == 8)
            h2::write_frame(&c->out, h2::PING, h2::FLAG_ACK, 0,
                            (const char*)p, 8);
        break;
    case h2::RST_STREAM:
        c->req_data.erase(sid);
        break;
    default:
        break;  // WINDOW_UPDATE/GOAWAY/PRIORITY: windows are huge, ignore
    }
}

int run_serve(int port, std::atomic<int>* bound_out) {
    int lfd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    int one = 1;
    setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    sa.sin_port = htons((uint16_t)port);
    if (bind(lfd, (sockaddr*)&sa, sizeof(sa)) < 0 ||
        listen(lfd, 1024) < 0) {
        perror("bind");
        ::close(lfd);
        return 1;
    }
    socklen_t sl = sizeof(sa);
    getsockname(lfd, (sockaddr*)&sa, &sl);
    if (bound_out != nullptr)
        bound_out->store((int)ntohs(sa.sin_port));
    printf("{\"listening\": %d}\n", ntohs(sa.sin_port));
    fflush(stdout);

    int epfd = epoll_create1(0);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = lfd;
    epoll_ctl(epfd, EPOLL_CTL_ADD, lfd, &ev);
    // l5d: ignore[bounded-table] — keyed by OUR accept4 fds, not peer input; population = live conns, bounded by the process fd limit
    std::unordered_map<int, Conn*> conns;
    ServeStats stats;
    epoll_event evs[128];
    while (!g_stop.load(std::memory_order_relaxed)) {
        int n = epoll_wait(epfd, evs, 128, 200);
        for (int i = 0; i < n; i++) {
            int fd = evs[i].data.fd;
            if (fd == lfd) {
                for (;;) {
                    int cfd = ::accept4(lfd, nullptr, nullptr,
                                        SOCK_NONBLOCK);
                    if (cfd < 0) {
                        if (errno == EINTR) continue;
                        break;
                    }
                    set_nodelay(cfd);
                    Conn* c = new Conn();
                    c->fd = cfd;
                    h2::write_settings(
                        &c->out,
                        {{h2::S_INITIAL_WINDOW_SIZE, (uint32_t)BIG_WIN},
                         {h2::S_MAX_FRAME_SIZE, 16384}},
                        false);
                    h2::write_window_update(
                        &c->out, 0,
                        (uint32_t)(BIG_WIN - h2::DEFAULT_WINDOW));
                    epoll_event e2{};
                    e2.events = EPOLLIN;
                    e2.data.fd = cfd;
                    epoll_ctl(epfd, EPOLL_CTL_ADD, cfd, &e2);
                    conns[cfd] = c;
                    stats.conns++;
                    flush_conn(epfd, c);
                }
                continue;
            }
            auto it = conns.find(fd);
            if (it == conns.end()) continue;
            Conn* c = it->second;
            bool dead = false;
            if (evs[i].events & (EPOLLHUP | EPOLLERR)) dead = true;
            if (!dead && (evs[i].events & EPOLLOUT))
                dead = !flush_conn(epfd, c);
            if (!dead && (evs[i].events & EPOLLIN)) {
                char buf[64 * 1024];
                for (;;) {
                    ssize_t r = ::recv(fd, buf, sizeof(buf), 0);
                    if (r > 0) {
                        c->in.append(buf, (size_t)r);
                    } else if (r < 0 && errno == EINTR) {
                        continue;
                    } else if (r < 0 && (errno == EAGAIN ||
                                         errno == EWOULDBLOCK)) {
                        break;
                    } else {
                        dead = true;
                        break;
                    }
                }
                if (!dead) {
                    size_t pos = 0;
                    if (!c->s.preface_seen) {
                        if (c->in.size() < h2::PREFACE_LEN) continue;
                        if (memcmp(c->in.data(), h2::PREFACE,
                                   h2::PREFACE_LEN) != 0) {
                            dead = true;
                        } else {
                            c->s.preface_seen = true;
                            pos = h2::PREFACE_LEN;
                        }
                    }
                    while (!dead && c->in.size() - pos >= 9) {
                        const uint8_t* h =
                            (const uint8_t*)c->in.data() + pos;
                        uint32_t len = ((uint32_t)h[0] << 16) |
                                       ((uint32_t)h[1] << 8) | h[2];
                        if (c->in.size() - pos < 9 + (size_t)len) break;
                        serve_handle_frame(
                            c, h[3], h[4],
                            h2::get_u32(h + 5) & 0x7FFFFFFF, h + 9, len,
                            &stats);
                        pos += 9 + (size_t)len;
                    }
                    if (pos) c->in.erase(0, pos);
                    if (!dead) dead = !flush_conn(epfd, c);
                }
            }
            if (dead) {
                epoll_ctl(epfd, EPOLL_CTL_DEL, fd, nullptr);
                ::close(fd);
                delete c;
                conns.erase(it);
            }
        }
    }
    fprintf(stderr,
            "{\"served\": %llu, \"conns\": %llu}\n",
            (unsigned long long)stats.requests,
            (unsigned long long)stats.conns);
    for (auto& kv : conns) {
        ::close(kv.first);
        delete kv.second;
    }
    ::close(lfd);
    ::close(epfd);
    return 0;
}

// ---------------- load mode ----------------

struct LoadState {
    std::string req_block_tail;  // DATA payload (gRPC-framed message)
    std::vector<Hdr> req_hdrs;
    uint64_t done = 0, errors = 0;
    std::vector<uint32_t> lat_us;
    uint64_t deadline_us = 0;
    int inflight_target = 0;
    int inflight = 0;
    // open-loop pacing (rps > 0): launch on the clock, not on completion
    bool paced = false;
    uint64_t interval_us = 0;
    uint64_t next_due_us = 0;
};

void launch_one(Conn* c, LoadState* ls) {
    uint32_t sid = c->next_id;
    c->next_id += 2;
    std::string block;
    c->s.enc.encode(ls->req_hdrs, &block);
    h2::write_frame(&c->out, h2::HEADERS, h2::FLAG_END_HEADERS, sid,
                    block.data(), block.size());
    h2::write_frame(&c->out, h2::DATA, h2::FLAG_END_STREAM, sid,
                    ls->req_block_tail.data(),
                    ls->req_block_tail.size());
    c->start_us[sid] = now_us();
    ls->inflight++;
}

void load_launch(Conn* c, LoadState* ls) {
    if (ls->paced) return;  // paced mode launches on the clock instead
    while (ls->inflight < ls->inflight_target &&
           now_us() < ls->deadline_us)
        launch_one(c, ls);
}

void load_handle_frame(Conn* c, LoadState* ls, uint8_t type, uint8_t flags,
                       uint32_t sid, const uint8_t* p, size_t len) {
    switch (type) {
    case h2::HEADERS: {
        size_t off, n;
        if (h2::strip_payload(flags, true, p, len, &off, &n)) return;
        std::vector<Hdr> hs;
        c->s.dec.decode(p + off, n, &hs);
        if (flags & h2::FLAG_END_STREAM) {
            auto it = c->start_us.find(sid);
            if (it != c->start_us.end()) {
                bool ok = true;
                for (auto& h : hs)
                    if (h.first == ":status" && h.second != "200")
                        ok = false;
                    else if (h.first == "grpc-status" && h.second != "0")
                        ok = false;
                if (ok) {
                    ls->done++;
                    if (ls->lat_us.size() < 2'000'000)
                        ls->lat_us.push_back(
                            (uint32_t)(now_us() - it->second));
                } else {
                    ls->errors++;
                }
                c->start_us.erase(it);
                ls->inflight--;
                load_launch(c, ls);
            }
        }
        break;
    }
    case h2::DATA:
        c->s.recv_unacked += len;
        c->recv_since_grant += len;
        conn_grant(c);
        if (flags & h2::FLAG_END_STREAM) {
            // stream ended on DATA (non-gRPC shape); count as done
            auto it = c->start_us.find(sid);
            if (it != c->start_us.end()) {
                ls->done++;
                c->start_us.erase(it);
                ls->inflight--;
                load_launch(c, ls);
            }
        }
        break;
    case h2::SETTINGS:
        if (!(flags & h2::FLAG_ACK)) {
            for (size_t o = 0; o + 6 <= len; o += 6) {
                uint16_t id = (uint16_t)((p[o] << 8) | p[o + 1]);
                uint32_t v = h2::get_u32(p + o + 2);
                if (id == h2::S_HEADER_TABLE_SIZE)
                    c->s.enc.set_max_table_size(v);
                else if (id == h2::S_MAX_FRAME_SIZE && v >= 16384)
                    c->s.peer_max_frame = v;
            }
            h2::write_settings(&c->out, {}, true);
        }
        break;
    case h2::PING:
        if (!(flags & h2::FLAG_ACK) && len == 8)
            h2::write_frame(&c->out, h2::PING, h2::FLAG_ACK, 0,
                            (const char*)p, 8);
        break;
    case h2::RST_STREAM: {
        auto it = c->start_us.find(sid);
        if (it != c->start_us.end()) {
            ls->errors++;
            c->start_us.erase(it);
            ls->inflight--;
            load_launch(c, ls);
        }
        break;
    }
    default:
        break;
    }
}

int run_load(const char* ip, int port, const char* authority, int conc,
             double seconds, int paysz, double rate_rps,
             uint64_t* done_out, bool tls = false,
             int nconns_override = 0) {
    if (tls && g_tls_client == nullptr && !tls_client_init("h2"))
        return 1;
    // gRPC-framed echo message: 5-byte prefix + protobuf bytes field
    std::string msg;
    msg.push_back(0x0A);  // field 1, wire type 2
    // varint length
    {
        unsigned v = (unsigned)paysz;
        while (v >= 128) {
            msg.push_back((char)((v & 0x7F) | 0x80));
            v >>= 7;
        }
        msg.push_back((char)v);
    }
    msg.append((size_t)paysz, 'x');
    std::string framed;
    framed.push_back(0);
    h2::put_u32(&framed, (uint32_t)msg.size());
    framed += msg;

    // --conns-per-worker spread: against an SO_REUSEPORT-sharded
    // proxy the kernel balances per CONNECTION, so a loadgen that
    // opens few fat conns can serialize onto one accept socket; the
    // override forces enough conns to cover every worker
    int nconns = nconns_override > 0 ? nconns_override
                                     : std::max(1, conc / 16);
    int per_conn = std::max(1, conc / nconns);

    int epfd = epoll_create1(0);
    // l5d: ignore[bounded-table] — keyed by our own connect() fds; exactly nconns entries, from the -c flag, not peer input
    std::unordered_map<int, Conn*> conns;
    std::vector<LoadState> states((size_t)nconns);
    // l5d: ignore[bounded-table] — parallel to conns above: nconns entries keyed by our own fds
    std::unordered_map<int, size_t> conn_state;
    uint64_t deadline = now_us() + (uint64_t)(seconds * 1e6);

    for (int i = 0; i < nconns; i++) {
        int fd = ::socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in sa{};
        sa.sin_family = AF_INET;
        sa.sin_port = htons((uint16_t)port);
        inet_pton(AF_INET, ip, &sa.sin_addr);
        if (::connect(fd, (sockaddr*)&sa, sizeof(sa)) < 0) {
            perror("connect");
            ::close(fd);
            ::close(epfd);
            for (auto& kv : conns) {
                ::close(kv.first);
                l5dtls::free_session(kv.second->tls);
                delete kv.second;
            }
            return 1;
        }
        set_nodelay(fd);
        // switch to nonblocking after connect
        int fl = fcntl(fd, F_GETFL, 0);
        fcntl(fd, F_SETFL, fl | O_NONBLOCK);
        Conn* c = new Conn();
        c->fd = fd;
        if (tls) {
            c->tls = l5dtls::new_session(g_tls_client, authority,
                                         /*verify=*/false, nullptr);
            if (c->tls == nullptr) {
                fprintf(stderr, "h2bench: TLS session alloc failed\n");
                ::close(fd);
                delete c;
                ::close(epfd);
                for (auto& kv : conns) {
                    ::close(kv.first);
                    l5dtls::free_session(kv.second->tls);
                    delete kv.second;
                }
                return 1;
            }
        }
        c->out.append(h2::PREFACE, h2::PREFACE_LEN);
        h2::write_settings(&c->out,
                           {{h2::S_INITIAL_WINDOW_SIZE, (uint32_t)BIG_WIN},
                            {h2::S_MAX_FRAME_SIZE, 16384}},
                           false);
        h2::write_window_update(&c->out, 0,
                                (uint32_t)(BIG_WIN - h2::DEFAULT_WINDOW));
        LoadState& ls = states[(size_t)i];
        ls.req_block_tail = framed;
        ls.req_hdrs = {{":method", "POST"},
                       {":scheme", tls ? "https" : "http"},
                       {":path", "/bench.Echo/Echo"},
                       {":authority", authority},
                       {"content-type", "application/grpc"},
                       {"te", "trailers"}};
        ls.deadline_us = deadline;
        ls.inflight_target = per_conn;
        if (rate_rps > 0) {
            ls.paced = true;
            ls.interval_us =
                (uint64_t)(1e6 * (double)nconns / rate_rps);
            ls.next_due_us = now_us()
                + (uint64_t)i * ls.interval_us / (uint64_t)nconns;
        }
        load_launch(c, &ls);
        epoll_event ev{};
        ev.events = EPOLLIN | EPOLLOUT;
        ev.data.fd = fd;
        c->want_write = true;
        epoll_ctl(epfd, EPOLL_CTL_ADD, fd, &ev);
        conns[fd] = c;
        conn_state[fd] = (size_t)i;
    }

    epoll_event evs[128];
    uint64_t t0 = now_us();
    for (;;) {
        uint64_t now = now_us();
        if (rate_rps > 0) {
            // paced launches ride the clock; stop launching at deadline
            for (auto& kv : conns) {
                Conn* c = kv.second;
                LoadState* ls = &states[conn_state[kv.first]];
                while (now < deadline && now >= ls->next_due_us) {
                    if (ls->inflight < 4 * ls->inflight_target + 64)
                        launch_one(c, ls);
                    ls->next_due_us += ls->interval_us;
                }
                flush_conn(epfd, c);
            }
        }
        bool any_inflight = false;
        for (auto& ls : states)
            if (ls.inflight > 0) any_inflight = true;
        // past the deadline: stop launching but DRAIN in-flight requests
        // (up to a 5s grace) so the tail isn't silently dropped from the
        // latency/error accounting — the tail IS the p99
        if (now >= deadline) {
            if (!any_inflight || now >= deadline + 5'000'000) break;
        } else if (!any_inflight && rate_rps <= 0) {
            break;
        }
        int n = epoll_wait(epfd, evs, 128, rate_rps > 0 ? 1 : 100);
        for (int i = 0; i < n; i++) {
            int fd = evs[i].data.fd;
            auto it = conns.find(fd);
            if (it == conns.end()) continue;
            Conn* c = it->second;
            LoadState* ls = &states[conn_state[fd]];
            bool dead = false;
            if (evs[i].events & (EPOLLHUP | EPOLLERR)) dead = true;
            if (!dead && (evs[i].events & EPOLLOUT))
                dead = !flush_conn(epfd, c);
            if (!dead && (evs[i].events & EPOLLIN)) {
                char buf[64 * 1024];
                bool tls_eof = false;
                for (;;) {
                    ssize_t r = ::recv(fd, buf, sizeof(buf), 0);
                    if (r > 0) {
                        if (c->tls != nullptr) {
                            if (!l5dtls::feed(c->tls, buf, (size_t)r)) {
                                dead = true;
                                break;
                            }
                        } else {
                            c->in.append(buf, (size_t)r);
                        }
                    } else if (r < 0 && errno == EINTR) {
                        continue;
                    } else if (r < 0 && (errno == EAGAIN ||
                                         errno == EWOULDBLOCK)) {
                        break;
                    } else {
                        dead = true;
                        break;
                    }
                }
                if (!dead && c->tls != nullptr) {
                    int rc = l5dtls::pump(c->tls, &c->in, &c->cipher_out);
                    if (rc < 0) dead = true;
                    else if (rc > 0) tls_eof = true;  // after the parse
                }
                size_t pos = 0;
                while (!dead && c->in.size() - pos >= 9) {
                    const uint8_t* h = (const uint8_t*)c->in.data() + pos;
                    uint32_t len = ((uint32_t)h[0] << 16) |
                                   ((uint32_t)h[1] << 8) | h[2];
                    if (c->in.size() - pos < 9 + (size_t)len) break;
                    load_handle_frame(c, ls, h[3], h[4],
                                      h2::get_u32(h + 5) & 0x7FFFFFFF,
                                      h + 9, len);
                    pos += 9 + (size_t)len;
                }
                if (pos) c->in.erase(0, pos);
                if (!dead && tls_eof) dead = true;
                if (!dead) dead = !flush_conn(epfd, c);
            }
            if (dead) {
                ls->errors += (uint64_t)ls->inflight;
                ls->inflight = 0;
                epoll_ctl(epfd, EPOLL_CTL_DEL, fd, nullptr);
                ::close(fd);
                l5dtls::free_session(c->tls);
                delete c;
                conns.erase(it);
            }
        }
        if (conns.empty()) break;
    }
    // rate denominator is the offered window: the post-deadline drain
    // adds completions (the tail) but no offered load, and must not
    // deflate rps
    uint64_t end = now_us();
    if (end > deadline) end = deadline;
    double dt = (double)(end - t0) / 1e6;
    uint64_t done = 0, errors = 0;
    std::vector<uint32_t> lat;
    for (auto& ls : states) {
        done += ls.done;
        // requests still in flight after the drain grace are failures,
        // not omissions
        errors += ls.errors + (uint64_t)ls.inflight;
        lat.insert(lat.end(), ls.lat_us.begin(), ls.lat_us.end());
    }
    std::sort(lat.begin(), lat.end());
    auto pct = [&](double q) -> double {
        if (lat.empty()) return 0.0;
        size_t i = (size_t)(q * (double)(lat.size() - 1));
        return (double)lat[i] / 1000.0;
    };
    if (done_out != nullptr) *done_out = done;
    printf("{\"reqs\": %llu, \"errors\": %llu, \"secs\": %.3f, "
           "\"rps\": %.1f, \"p50_ms\": %.3f, \"p99_ms\": %.3f}\n",
           (unsigned long long)done, (unsigned long long)errors, dt,
           dt > 0 ? (double)done / dt : 0.0, pct(0.5), pct(0.99));
    for (auto& kv : conns) {
        ::close(kv.first);
        l5dtls::free_session(kv.second->tls);
        delete kv.second;
    }
    ::close(epfd);
    return 0;
}

// ---------------- h1 load mode (config 1's wrk analog) ----------------

struct H1Conn {
    int fd = -1;
    std::string in, out;
    bool want_write = false;
    l5dtls::Sess* tls = nullptr;   // TLS leg (h1loadtls)
    std::string cipher_out;
    std::deque<uint64_t> sent_at;  // FIFO: pipelined responses in order
    size_t scan = 0;               // resume offset for head scanning
    long body_left = -1;           // -1: parsing head
};

int run_h1_load(const char* ip, int port, const char* host, int conc,
                double seconds, uint64_t* done_out, bool tls = false,
                int nconns_override = 0) {
    if (tls && g_tls_client == nullptr && !tls_client_init("http/1.1"))
        return 1;
    char reqbuf[256];
    int reqlen = snprintf(reqbuf, sizeof(reqbuf),
                          "GET /bench HTTP/1.1\r\nHost: %s\r\n\r\n", host);
    int nconns = nconns_override > 0 ? nconns_override
                                     : std::max(1, conc / 16);
    int window = std::max(1, conc / nconns);

    int epfd = epoll_create1(0);
    // l5d: ignore[bounded-table] — keyed by our own connect() fds; exactly nconns entries, from the -c flag, not peer input
    std::unordered_map<int, H1Conn*> conns;
    uint64_t done = 0, errors = 0;
    std::vector<uint32_t> lat;
    uint64_t deadline = now_us() + (uint64_t)(seconds * 1e6);

    for (int i = 0; i < nconns; i++) {
        int fd = ::socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in sa{};
        sa.sin_family = AF_INET;
        sa.sin_port = htons((uint16_t)port);
        inet_pton(AF_INET, ip, &sa.sin_addr);
        if (::connect(fd, (sockaddr*)&sa, sizeof(sa)) < 0) {
            perror("connect");
            ::close(fd);
            ::close(epfd);
            for (auto& kv : conns) {
                ::close(kv.first);
                l5dtls::free_session(kv.second->tls);
                delete kv.second;
            }
            return 1;
        }
        set_nodelay(fd);
        int fl = fcntl(fd, F_GETFL, 0);
        fcntl(fd, F_SETFL, fl | O_NONBLOCK);
        H1Conn* c = new H1Conn();
        c->fd = fd;
        if (tls) {
            c->tls = l5dtls::new_session(g_tls_client, host,
                                         /*verify=*/false, nullptr);
            if (c->tls == nullptr) {
                fprintf(stderr, "h2bench: TLS session alloc failed\n");
                ::close(fd);
                delete c;
                ::close(epfd);
                for (auto& kv : conns) {
                    ::close(kv.first);
                    l5dtls::free_session(kv.second->tls);
                    delete kv.second;
                }
                return 1;
            }
        }
        for (int w = 0; w < window; w++) {
            c->out.append(reqbuf, (size_t)reqlen);
            c->sent_at.push_back(now_us());
        }
        epoll_event ev{};
        ev.events = EPOLLIN | EPOLLOUT;
        ev.data.fd = fd;
        c->want_write = true;
        epoll_ctl(epfd, EPOLL_CTL_ADD, fd, &ev);
        conns[fd] = c;
    }

    auto flush_h1 = [&](H1Conn* c) -> bool {
        if (c->tls != nullptr) {
            if (!tls_flush_bytes(c->fd, c->tls, &c->out, &c->cipher_out))
                return false;
            bool tww = !c->cipher_out.empty();
            if (tww != c->want_write) {
                c->want_write = tww;
                epoll_event ev{};
                ev.events = EPOLLIN | (tww ? EPOLLOUT : 0);
                ev.data.fd = c->fd;
                epoll_ctl(epfd, EPOLL_CTL_MOD, c->fd, &ev);
            }
            return true;
        }
        while (!c->out.empty()) {
            ssize_t n = ::send(c->fd, c->out.data(), c->out.size(),
                               MSG_NOSIGNAL);
            if (n > 0) c->out.erase(0, (size_t)n);
            else if (n < 0 && errno == EINTR)
                continue;  // signal during send: retry
            else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK))
                break;
            else
                return false;
        }
        bool ww = !c->out.empty();
        if (ww != c->want_write) {
            c->want_write = ww;
            epoll_event ev{};
            ev.events = EPOLLIN | (ww ? EPOLLOUT : 0);
            ev.data.fd = c->fd;
            epoll_ctl(epfd, EPOLL_CTL_MOD, c->fd, &ev);
        }
        return true;
    };

    epoll_event evs[128];
    uint64_t t0 = now_us();
    while (!conns.empty()) {
        uint64_t now = now_us();
        if (now >= deadline) {
            bool any = false;
            for (auto& kv : conns)
                if (!kv.second->sent_at.empty()) any = true;
            if (!any || now >= deadline + 5'000'000) break;
        }
        int n = epoll_wait(epfd, evs, 128, 100);
        for (int i = 0; i < n; i++) {
            auto it = conns.find(evs[i].data.fd);
            if (it == conns.end()) continue;
            H1Conn* c = it->second;
            bool dead = (evs[i].events & (EPOLLHUP | EPOLLERR)) != 0;
            if (!dead && (evs[i].events & EPOLLOUT))
                dead = !flush_h1(c);
            if (!dead && (evs[i].events & EPOLLIN)) {
                char buf[64 * 1024];
                bool tls_eof = false;
                for (;;) {
                    ssize_t r = ::recv(c->fd, buf, sizeof(buf), 0);
                    if (r > 0) {
                        if (c->tls != nullptr) {
                            if (!l5dtls::feed(c->tls, buf, (size_t)r)) {
                                dead = true;
                                break;
                            }
                        } else {
                            c->in.append(buf, (size_t)r);
                        }
                    } else if (r < 0 && errno == EINTR) {
                        continue;
                    } else if (r < 0 && (errno == EAGAIN ||
                                         errno == EWOULDBLOCK)) {
                        break;
                    } else { dead = true; break; }
                }
                if (!dead && c->tls != nullptr) {
                    int rc = l5dtls::pump(c->tls, &c->in, &c->cipher_out);
                    if (rc < 0) dead = true;
                    else if (rc > 0) tls_eof = true;  // after the parse
                }
                // consume complete responses
                while (!dead) {
                    if (c->body_left < 0) {
                        size_t hs = c->in.find("\r\n\r\n", c->scan);
                        if (hs == std::string::npos) {
                            c->scan = c->in.size() > 3
                                ? c->in.size() - 3 : 0;
                            break;
                        }
                        long cl = 0;
                        // case-insensitive content-length scan in head
                        for (size_t p2 = 0; p2 + 15 < hs; p2++) {
                            if (strncasecmp(c->in.data() + p2,
                                            "content-length:", 15) == 0) {
                                cl = atol(c->in.data() + p2 + 15);
                                break;
                            }
                        }
                        c->in.erase(0, hs + 4);
                        c->scan = 0;
                        c->body_left = cl;
                    }
                    if ((long)c->in.size() < c->body_left) break;
                    c->in.erase(0, (size_t)c->body_left);
                    c->body_left = -1;
                    if (!c->sent_at.empty()) {
                        uint64_t t = c->sent_at.front();
                        c->sent_at.pop_front();
                        done++;
                        if (lat.size() < 2'000'000)
                            lat.push_back((uint32_t)(now_us() - t));
                    }
                    if (now_us() < deadline) {
                        c->out.append(reqbuf, (size_t)reqlen);
                        c->sent_at.push_back(now_us());
                    }
                }
                if (!dead && tls_eof) dead = true;
                if (!dead && (!c->out.empty() || c->tls != nullptr))
                    dead = !flush_h1(c);
            }
            if (dead) {
                errors += c->sent_at.size();
                epoll_ctl(epfd, EPOLL_CTL_DEL, c->fd, nullptr);
                ::close(c->fd);
                l5dtls::free_session(c->tls);
                delete c;
                conns.erase(it);
            }
        }
    }
    uint64_t end = now_us();
    if (end > deadline) end = deadline;
    double dt = (double)(end - t0) / 1e6;
    std::sort(lat.begin(), lat.end());
    auto pct = [&](double q) -> double {
        if (lat.empty()) return 0.0;
        return (double)lat[(size_t)(q * (double)(lat.size() - 1))] / 1e3;
    };
    if (done_out != nullptr) *done_out = done;
    printf("{\"reqs\": %llu, \"errors\": %llu, \"secs\": %.3f, "
           "\"rps\": %.1f, \"p50_ms\": %.3f, \"p99_ms\": %.3f}\n",
           (unsigned long long)done, (unsigned long long)errors, dt,
           dt > 0 ? (double)done / dt : 0.0, pct(0.5), pct(0.99));
    for (auto& kv : conns) {
        ::close(kv.first);
        l5dtls::free_session(kv.second->tls);
        delete kv.second;
    }
    ::close(epfd);
    return 0;
}

}  // namespace h2bench

#ifndef H2BENCH_NO_MAIN
int main(int argc, char** argv) {
    signal(SIGINT, h2bench::on_sig);
    signal(SIGTERM, h2bench::on_sig);
    signal(SIGPIPE, SIG_IGN);
    // --conns-per-worker N [--workers W]: force N*W client connections
    // so a load run against an SO_REUSEPORT-sharded proxy spreads
    // across every worker's accept socket (the kernel balances per
    // connection). Flags are stripped before positional parsing.
    int conns_per_worker = 0, workers = 1;
    std::vector<char*> pos;
    for (int i = 0; i < argc; i++) {
        if (i + 1 < argc && strcmp(argv[i], "--conns-per-worker") == 0) {
            conns_per_worker = atoi(argv[++i]);
        } else if (i + 1 < argc && strcmp(argv[i], "--workers") == 0) {
            workers = atoi(argv[++i]);
        } else {
            pos.push_back(argv[i]);
        }
    }
    int nconns = conns_per_worker > 0
        ? conns_per_worker * std::max(1, workers) : 0;
    argc = (int)pos.size();
    argv = pos.data();
    if (argc >= 3 && strcmp(argv[1], "serve") == 0)
        return h2bench::run_serve(atoi(argv[2]), nullptr);
    if (argc >= 7 && (strcmp(argv[1], "h1load") == 0 ||
                      strcmp(argv[1], "h1loadtls") == 0))
        return h2bench::run_h1_load(argv[2], atoi(argv[3]), argv[4],
                                    atoi(argv[5]), atof(argv[6]), nullptr,
                                    strcmp(argv[1], "h1loadtls") == 0,
                                    nconns);
    if (argc >= 7 && (strcmp(argv[1], "load") == 0 ||
                      strcmp(argv[1], "loadtls") == 0))
        return h2bench::run_load(argv[2], atoi(argv[3]), argv[4],
                                 atoi(argv[5]), atof(argv[6]),
                                 argc > 7 ? atoi(argv[7]) : 128,
                                 argc > 8 ? atof(argv[8]) : 0.0, nullptr,
                                 strcmp(argv[1], "loadtls") == 0,
                                 nconns);
    fprintf(stderr,
            "usage: h2bench serve <port> | h1load|h1loadtls <ip> <port> <host> <conc> <secs> | h2bench "
            "load|loadtls <ip> <port> <authority> <conc> <secs> [paysz] [rate_rps]\n"
            "       [--conns-per-worker N [--workers W]] forces N*W client conns (REUSEPORT spread)\n");
    return 2;
}
#endif  // H2BENCH_NO_MAIN
