// Sanitizer stress driver for the h2 fastpath engine (h2_fastpath.cpp).
//
// Same purpose as tsan_stress.cpp for the h1 engine (SURVEY.md §5: the
// C++ pieces must be TSan-cleanable): real gRPC-shaped traffic flows
// through the engine while a second thread hammers every cross-thread
// entry point (set_route/remove_route/stats/misses/features) — the
// exact surface the Python control plane exercises concurrently with
// the epoll loop thread. Build + run via
// `python native/build.py --sanitize thread` (and `address`).
//
// In-process topology: h2bench's echo server and closed-loop load
// generator run on their own pthreads (each is a self-contained epoll
// loop), the engine under test proxies between them, and the churn
// thread plays the FastPathController.

#define H2BENCH_NO_MAIN
#include "h2bench.cpp"  // serve/load harness (namespace h2bench)

#include <atomic>
#include <pthread.h>

#include "scorer.h"        // build_test_blob: the scoring leg's weight source
#include "tenant_guard.h"  // tenant_hash: the quota-push leg's key

extern "C" {
void* fph2_create();
int fph2_start(void* e);
int fph2_listen(void* e, const char* ip, int port);
int fph2_listen_shared(void* e, const char* ip, int port);
int fph2_listen_tls_shared(void* e, const char* ip, int port);
int fph2_attach_slab(void* e, void* slab);
int fph2_set_route(void* e, const char* host, const char* endpoints);
int fph2_remove_route(void* e, const char* host);
long fph2_drain_misses(void* e, char* buf, size_t cap);
long fph2_stats_json(void* e, char* buf, size_t cap);
long fph2_drain_features(void* e, float* buf, long cap_rows);
void fph2_shutdown(void* e);
int fph2_tls_runtime_available();
int fph2_set_tls(void* e, const char* cert, const char* key,
                 const char* alpn, char* err, size_t errcap);
int fph2_listen_tls(void* e, const char* ip, int port);
int fph2_set_client_tls(void* e, const char* alpn, int verify,
                        const char* ca_path, char* err, size_t errcap);
int fph2_publish_weights(void* e, const unsigned char* blob, size_t len,
                         char* err, size_t errcap);
int fph2_publish_delta(void* e, const unsigned char* blob, size_t len,
                       char* err, size_t errcap);
int fph2_set_route_feature(void* e, const char* host, int col, float sign);
int fph2_set_route_hash(void* e, const char* host, unsigned int rhash);
int fph2_set_tenant(void* e, int kind, const char* header, int segment);
int fph2_set_tenant_quota(void* e, unsigned int hash, int limit);
int fph2_set_guard(void* e, long header_budget_ms, long body_stall_ms,
                   long accept_burst, long accept_window_ms,
                   long max_hs_inflight, long tenant_cap);
int fph2_set_flood_guard(void* e, long max_streams, long rst_burst,
                         long ping_burst, long settings_burst,
                         long window_ms);
int fph2_set_stream_cfg(void* e, long enabled, long sample_every,
                        long min_gap_ms, long table_cap, double enter,
                        double exitv, long quorum, long dwell_ms,
                        long action);
long fph2_streams_json(void* e, char* buf, size_t cap);
int fph2_rst_stream(void* e, unsigned int skey);
}

namespace {

struct ServeArgs {
    std::atomic<int> bound_port{0};
};

void* serve_main(void* arg) {
    ServeArgs* a = (ServeArgs*)arg;
    h2bench::run_serve(0, &a->bound_port);
    return nullptr;
}

struct LoadArgs {
    int port = 0;
    uint64_t done = 0;
};

void* load_main(void* arg) {
    LoadArgs* a = (LoadArgs*)arg;
    h2bench::run_load("127.0.0.1", a->port, "echoext", 16, 3.0, 128, 0.0,
                      &a->done);
    return nullptr;
}

constexpr int NWORKERS = 2;  // the engine under test is a shard group

struct ChurnArgs {
    void* engines[NWORKERS] = {nullptr, nullptr};
    int serve_port = 0;
    std::atomic<int> stop{0};
    std::atomic<long> scored{0};    // drained rows the engine pre-scored
    std::atomic<long> swaps{0};     // weight publishes that landed
    std::atomic<long> stream_rows{0};  // ROW_STREAM samples drained
};

void* churn_main(void* arg) {
    ChurnArgs* a = (ChurnArgs*)arg;
    char ep[64];
    snprintf(ep, sizeof(ep), "127.0.0.1:%d ", a->serve_port);
    char* stats = new char[1 << 20];
    char* misses = new char[64 * 1024];
    float* feats = new float[4096 * 12];  // FeatureRow is 12 floats wide
    std::vector<uint8_t> blob;
    char err[256];
    int i = 0;
    while (!a->stop.load(std::memory_order_relaxed)) {
        // the whole Python-facing control surface, hammered —
        // broadcast to every worker like the sharded wrapper does
        for (int w = 0; w < NWORKERS; w++) {
            fph2_set_route(a->engines[w], "echoext", ep);
            // scoring leg: the route-feature + bank-key pushes ride
            // every re-install (the Python controller's _push does the
            // same), and weight banks hot-swap mid-traffic —
            // concurrent score + head-select + swap + drain is exactly
            // the slab's seqlock contract under test, now with BOTH
            // workers' epoll threads reading the ONE shared slab
            fph2_set_route_feature(a->engines[w], "echoext", 14, 1.0f);
            fph2_set_route_hash(a->engines[w], "echoext", 1000u);
        }
        if (i % 4 == 0) {
            // bank publish (f32/int8/int4 rotating) + a fenced
            // per-route DELTA patch on the hashed route — the
            // distiller's publish path under sanitizer fire
            const uint32_t gen = (uint32_t)(i / 4) * 2 + 1;
            l5dscore::build_test_bank_blob(&blob, gen, i % 3,
                                           (uint32_t)i, 1);
            // one publish through EITHER worker lands in the shared
            // slab and fans out to all of them
            if (fph2_publish_weights(a->engines[(i / 4) % NWORKERS],
                                     blob.data(), blob.size(),
                                     err, sizeof(err)) == 0)
                a->swaps.fetch_add(1);
            l5dscore::build_test_delta_blob(&blob, gen, gen + 1, 1000u,
                                            i % 3, (uint32_t)i + 3,
                                            /*remove=*/false);
            if (fph2_publish_delta(a->engines[(i / 4 + 1) % NWORKERS],
                                   blob.data(), blob.size(), err,
                                   sizeof(err)) == 0)
                a->swaps.fetch_add(1);
        }
        if (i % 7 == 0) {
            for (int w = 0; w < NWORKERS; w++) {
                fph2_set_route(a->engines[w], "ghost", "127.0.0.1:1 ");
                fph2_remove_route(a->engines[w], "ghost");
            }
        }
        // per-tenant quota push/clear races the data plane's quota
        // reads in client_headers_complete
        for (int w = 0; w < NWORKERS; w++)
            fph2_set_tenant_quota(a->engines[w],
                                  l5dtg::tenant_hash("echoext", 7),
                                  i % 2 ? 1024 : -1);
        // stream-sentinel leg: the mid-stream actuation queue (keys
        // resolve against live streams on the loop thread — skeys are
        // sequential so low keys DO hit in-flight gRPC streams) plus
        // the streams.json snapshot racing the stream table
        if (i % 16 == 0)
            for (int w = 0; w < NWORKERS; w++)
                fph2_rst_stream(a->engines[w],
                                (unsigned)(i / 16 % 2048) + 1);
        for (int w = 0; w < NWORKERS; w++) {
            fph2_stats_json(a->engines[w], stats, 1 << 20);
            fph2_streams_json(a->engines[w], stats, 1 << 20);
            fph2_drain_misses(a->engines[w], misses, 64 * 1024);
            long n = fph2_drain_features(a->engines[w], feats, 4096);
            for (long r = 0; r < n; r++) {
                if (feats[r * 12 + 7] > 0.5f) a->scored.fetch_add(1);
                if (feats[r * 12 + 9] > 0.5f) a->stream_rows.fetch_add(1);
            }
        }
        usleep(500);
        i++;
    }
    delete[] stats;
    delete[] misses;
    delete[] feats;
    return nullptr;
}

std::atomic<int> g_attack_stop{0};

// Slowloris: connect, send a PARTIAL client preface, stall until the
// engine's preface budget reaps us.
void* h2_slowloris_main(void* arg) {
    int port = *(int*)arg;
    while (!g_attack_stop.load(std::memory_order_relaxed)) {
        int fd = socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in addr{};
        addr.sin_family = AF_INET;
        addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
        addr.sin_port = htons((uint16_t)port);
        if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
            close(fd);
            usleep(2000);
            continue;
        }
        (void)write(fd, "PRI * HTTP/2.0\r\n", 16);  // half a preface
        char buf[256];
        struct timeval tv{2, 0};
        setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
        while (read(fd, buf, sizeof(buf)) > 0) {}
        close(fd);
    }
    return nullptr;
}

// Connection churn: connect + close at rate.
void* h2_churn_main(void* arg) {
    int port = *(int*)arg;
    while (!g_attack_stop.load(std::memory_order_relaxed)) {
        int fd = socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in addr{};
        addr.sin_family = AF_INET;
        addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
        addr.sin_port = htons((uint16_t)port);
        if (connect(fd, (sockaddr*)&addr, sizeof(addr)) == 0) close(fd);
        else close(fd);
        usleep(200);
    }
    return nullptr;
}

}  // namespace

int main() {
    signal(SIGPIPE, SIG_IGN);

    ServeArgs sa;
    pthread_t serve_t;
    pthread_create(&serve_t, nullptr, serve_main, &sa);
    for (int i = 0; i < 200 && sa.bound_port.load() == 0; i++)
        usleep(10'000);
    if (sa.bound_port.load() == 0) {
        fprintf(stderr, "echo server never bound\n");
        return 2;
    }

    // the engine under test is a 2-worker shard group: shared ports
    // (SO_REUSEPORT) + ONE shared weight slab read by both epoll
    // threads (the multi-core topology, under the sanitizer)
    void* engines[NWORKERS];
    l5dscore::Slab shared_slab;
    for (int w = 0; w < NWORKERS; w++) {
        engines[w] = fph2_create();
        fph2_attach_slab(engines[w], &shared_slab);
    }
    void* eng = engines[0];
    int lport = fph2_listen_shared(eng, "127.0.0.1", 0);
    if (lport <= 0) {
        fprintf(stderr, "engine listen failed\n");
        return 2;
    }
    for (int w = 1; w < NWORKERS; w++)
        if (fph2_listen_shared(engines[w], "127.0.0.1", lport) <= 0) {
            fprintf(stderr, "shared listen failed\n");
            return 2;
        }
    // TLS leg (cert provided by the runner + OpenSSL runtime loads):
    // h2c load -> front engine (TLS ORIGINATION, ALPN h2) -> this
    // engine's TLS listener (TERMINATION) -> echo server. Exercises the
    // memory-BIO pump on both legs under the sanitizer.
    const char* cert = getenv("L5D_STRESS_CERT");
    const char* key = getenv("L5D_STRESS_KEY");
    bool tls_leg = cert && key && fph2_tls_runtime_available();
    void* front = nullptr;
    int front_port = 0;
    if (tls_leg) {
        char err[256];
        for (int w = 0; w < NWORKERS; w++)
            if (fph2_set_tls(engines[w], cert, key, "h2", err,
                             sizeof(err)) != 0) {
                fprintf(stderr, "fph2_set_tls: %s\n", err);
                return 2;
            }
        int tls_port = fph2_listen_tls_shared(eng, "127.0.0.1", 0);
        if (tls_port <= 0) {
            fprintf(stderr, "tls listen failed\n");
            return 2;
        }
        for (int w = 1; w < NWORKERS; w++)
            if (fph2_listen_tls_shared(engines[w], "127.0.0.1",
                                       tls_port) <= 0) {
                fprintf(stderr, "shared tls listen failed\n");
                return 2;
            }
        front = fph2_create();
        if (fph2_set_client_tls(front, "h2", 0, nullptr, err,
                                sizeof(err)) != 0) {
            fprintf(stderr, "fph2_set_client_tls: %s\n", err);
            return 2;
        }
        front_port = fph2_listen(front, "127.0.0.1", 0);
        if (front_port <= 0) {
            fprintf(stderr, "front listen failed\n");
            return 2;
        }
        char tls_ep[64];
        snprintf(tls_ep, sizeof(tls_ep), "127.0.0.1:%d ", tls_port);
        fph2_set_route(front, "echoext", tls_ep);
        fph2_start(front);
    } else {
        fprintf(stderr, "h2 stress: TLS leg skipped (%s)\n",
                cert && key ? "no OpenSSL runtime" : "no cert in env");
    }
    // tenant + guard legs: path-segment extraction (h2bench's :path
    // feeds the tenant table without touching the load generator),
    // tight preface budget for the slowloris thread, generous accept
    // throttle, small tenant LRU, and flood caps high enough that the
    // legit load never trips them
    for (int w = 0; w < NWORKERS; w++) {
        fph2_set_tenant(engines[w], 2, nullptr, 0);
        fph2_set_guard(engines[w], /*header_ms=*/400, /*body_ms=*/400,
                       /*accept_burst=*/100000, /*accept_window_ms=*/1000,
                       /*max_hs_inflight=*/64, /*tenant_cap=*/16);
        fph2_set_flood_guard(engines[w], /*max_streams=*/512,
                             /*rst=*/100000, /*ping=*/100000,
                             /*settings=*/100000, /*window_ms=*/1000);
        // stream sentinel ON with a tiny table (forces LRU eviction
        // under stream churn) and action=1; enter is set high so legit
        // echo streams rarely trip organically — the deterministic
        // mid-stream RST pressure comes from the churn thread's
        // fph2_rst_stream leg
        fph2_set_stream_cfg(engines[w], /*enabled=*/1,
                            /*sample_every=*/2, /*min_gap_ms=*/0,
                            /*table_cap=*/64, /*enter=*/0.95,
                            /*exit=*/0.5, /*quorum=*/4, /*dwell_ms=*/0,
                            /*action=*/1);
        fph2_start(engines[w]);
    }

    ChurnArgs ca;
    for (int w = 0; w < NWORKERS; w++) ca.engines[w] = engines[w];
    ca.serve_port = sa.bound_port.load();
    // install the route up-front (the churn thread keeps re-installing)
    char ep[64];
    snprintf(ep, sizeof(ep), "127.0.0.1:%d ", sa.bound_port.load());
    for (int w = 0; w < NWORKERS; w++)
        fph2_set_route(engines[w], "echoext", ep);
    pthread_t churn_t;
    pthread_create(&churn_t, nullptr, churn_main, &ca);

    pthread_t loris_t, churnflood_t;
    int attack_port = lport;
    pthread_create(&loris_t, nullptr, h2_slowloris_main, &attack_port);
    pthread_create(&churnflood_t, nullptr, h2_churn_main, &attack_port);

    int nload = tls_leg ? 3 : 2;
    LoadArgs la[3];
    pthread_t load_t[3];
    for (int i = 0; i < nload; i++) {
        // the last loader drives the TLS chain through the front engine
        la[i].port = (tls_leg && i == nload - 1) ? front_port : lport;
        pthread_create(&load_t[i], nullptr, load_main, &la[i]);
    }
    uint64_t total = 0, tls_total = 0;
    for (int i = 0; i < nload; i++) {
        pthread_join(load_t[i], nullptr);
        total += la[i].done;
        if (tls_leg && i == nload - 1) tls_total = la[i].done;
    }

    g_attack_stop.store(1);
    pthread_join(loris_t, nullptr);
    pthread_join(churnflood_t, nullptr);
    ca.stop.store(1);
    pthread_join(churn_t, nullptr);
    if (front != nullptr) fph2_shutdown(front);
    // every worker joins its loop thread BEFORE the shared slab (a
    // stack local) goes out of scope — mirrors the wrapper's close()
    for (int w = 0; w < NWORKERS; w++) fph2_shutdown(engines[w]);
    h2bench::g_stop.store(1);
    pthread_join(serve_t, nullptr);

    fprintf(stderr,
            "h2 stress: %llu requests proxied (%llu via TLS), "
            "%ld rows scored in-engine across %ld weight swaps, "
            "%ld stream samples\n",
            (unsigned long long)total, (unsigned long long)tls_total,
            ca.scored.load(), ca.swaps.load(), ca.stream_rows.load());
    if (total < 500) {
        fprintf(stderr, "too little traffic flowed (%llu)\n",
                (unsigned long long)total);
        return 3;
    }
    if (tls_leg && tls_total < 100) {
        fprintf(stderr, "too little TLS traffic flowed (%llu)\n",
                (unsigned long long)tls_total);
        return 3;
    }
    if (ca.scored.load() < 50 || ca.swaps.load() < 10) {
        fprintf(stderr, "scoring leg starved (scored=%ld swaps=%ld)\n",
                ca.scored.load(), ca.swaps.load());
        return 3;
    }
    if (ca.stream_rows.load() < 10) {
        fprintf(stderr, "stream-sentinel leg starved (stream_rows=%ld)\n",
                ca.stream_rows.load());
        return 3;
    }
    return 0;
}
