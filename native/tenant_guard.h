// tenant_guard.h — tenant accounting + connection-plane defenses for
// the native engines.
//
// Both epoll engines (fastpath.cpp, h2_fastpath.cpp) embed the same
// two pieces:
//
// - TenantTable: per-tenant request/shed/error/score-EWMA aggregates
//   keyed by a 32-bit FNV-1a hash of the extracted tenant id. The table
//   is bounded-cardinality with amortized-LRU eviction, so hostile
//   tenant-id churn (a new id per request) costs eviction work, never
//   unbounded memory. Quotas live in a separate, pusher-bounded map so
//   a sick tenant's quota survives stats eviction.
//
// - Guard: connection-plane defense state — per-source accept
//   throttling (SourceTable), slowloris budgets (header-read and
//   zero-progress-body, enforced by the engines' sweeps), TLS
//   handshake-churn backpressure, and (h2) SETTINGS/PING/RST flood +
//   rapid-reset caps. All knobs arrive from Python before start();
//   counters are atomics (loop thread writes, stats readers read).
//
// The isolation DECISION is evaluated where the score is computed: the
// engine sheds an over-quota tenant's request itself (503 +
// l5d-retryable on h1, RST_STREAM REFUSED_STREAM on h2 — retry-safe by
// contract, the request was never admitted), per the Taurus/INSIGHT
// in-network-policy argument (PAPERS.md).

#pragma once

#include <stdint.h>
#include <string.h>

#include <algorithm>
#include <atomic>
#include <string>
#include <unordered_map>
#include <vector>

namespace l5dtg {

// FNV-1a 32-bit over the raw tenant-id bytes; mirrored bit-identically
// by linkerd_tpu.router.tenancy.tenant_hash (pinned by the parity
// test). 0 is reserved for "no tenant" — a real id hashing to 0 is
// folded to 1 so absence stays unambiguous.
inline uint32_t tenant_hash(const char* s, size_t n) {
    uint32_t h = 2166136261u;
    for (size_t i = 0; i < n; i++) {
        h ^= (uint8_t)s[i];
        h *= 16777619u;
    }
    return h == 0 ? 1u : h;
}

// Feature rows carry the hash folded to 24 bits so the value stays
// exact in float32 (2^24 is f32's integer-exact ceiling).
inline float tenant_feature(uint32_t h) {
    return (float)(h & 0xFFFFFFu);
}

struct TenantStats {
    uint64_t requests = 0;
    uint64_t shed = 0;       // refused by the per-tenant quota
    uint64_t errors = 0;     // 5xx outcomes
    uint64_t scored = 0;     // rows the in-plane scorer evaluated
    double score_ewma = 0.0; // EWMA of in-plane anomaly scores
    int inflight = 0;        // live exchanges/streams
    uint64_t last_seen_us = 0;
};

// Bounded-cardinality tenant aggregates. Callers hold the engine mu.
// When the table overflows its cap, the oldest ~quarter (by last_seen)
// is evicted in one pass — amortized O(1) per insert, so an attacker
// minting a fresh tenant id per request buys eviction churn, not
// memory. Entries with live inflight are never evicted (their
// decrement must find them).
struct TenantTable {
    std::unordered_map<uint32_t, TenantStats> map;
    size_t cap = 1024;
    uint64_t evicted = 0;

    TenantStats* get(uint32_t h, uint64_t now_us) {
        auto it = map.find(h);
        if (it != map.end()) {
            it->second.last_seen_us = now_us;
            return &it->second;
        }
        if (map.size() >= cap) evict(now_us);
        TenantStats& ts = map[h];
        ts.last_seen_us = now_us;
        return &ts;
    }

    // Look up without inserting (inflight decrements on finish paths).
    TenantStats* peek(uint32_t h) {
        auto it = map.find(h);
        return it == map.end() ? nullptr : &it->second;
    }

    void observe(uint32_t h, int status, float score, bool scored,
                 uint64_t now_us) {
        TenantStats* ts = get(h, now_us);
        ts->requests++;
        if (status >= 500) ts->errors++;
        if (scored) {
            ts->scored++;
            ts->score_ewma += 0.1 * ((double)score - ts->score_ewma);
        }
    }

    void evict(uint64_t now_us) {
        // drop the stalest quarter in one pass (skip live entries)
        std::vector<std::pair<uint64_t, uint32_t>> ages;
        ages.reserve(map.size());
        for (auto& kv : map)
            if (kv.second.inflight <= 0)
                ages.push_back({kv.second.last_seen_us, kv.first});
        if (ages.empty()) return;
        size_t k = ages.size() / 4;
        if (k == 0) k = 1;
        std::nth_element(ages.begin(), ages.begin() + (long)(k - 1),
                         ages.end());
        uint64_t cutoff = ages[k - 1].first;
        size_t dropped = 0;
        for (auto it = map.begin(); it != map.end() && dropped < k;) {
            if (it->second.inflight <= 0 &&
                it->second.last_seen_us <= cutoff) {
                it = map.erase(it);
                dropped++;
            } else {
                ++it;
            }
        }
        evicted += dropped;
        (void)now_us;
    }
};

// Per-tenant concurrency quotas pushed from the control plane (the
// TenantAdmission governor). Separate from the stats LRU: quotas are
// few (one per SICK tenant) and must survive stats eviction. Bounded
// by refusing pushes past cap — the pusher clamps long before that.
struct QuotaMap {
    std::unordered_map<uint32_t, int> map;
    size_t cap = 4096;

    // limit < 0 clears. Returns 0, or -1 when full.
    int set(uint32_t h, int limit) {
        if (limit < 0) {
            map.erase(h);
            return 0;
        }
        if (map.find(h) == map.end() && map.size() >= cap) return -1;
        map[h] = limit;
        return 0;
    }

    // -1 = no quota for this tenant
    int limit_of(uint32_t h) const {
        auto it = map.find(h);
        return it == map.end() ? -1 : it->second;
    }
};

// ---- connection-plane guard ------------------------------------------------

struct GuardCfg {
    // slowloris: a fresh conn (or a conn with a partial request head)
    // must complete its head within this budget; 0 disables.
    uint64_t header_budget_us = 10'000'000;
    // zero-progress body: a request body that advances no bytes for
    // this long is a stalled attacker; 0 disables.
    uint64_t body_stall_budget_us = 30'000'000;
    // per-source accept throttle: more than `accept_burst` accepts from
    // one source ip within `accept_window_us` are closed on arrival;
    // 0 disables.
    uint32_t accept_burst = 0;
    uint64_t accept_window_us = 1'000'000;
    // handshake-churn backpressure: new TLS conns are shed while this
    // many handshakes are already in flight (the resumption cache must
    // not thrash); 0 disables.
    uint32_t max_hs_inflight = 0;
    // h2 flood caps (per client conn per flood_window_us); 0 disables
    // the individual cap.
    uint32_t max_streams_per_conn = 512;
    uint32_t rst_burst = 200;      // CVE-2023-44487 rapid reset
    uint32_t ping_burst = 256;
    uint32_t settings_burst = 64;
    uint64_t flood_window_us = 1'000'000;
};

struct GuardStats {
    std::atomic<uint64_t> slowloris_closed{0};
    std::atomic<uint64_t> body_stall_closed{0};
    std::atomic<uint64_t> accept_throttled{0};
    std::atomic<uint64_t> hs_churn_shed{0};
    std::atomic<uint64_t> rapid_reset_closed{0};
    std::atomic<uint64_t> flood_closed{0};
    std::atomic<uint64_t> tenant_shed{0};  // quota refusals, all tenants
};

// Per-source accept-rate tracking (loop thread only). Bounded the same
// amortized way as TenantTable: source-ip churn cannot grow it.
struct SourceTable {
    struct Slot {
        uint64_t window_start_us = 0;
        uint32_t count = 0;
    };
    std::unordered_map<uint32_t, Slot> map;
    size_t cap = 4096;

    // True when this accept is within budget.
    bool allow(uint32_t ip_be, const GuardCfg& cfg, uint64_t now_us) {
        if (cfg.accept_burst == 0) return true;
        if (map.size() >= cap && map.find(ip_be) == map.end()) {
            // stalest-quarter eviction keyed by window start
            std::vector<std::pair<uint64_t, uint32_t>> ages;
            ages.reserve(map.size());
            for (auto& kv : map)
                ages.push_back({kv.second.window_start_us, kv.first});
            size_t k = ages.size() / 4;
            if (k == 0) k = 1;
            std::nth_element(ages.begin(), ages.begin() + (long)(k - 1),
                             ages.end());
            uint64_t cutoff = ages[k - 1].first;
            size_t dropped = 0;
            for (auto it = map.begin(); it != map.end() && dropped < k;) {
                if (it->second.window_start_us <= cutoff) {
                    it = map.erase(it);
                    dropped++;
                } else {
                    ++it;
                }
            }
        }
        Slot& s = map[ip_be];
        if (now_us - s.window_start_us > cfg.accept_window_us) {
            s.window_start_us = now_us;
            s.count = 0;
        }
        s.count++;
        return s.count <= cfg.accept_burst;
    }
};

// ---- stats JSON ------------------------------------------------------------

// Append `"tenants":{...}` (caller holds the engine mu for the table).
inline void tenants_json(const TenantTable& t, const QuotaMap& q,
                         std::string* s) {
    char tmp[320];
    snprintf(tmp, sizeof(tmp),
             "\"tenants\":{\"count\":%zu,\"evicted\":%llu,\"by_tenant\":{",
             t.map.size(), (unsigned long long)t.evicted);
    *s += tmp;
    bool first = true;
    for (auto& kv : t.map) {
        snprintf(tmp, sizeof(tmp),
                 "%s\"%u\":{\"requests\":%llu,\"shed\":%llu,"
                 "\"errors\":%llu,\"scored\":%llu,\"score_ewma\":%.6f,"
                 "\"inflight\":%d,\"quota\":%d}",
                 first ? "" : ",", kv.first,
                 (unsigned long long)kv.second.requests,
                 (unsigned long long)kv.second.shed,
                 (unsigned long long)kv.second.errors,
                 (unsigned long long)kv.second.scored,
                 kv.second.score_ewma, kv.second.inflight,
                 q.limit_of(kv.first));
        *s += tmp;
        first = false;
    }
    *s += "}}";
}

// Append `"guard":{...}`.
inline void guard_json(const GuardStats& g, std::string* s) {
    char tmp[448];
    snprintf(tmp, sizeof(tmp),
             "\"guard\":{\"slowloris_closed\":%llu,"
             "\"body_stall_closed\":%llu,\"accept_throttled\":%llu,"
             "\"hs_churn_shed\":%llu,\"rapid_reset_closed\":%llu,"
             "\"flood_closed\":%llu,\"tenant_shed\":%llu}",
             (unsigned long long)g.slowloris_closed.load(
                 std::memory_order_relaxed),
             (unsigned long long)g.body_stall_closed.load(
                 std::memory_order_relaxed),
             (unsigned long long)g.accept_throttled.load(
                 std::memory_order_relaxed),
             (unsigned long long)g.hs_churn_shed.load(
                 std::memory_order_relaxed),
             (unsigned long long)g.rapid_reset_closed.load(
                 std::memory_order_relaxed),
             (unsigned long long)g.flood_closed.load(
                 std::memory_order_relaxed),
             (unsigned long long)g.tenant_shed.load(
                 std::memory_order_relaxed));
    *s += tmp;
}

// ---- tenant extraction -----------------------------------------------------

// Extraction mode, pushed from Python before start(). kind: 0 = off,
// 1 = header (name in `header`, lowercase), 2 = path segment
// (`segment`th slash-separated element of the request path), 3 = SNI
// (TLS server name; TLS listeners only).
struct TenantExtract {
    int kind = 0;
    std::string header;
    int segment = 0;
};

// Path-segment extraction: "/a/b/c" segment 0 -> "a". Query strings are
// cut first. Empty result -> no tenant.
inline uint32_t hash_path_segment(const std::string& path, int segment) {
    size_t end = path.find('?');
    if (end == std::string::npos) end = path.size();
    size_t pos = 0;
    int idx = -1;
    while (pos < end) {
        if (path[pos] == '/') {
            pos++;
            continue;
        }
        size_t seg_end = pos;
        while (seg_end < end && path[seg_end] != '/') seg_end++;
        idx++;
        if (idx == segment) {
            return tenant_hash(path.data() + pos, seg_end - pos);
        }
        pos = seg_end;
    }
    return 0;
}

}  // namespace l5dtg
