// Per-connection TLS adapter shared by both native proxy engines
// (fastpath.cpp, h2_fastpath.cpp) and the h2bench load generator.
//
// The engines keep their single-threaded epoll shape: a connection's
// `out` string always holds WIRE bytes (ciphertext once TLS is up), and
// a TLS connection stages application plaintext in `plain_out` until
// flush time, when write_plain() moves it through the memory-BIO pump.
// Reads go the other way: recv'd ciphertext is fed to the session and
// the decrypted plaintext lands in the connection's normal `in` buffer,
// so none of the protocol logic above this layer knows TLS exists.
//
// Lifecycle: an accepted/connected socket gets a TlsIo when its engine
// has a server/client l5dtls::Ctx configured; the handshake rides the
// first reads/writes; `hs_deadline_us` bounds how long a peer may take
// (a slow or stalled handshaker is closed by the engine's sweep — the
// epoll loop itself never blocks on TLS, everything is memory-BIO).
#pragma once

#include <string>
#include <unordered_map>

#include "tls_shim.h"

namespace l5dtls {

struct TlsIo {
    Sess* sess = nullptr;
    std::string plain_out;       // app plaintext staged until hs_done
    std::string sni;             // verify/SNI name (client sessions)
    uint64_t hs_deadline_us = 0; // 0 once the handshake completed
    bool accounted = false;      // handshake counted in engine stats
    bool close_notify = false;   // peer sent a clean TLS shutdown
    bool shutdown_sent = false;  // we queued our close-notify

    ~TlsIo() { free_session(sess); }
};

// Resumption-cache key: endpoint AND the SNI/verify name the session
// was handshaken under. Resumption skips the Certificate exchange, so
// a session verified against one authority must never be offered for a
// connection that would pin a different one (two routes sharing an
// ip:port would otherwise bypass hostname verification).
inline std::string session_key(uint32_t ip_be, uint16_t port,
                               const std::string& sni) {
    char buf[32];
    snprintf(buf, sizeof(buf), "%u:%u|", ip_be, port);
    return buf + sni;
}

// Harvest the latest session of a dying origination conn into the
// engine's cache (tickets arrive post-handshake, so harvesting at
// close catches them). Frees the displaced session.
inline void stash_session(
    std::unordered_map<std::string, SSL_SESSION*>* cache,
    const std::string& key, Sess* sess) {
    if (sess == nullptr || !sess->hs_done) return;
    SSL_SESSION* s = get1_session(sess);
    if (s == nullptr) return;
    SSL_SESSION*& slot = (*cache)[key];
    free_ssl_session(slot);
    slot = s;
}

// Counters an engine exports under stats_json "tls": {...}. Written by
// the loop thread; snapshotted under the engine's stats mutex by the
// exporters, so plain integers suffice.
struct TlsStats {
    uint64_t handshakes = 0, failures = 0, resumed = 0;
    uint64_t alpn_h2 = 0, alpn_http1 = 0;
    uint64_t up_handshakes = 0, up_resumed = 0, up_failures = 0;
};

inline void count_alpn(TlsStats* st, const std::string& proto) {
    if (proto == "h2") st->alpn_h2++;
    else if (proto == "http/1.1") st->alpn_http1++;
}

// Account a finished (or failed) handshake exactly once.
inline void account_handshake(TlsIo* t, TlsStats* st, bool is_server,
                              bool failed) {
    if (t->accounted) return;
    t->accounted = true;
    if (failed) {
        (is_server ? st->failures : st->up_failures)++;
        return;
    }
    if (is_server) {
        st->handshakes++;
        if (resumed(t->sess)) st->resumed++;
        count_alpn(st, t->sess->alpn);
    } else {
        st->up_handshakes++;
        if (resumed(t->sess)) st->up_resumed++;
    }
}

// Move staged plaintext into the wire buffer. Returns false on a fatal
// TLS error (caller closes the conn; ciphertext already in *out should
// still be flushed so the peer sees the alert).
inline bool encrypt_pending(TlsIo* t, std::string* out) {
    // write_plain with an empty buffer still pumps the handshake, which
    // is what emits the connect-side ClientHello on first flush
    long n = write_plain(t->sess, t->plain_out.data(),
                         t->plain_out.size(), out);
    if (n < 0) return false;
    if (n > 0) t->plain_out.erase(0, (size_t)n);
    return !t->sess->fatal;
}

// Feed ciphertext from the socket; decrypted plaintext is appended to
// *plain_in and any TLS-layer output (handshake records, tickets,
// close-notify acks) to *out. Returns 0 = ok, 1 = clean TLS shutdown
// from the peer (process plain_in, then close), -1 = fatal.
inline int ingest(TlsIo* t, const char* data, size_t n,
                  std::string* plain_in, std::string* out) {
    if (!feed(t->sess, data, n)) return -1;
    int rc = pump(t->sess, plain_in, out);
    if (rc == 1) t->close_notify = true;
    return rc;
}

// JSON string escaping for engine stats (route keys are attacker-ish
// input on the h1 side: the Host header). Minimal but complete for the
// JSON grammar: quotes, backslashes, and control bytes.
inline void json_escape(const std::string& s, std::string* out) {
    for (char ch : s) {
        unsigned char c = (unsigned char)ch;
        if (c == '"' || c == '\\') {
            out->push_back('\\');
            out->push_back((char)c);
        } else if (c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            out->append(buf);
        } else {
            out->push_back((char)c);
        }
    }
}

// Authority / Host validation before routing (RFC 3986 reg-name +
// optional port, plus IPv6 literals). Rejects userinfo ('@'), path
// separators, spaces and control bytes — the characters that would let
// a crafted :authority smuggle through routing, logs, or stats JSON.
inline bool valid_authority(const std::string& a) {
    if (a.empty() || a.size() > 255) return false;
    for (char ch : a) {
        unsigned char c = (unsigned char)ch;
        bool ok = (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
                  (c >= '0' && c <= '9') || c == '.' || c == '-' ||
                  c == '_' || c == ':' || c == '[' || c == ']' ||
                  c == '%' || c == '~';
        if (!ok) return false;
    }
    return true;
}

}  // namespace l5dtls
