// scorer.h — in-data-plane anomaly scoring for the native engines.
//
// A dependency-free evaluator for the distilled anomaly model (the
// autoencoder + classifier dense stack of models/anomaly.py): a request
// retired by an epoll engine is featurized AND scored without leaving
// the engine thread — the Taurus/FENIX move of evaluating a small model
// inside the forwarding element itself. Weights arrive from Python as a
// versioned, CRC'd flat blob (lifecycle/export.py emits it on model
// promote/hot-swap) into a double-buffered, seqlock-style slab: readers
// never block on a publish, a publish never pauses the data plane, and
// a reader that raced a buffer flip retries instead of evaluating torn
// weights (slab_score's recheck; `retries` counts them).
//
// Layout contract (must mirror lifecycle/export.py exactly):
//
//   magic "L5DWTS01" | u32 version | u32 quant (0=f32, 1=int8)
//   | u32 in_dim | u32 n_enc | u32 n_dec | u32 n_cls | f32 recon_weight
//   | f32 mu[in_dim] | f32 var[in_dim]
//   | per layer (enc..., dec..., cls...):
//       u32 rows | u32 cols | f32 b[cols]
//       | quant 0: f32 w[rows*cols]        (row-major, w[i][j] = in i -> out j)
//       | quant 1: f32 scale[cols] | i8 w[rows*cols]
//   | u32 crc32 (zlib polynomial, over everything before it)
//
// All fields little-endian. int8 weights dequantize per OUTPUT column
// (w_f32 ≈ scale[j] * w_i8) and accumulate in f32 — the "int8 weights,
// f32 accumulate" scheme, so quantization error stays a weight-rounding
// effect and never compounds through the accumulation.

#pragma once

#include <math.h>
#include <sched.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>
#include <time.h>

#include <atomic>
#include <mutex>
#include <string>
#include <vector>

namespace l5dscore {

// Feature schema (models/features.py FEATURE_DIM + column layout); the
// featurizer below mirrors telemetry/linerate.NativeFeaturizer, which
// is the one Python-side encoder for engine rows.
constexpr int FEATURE_DIM = 36;
constexpr int STATUS_ONEHOT_OFF = 1;
constexpr int MAX_WIDTH = 1024;   // widest layer a blob may carry
constexpr int MAX_LAYERS = 16;    // per group (enc/dec/cls)
constexpr int SCORE_HIST_BUCKETS = 32;  // log2(ns) buckets

// ---- crc32 (zlib polynomial; must match Python zlib.crc32) -----------------

struct Crc32Table {
    uint32_t t[256];
    Crc32Table() {
        for (uint32_t i = 0; i < 256; i++) {
            uint32_t c = i;
            for (int k = 0; k < 8; k++)
                c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : (c >> 1);
            t[i] = c;
        }
    }
};

inline uint32_t crc32_of(const uint8_t* p, size_t n) {
    static Crc32Table tbl;  // C++11 magic static: thread-safe init
    uint32_t c = 0xFFFFFFFFu;
    for (size_t i = 0; i < n; i++)
        c = tbl.t[(c ^ p[i]) & 0xFFu] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

// ---- model -----------------------------------------------------------------

struct Layer {
    int rows = 0, cols = 0;
    std::vector<float> w;       // f32 weights (quant 0)
    std::vector<int8_t> wq;     // int8 weights (quant 1)
    std::vector<float> scale;   // per-output-column dequant (quant 1)
    std::vector<float> b;
};

struct Model {
    uint32_t version = 0;
    uint32_t crc = 0;       // the blob's own trailing crc32
    uint32_t quant = 0;     // 0 = f32, 1 = int8
    int in_dim = 0;
    int n_enc = 0, n_dec = 0, n_cls = 0;
    float recon_weight = 0.5f;
    std::vector<float> mu;
    std::vector<float> inv_std;  // precomputed 1/sqrt(var + 1e-2)
    std::vector<Layer> layers;   // enc..., dec..., cls...
};

// bounds-checked little-endian reader
struct Cursor {
    const uint8_t* p;
    size_t len, off = 0;
    bool ok = true;
    Cursor(const uint8_t* d, size_t n) : p(d), len(n) {}
    bool take(void* out, size_t n) {
        if (!ok || off + n > len) { ok = false; return false; }
        memcpy(out, p + off, n);
        off += n;
        return true;
    }
    uint32_t u32() { uint32_t v = 0; take(&v, 4); return v; }
    float f32() { float v = 0; take(&v, 4); return v; }
    bool floats(std::vector<float>* out, size_t n) {
        if (!ok || off + n * 4 > len) { ok = false; return false; }
        out->resize(n);
        memcpy(out->data(), p + off, n * 4);
        off += n * 4;
        return true;
    }
    bool bytes(std::vector<int8_t>* out, size_t n) {
        if (!ok || off + n > len) { ok = false; return false; }
        out->resize(n);
        memcpy(out->data(), p + off, n);
        off += n;
        return true;
    }
};

inline bool fail(char* err, size_t errcap, const char* msg) {
    if (err != nullptr && errcap > 0) {
        strncpy(err, msg, errcap - 1);
        err[errcap - 1] = 0;
    }
    return false;
}

// Parse + fully validate a weight blob. Geometry is checked end to end
// (layer chain, bottleneck consistency, classifier output width 1) so a
// published blob can never index out of bounds at eval time.
inline bool parse_blob(const uint8_t* data, size_t len, Model* out,
                       char* err, size_t errcap) {
    if (len < 8 + 4 * 6 + 4 + 4)
        return fail(err, errcap, "weight blob truncated");
    if (memcmp(data, "L5DWTS01", 8) != 0)
        return fail(err, errcap, "bad weight blob magic");
    uint32_t crc_stored;
    memcpy(&crc_stored, data + len - 4, 4);
    if (crc32_of(data, len - 4) != crc_stored)
        return fail(err, errcap, "weight blob crc mismatch");
    Cursor c(data + 8, len - 8 - 4);
    Model m;
    m.crc = crc_stored;
    m.version = c.u32();
    m.quant = c.u32();
    uint32_t in_dim = c.u32();
    uint32_t n_enc = c.u32(), n_dec = c.u32(), n_cls = c.u32();
    m.recon_weight = c.f32();
    if (!c.ok) return fail(err, errcap, "weight blob header truncated");
    if (m.quant > 1)
        return fail(err, errcap, "unknown weight quantization");
    if (in_dim < 1 || in_dim > MAX_WIDTH)
        return fail(err, errcap, "weight blob in_dim out of range");
    if (n_enc < 1 || n_dec < 1 || n_cls < 1 || n_enc > MAX_LAYERS ||
        n_dec > MAX_LAYERS || n_cls > MAX_LAYERS)
        return fail(err, errcap, "weight blob layer counts out of range");
    if (!(m.recon_weight >= 0.0f && m.recon_weight <= 1.0f))
        return fail(err, errcap, "recon_weight out of [0, 1]");
    m.in_dim = (int)in_dim;
    m.n_enc = (int)n_enc;
    m.n_dec = (int)n_dec;
    m.n_cls = (int)n_cls;
    if (!c.floats(&m.mu, in_dim))
        return fail(err, errcap, "weight blob mu truncated");
    std::vector<float> var;
    if (!c.floats(&var, in_dim))
        return fail(err, errcap, "weight blob var truncated");
    m.inv_std.resize(in_dim);
    for (uint32_t i = 0; i < in_dim; i++) {
        // soft variance floor, matching models.anomaly.normalize_features
        m.inv_std[i] = 1.0f / sqrtf(var[i] + 1e-2f);
        if (!(m.inv_std[i] == m.inv_std[i]))  // NaN guard
            return fail(err, errcap, "weight blob var not finite");
    }
    int total = m.n_enc + m.n_dec + m.n_cls;
    m.layers.resize(total);
    for (int k = 0; k < total; k++) {
        Layer& L = m.layers[k];
        L.rows = (int)c.u32();
        L.cols = (int)c.u32();
        if (!c.ok || L.rows < 1 || L.cols < 1 || L.rows > MAX_WIDTH ||
            L.cols > MAX_WIDTH)
            return fail(err, errcap, "weight blob layer dims out of range");
        if (!c.floats(&L.b, L.cols))
            return fail(err, errcap, "weight blob bias truncated");
        size_t n = (size_t)L.rows * L.cols;
        if (m.quant == 0) {
            if (!c.floats(&L.w, n))
                return fail(err, errcap, "weight blob weights truncated");
        } else {
            if (!c.floats(&L.scale, L.cols))
                return fail(err, errcap, "weight blob scales truncated");
            if (!c.bytes(&L.wq, n))
                return fail(err, errcap, "weight blob weights truncated");
        }
    }
    if (c.off != c.len)
        return fail(err, errcap, "weight blob has trailing bytes");
    // geometry: enc chain from in_dim to the bottleneck, dec mirrors it
    // back to in_dim, cls maps the bottleneck to one logit
    int w = m.in_dim;
    for (int k = 0; k < m.n_enc; k++) {
        if (m.layers[k].rows != w)
            return fail(err, errcap, "encoder layer chain mismatch");
        w = m.layers[k].cols;
    }
    int bottleneck = w;
    for (int k = 0; k < m.n_dec; k++) {
        if (m.layers[m.n_enc + k].rows != w)
            return fail(err, errcap, "decoder layer chain mismatch");
        w = m.layers[m.n_enc + k].cols;
    }
    if (w != m.in_dim)
        return fail(err, errcap, "decoder does not reconstruct in_dim");
    w = bottleneck;
    for (int k = 0; k < m.n_cls; k++) {
        if (m.layers[m.n_enc + m.n_dec + k].rows != w)
            return fail(err, errcap, "classifier layer chain mismatch");
        w = m.layers[m.n_enc + m.n_dec + k].cols;
    }
    if (w != 1)
        return fail(err, errcap, "classifier head must end at width 1");
    *out = std::move(m);
    return true;
}

// ---- forward pass ----------------------------------------------------------

// out[j] = act(b[j] + sum_i in[i] * w[i][j]); f32 weights or int8 with
// f32 accumulation. `in` and `out` must not alias.
inline void dense(const Layer& L, const float* in, float* out, bool relu) {
    for (int j = 0; j < L.cols; j++) out[j] = 0.0f;
    if (!L.w.empty()) {
        for (int i = 0; i < L.rows; i++) {
            const float v = in[i];
            const float* wr = &L.w[(size_t)i * L.cols];
            for (int j = 0; j < L.cols; j++) out[j] += v * wr[j];
        }
        for (int j = 0; j < L.cols; j++) out[j] += L.b[j];
    } else {
        for (int i = 0; i < L.rows; i++) {
            const float v = in[i];
            const int8_t* wr = &L.wq[(size_t)i * L.cols];
            for (int j = 0; j < L.cols; j++) out[j] += v * (float)wr[j];
        }
        for (int j = 0; j < L.cols; j++)
            out[j] = out[j] * L.scale[j] + L.b[j];
    }
    if (relu)
        for (int j = 0; j < L.cols; j++)
            if (out[j] < 0.0f) out[j] = 0.0f;
}

// One row through normalize -> autoencoder -> classifier -> blended
// score, mirroring ops/scoring._score_kernel (reconstruction error is
// measured against the NORMALIZED input, which is what the jitted step
// scores after folding normalize_features in).
inline float eval_model(const Model& m, const float* x) {
    float b0[MAX_WIDTH], b1[MAX_WIDTH], zb[MAX_WIDTH], xn[MAX_WIDTH];
    for (int i = 0; i < m.in_dim; i++)
        xn[i] = (x[i] - m.mu[i]) * m.inv_std[i];
    // encoder: relu on every layer (final_act=true in _mlp)
    const float* cur = xn;
    float* dst = b0;
    for (int k = 0; k < m.n_enc; k++) {
        dense(m.layers[k], cur, dst, true);
        cur = dst;
        dst = (dst == b0) ? b1 : b0;
    }
    const int zw = m.layers[m.n_enc - 1].cols;
    memcpy(zb, cur, (size_t)zw * sizeof(float));
    // decoder: relu except the last layer
    cur = zb;
    dst = b0;
    for (int k = 0; k < m.n_dec; k++) {
        dense(m.layers[m.n_enc + k], cur, dst, k < m.n_dec - 1);
        cur = dst;
        dst = (dst == b0) ? b1 : b0;
    }
    float err = 0.0f;
    for (int i = 0; i < m.in_dim; i++) {
        const float d = cur[i] - xn[i];
        err += d * d;
    }
    err /= (float)m.in_dim;
    // classifier head from the bottleneck: relu except the last layer
    cur = zb;
    dst = b0;
    for (int k = 0; k < m.n_cls; k++) {
        dense(m.layers[m.n_enc + m.n_dec + k], cur, dst, k < m.n_cls - 1);
        cur = dst;
        dst = (dst == b0) ? b1 : b0;
    }
    const float logit = cur[0];
    const float recon_score = tanhf(err);
    const float cls_score = 1.0f / (1.0f + expf(-logit));
    return m.recon_weight * recon_score
        + (1.0f - m.recon_weight) * cls_score;
}

// ---- double-buffered weight slab -------------------------------------------

// Publishes go to the inactive buffer; the flip is one release-store of
// `active`. Readers take a per-buffer refcount and RE-CHECK `active`
// before touching weights — a reader that raced a flip backs off and
// retries (counted in `retries`), so it can never evaluate a buffer a
// concurrent publish is rewriting. The publisher in turn drains the
// target buffer's refcount before writing, so it never rewrites under
// a reader that already passed its recheck. No reader ever blocks on a
// lock; the (rare) publisher spin is bounded by one in-flight eval.
struct Slab {
    std::mutex write_mu;  // serializes publishers only
    Model bufs[2];
    std::atomic<int> active{-1};  // -1 = nothing published yet
    std::atomic<uint32_t> readers[2] = {{0}, {0}};
    std::atomic<uint64_t> swaps{0};
    std::atomic<uint64_t> retries{0};
    std::atomic<uint32_t> version{0};
    std::atomic<uint32_t> crc{0};
};

inline bool slab_has_weights(const Slab* s) {
    return s->active.load(std::memory_order_acquire) >= 0;
}

inline bool slab_score(Slab* s, const float* x, float* out) {
    for (;;) {
        const int idx = s->active.load(std::memory_order_acquire);
        if (idx < 0) return false;
        s->readers[idx].fetch_add(1, std::memory_order_acq_rel);
        if (s->active.load(std::memory_order_acquire) != idx) {
            // a publish flipped (or is flipping) this buffer under us:
            // back off WITHOUT reading any weight bytes and retry
            s->readers[idx].fetch_sub(1, std::memory_order_acq_rel);
            s->retries.fetch_add(1, std::memory_order_relaxed);
            continue;
        }
        const float score = eval_model(s->bufs[idx], x);
        s->readers[idx].fetch_sub(1, std::memory_order_release);
        *out = score;
        return true;
    }
}

inline void slab_install(Slab* s, Model&& m) {
    std::lock_guard<std::mutex> g(s->write_mu);
    const int cur = s->active.load(std::memory_order_acquire);
    const int target = cur < 0 ? 0 : 1 - cur;
    // drain stragglers still evaluating the target buffer (bounded:
    // one row eval is microseconds)
    while (s->readers[target].load(std::memory_order_acquire) != 0)
        sched_yield();
    s->bufs[target] = std::move(m);
    s->version.store(s->bufs[target].version, std::memory_order_relaxed);
    s->crc.store(s->bufs[target].crc, std::memory_order_relaxed);
    s->active.store(target, std::memory_order_release);
    s->swaps.fetch_add(1, std::memory_order_relaxed);
}

// ---- featurizer ------------------------------------------------------------

// Per-route featurizer state. The dst-path hash column/sign is pushed
// from Python (fp_set_route_feature: the controller knows the dst path,
// the engine does not); the latency EWMA is the robust drift baseline
// of models.features.DstTemporal, updated per retired request. Guarded
// by the engine's `mu` like the rest of the Route.
struct RouteFeat {
    int col = -1;        // dst-path hash column (-1: not pushed yet)
    float sign = 0.0f;
    bool ewma_init = false;
    float ewma = 0.0f;
    float dev = 0.25f;
};

// Returns the drift (lat - EWMA before update) and applies the robust
// update: increments winsorized at 3 deviation-scales so anomalies
// barely drag the baseline toward themselves (DstTemporal's lat_alpha
// 0.05 / dev_clip 3.0 / dev_alpha 0.05).
inline float feat_drift_update(RouteFeat* rf, float lat_ms) {
    if (!rf->ewma_init) {
        rf->ewma_init = true;
        rf->ewma = lat_ms;
        rf->dev = fmaxf(fabsf(lat_ms) * 0.1f, 0.25f);
        return 0.0f;
    }
    const float drift = lat_ms - rf->ewma;
    const float dev = rf->dev;
    const float lim = 3.0f * fmaxf(dev, 0.25f);
    float inc = drift;
    if (inc > lim) inc = lim;
    if (inc < -lim) inc = -lim;
    rf->ewma += 0.05f * inc;
    const float ad = fminf(fabsf(drift), lim);
    rf->dev = dev + 0.05f * (ad - dev);
    return drift;
}

// One engine row -> FEATURE_DIM model features; must stay bit-for-bit
// in step with telemetry/linerate.NativeFeaturizer.encode_block (the
// Python encoder for the same raw rows — pinned by the parity test).
inline void featurize(float lat_ms, int status, float req_b, float rsp_b,
                      int col, float sign, float drift, float* x) {
    memset(x, 0, FEATURE_DIM * sizeof(float));
    x[0] = log1pf(fmaxf(lat_ms, 0.0f));
    const int sc = status / 100;
    if (sc >= 1 && sc <= 5) x[STATUS_ONEHOT_OFF + sc - 1] = 1.0f;
    x[8] = log1pf(fmaxf(req_b, 0.0f));
    x[9] = log1pf(fmaxf(rsp_b, 0.0f));
    x[10] = log1pf(1.0f);  // engine rows carry no concurrency
    if (col >= 0 && col < FEATURE_DIM) x[col] += sign;
    x[31] = 1.0f;
    const float ad = fabsf(drift);
    const float s = drift > 0.0f ? 1.0f : (drift < 0.0f ? -1.0f : 0.0f);
    x[32] = s * log1pf(ad);
}

// ---- per-engine accounting -------------------------------------------------

struct ScoreStats {  // guarded by the engine's mu
    uint64_t scored = 0;    // rows scored in-engine
    uint64_t unscored = 0;  // rows passed through (no weights / no feat)
    uint64_t ns_hist[SCORE_HIST_BUCKETS] = {0};
    void record(uint64_t ns) {
        int b = 0;
        uint64_t v = ns;
        while (v > 1 && b < SCORE_HIST_BUCKETS - 1) { v >>= 1; b++; }
        ns_hist[b]++;
        scored++;
    }
};

inline uint64_t now_ns() {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1'000'000'000ull + (uint64_t)ts.tv_nsec;
}

// Append the engine's "native_scorer" stats block (caller holds the
// engine mu for the ScoreStats half; slab fields are atomics).
inline void stats_json(const Slab& slab, const ScoreStats& st,
                       std::string* s) {
    char tmp[256];
    snprintf(tmp, sizeof(tmp),
             "\"native_scorer\":{\"weights\":%s,\"version\":%u,"
             "\"crc\":%u,\"swaps\":%llu,\"retries\":%llu,"
             "\"scored\":%llu,\"unscored\":%llu,\"score_ns_hist\":[",
             slab.active.load(std::memory_order_acquire) >= 0
                 ? "true" : "false",
             slab.version.load(std::memory_order_relaxed),
             slab.crc.load(std::memory_order_relaxed),
             (unsigned long long)slab.swaps.load(std::memory_order_relaxed),
             (unsigned long long)slab.retries.load(
                 std::memory_order_relaxed),
             (unsigned long long)st.scored,
             (unsigned long long)st.unscored);
    *s += tmp;
    for (int i = 0; i < SCORE_HIST_BUCKETS; i++) {
        if (i) *s += ",";
        snprintf(tmp, sizeof(tmp), "%llu",
                 (unsigned long long)st.ns_hist[i]);
        *s += tmp;
    }
    *s += "]}";
}

// ---- deterministic test blob (stress drivers + C-level tests) --------------

inline void put_u32(std::vector<uint8_t>* v, uint32_t x) {
    const uint8_t* p = (const uint8_t*)&x;
    v->insert(v->end(), p, p + 4);
}

inline void put_f32(std::vector<uint8_t>* v, float f) {
    const uint8_t* p = (const uint8_t*)&f;
    v->insert(v->end(), p, p + 4);
}

// A small, valid blob with seeded pseudo-random weights; the stress
// drivers publish alternating seeds while traffic scores concurrently.
inline void build_test_blob(std::vector<uint8_t>* out, uint32_t version,
                            int quant, uint32_t seed) {
    out->clear();
    const char magic[8] = {'L', '5', 'D', 'W', 'T', 'S', '0', '1'};
    out->insert(out->end(), magic, magic + 8);
    const int in_dim = FEATURE_DIM;
    const int dims_enc[] = {in_dim, 32, 8};    // two enc layers
    const int dims_dec[] = {8, 32, in_dim};    // mirrored back
    const int dims_cls[] = {8, 16, 1};
    put_u32(out, version);
    put_u32(out, (uint32_t)quant);
    put_u32(out, (uint32_t)in_dim);
    put_u32(out, 2);
    put_u32(out, 2);
    put_u32(out, 2);
    put_f32(out, 0.5f);
    uint32_t st = seed * 2654435761u + 1u;
    auto rnd = [&st]() {
        st = st * 1664525u + 1013904223u;
        return ((float)(st >> 8) / (float)(1u << 24) - 0.5f) * 0.2f;
    };
    for (int i = 0; i < in_dim; i++) put_f32(out, rnd());        // mu
    for (int i = 0; i < in_dim; i++) put_f32(out, 1.0f);         // var
    auto layer = [&](int rows, int cols) {
        put_u32(out, (uint32_t)rows);
        put_u32(out, (uint32_t)cols);
        for (int j = 0; j < cols; j++) put_f32(out, rnd());      // bias
        if (quant == 0) {
            for (int i = 0; i < rows * cols; i++) put_f32(out, rnd());
        } else {
            for (int j = 0; j < cols; j++) put_f32(out, 0.01f);  // scale
            for (int i = 0; i < rows * cols; i++)
                out->push_back((uint8_t)(int8_t)(int)(rnd() * 600.0f));
        }
    };
    for (int k = 0; k < 2; k++) layer(dims_enc[k], dims_enc[k + 1]);
    for (int k = 0; k < 2; k++) layer(dims_dec[k], dims_dec[k + 1]);
    for (int k = 0; k < 2; k++) layer(dims_cls[k], dims_cls[k + 1]);
    put_u32(out, crc32_of(out->data(), out->size()));
}

}  // namespace l5dscore
