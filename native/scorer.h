// scorer.h — in-data-plane anomaly scoring for the native engines.
//
// A dependency-free evaluator for the distilled anomaly model (the
// autoencoder + classifier dense stack of models/anomaly.py): a request
// retired by an epoll engine is featurized AND scored without leaving
// the engine thread — the Taurus/FENIX move of evaluating a small model
// inside the forwarding element itself. Weights arrive from Python as a
// versioned, CRC'd flat blob (lifecycle/export.py emits it on model
// promote/hot-swap) into a double-buffered, seqlock-style slab: readers
// never block on a publish, a publish never pauses the data plane, and
// a reader that raced a buffer flip retries instead of evaluating torn
// weights (slab_score's recheck; `retries` counts them).
//
// Layout contract (must mirror lifecycle/export.py exactly). A "model
// section" is the quant-tagged dense stack:
//
//   u32 version | u32 quant (0=f32, 1=int8, 2=int4)
//   | u32 in_dim | u32 n_enc | u32 n_dec | u32 n_cls | f32 recon_weight
//   | f32 mu[in_dim] | f32 var[in_dim]
//   | per layer (enc..., dec..., cls...):
//       u32 rows | u32 cols | f32 b[cols]
//       | quant 0: f32 w[rows*cols]        (row-major, w[i][j] = in i -> out j)
//       | quant 1: f32 scale[cols] | i8 w[rows*cols]
//       | quant 2: f32 scale[cols] | u8 packed[(rows*cols+1)/2]
//                  (two 4-bit two's-complement weights per byte, low
//                  nibble first, row-major order, values in [-7, 7])
//
// Three blob kinds share it, each CRC32-tailed (zlib polynomial, over
// everything before the trailing u32), all fields little-endian:
//
//   "L5DWTS01" | <model section> | crc            (one global model)
//   "L5DWTS02" | u32 generation | u32 n_heads
//              | <model section>                  (the base model)
//              | per head, route_hash ascending:
//                  u32 route_hash | <model section>
//              | crc                              (specialist bank)
//   "L5DWTD01" | u32 base_generation | u32 new_generation | u32 n_ops
//              | per op: u32 op (0=upsert, 1=remove) | u32 route_hash
//                        | upsert: <model section>
//              | crc                              (per-route delta patch)
//
// int8/int4 weights dequantize per OUTPUT column (w_f32 ≈ scale[j] *
// w_q) and accumulate in f32 — quantization error stays a
// weight-rounding effect and never compounds through the accumulation.
// A delta patches the CURRENTLY ACTIVE bank: it is rejected unless its
// base_generation matches, so a patch can never apply over the wrong
// bank (an engine that restarted re-requests a full publish instead).

#pragma once

#include <math.h>
#include <sched.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>
#include <time.h>

#include <atomic>
#include <mutex>
#include <string>
#include <vector>

namespace l5dscore {

// Feature schema (models/features.py FEATURE_DIM + column layout); the
// featurizer below mirrors telemetry/linerate.NativeFeaturizer, which
// is the one Python-side encoder for engine rows.
constexpr int FEATURE_DIM = 36;
constexpr int STATUS_ONEHOT_OFF = 1;
constexpr int MAX_WIDTH = 1024;   // widest layer a blob may carry
constexpr int MAX_LAYERS = 16;    // per group (enc/dec/cls)
constexpr int MAX_HEADS = 256;    // specialist heads a bank may carry
constexpr int MAX_DELTA_OPS = 64; // ops one delta patch may carry
constexpr int SCORE_HIST_BUCKETS = 32;  // log2(ns) buckets

constexpr uint32_t QUANT_F32 = 0;
constexpr uint32_t QUANT_INT8 = 1;
constexpr uint32_t QUANT_INT4 = 2;

// ---- crc32 (zlib polynomial; must match Python zlib.crc32) -----------------

struct Crc32Table {
    uint32_t t[256];
    Crc32Table() {
        for (uint32_t i = 0; i < 256; i++) {
            uint32_t c = i;
            for (int k = 0; k < 8; k++)
                c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : (c >> 1);
            t[i] = c;
        }
    }
};

inline uint32_t crc32_of(const uint8_t* p, size_t n) {
    static Crc32Table tbl;  // C++11 magic static: thread-safe init
    uint32_t c = 0xFFFFFFFFu;
    for (size_t i = 0; i < n; i++)
        c = tbl.t[(c ^ p[i]) & 0xFFu] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

// ---- model -----------------------------------------------------------------

struct Layer {
    int rows = 0, cols = 0;
    std::vector<float> w;       // f32 weights (quant 0)
    std::vector<int8_t> wq;     // int8 weights (quant 1; int4 unpacks
                                // into the same [-7, 7] int8 storage)
    std::vector<float> scale;   // per-output-column dequant (quant 1/2)
    std::vector<float> b;
};

struct Model {
    uint32_t version = 0;
    uint32_t crc = 0;       // the enclosing blob's trailing crc32
    uint32_t quant = 0;     // 0 = f32, 1 = int8, 2 = int4
    int in_dim = 0;
    int n_enc = 0, n_dec = 0, n_cls = 0;
    float recon_weight = 0.5f;
    std::vector<float> mu;
    std::vector<float> inv_std;  // precomputed 1/sqrt(var + 1e-2)
    std::vector<Layer> layers;   // enc..., dec..., cls...
};

// A specialist bank: the base (global) model plus per-route heads
// selected by the route hash stamped on each engine route. Heads are
// kept sorted by hash (the wire format requires ascending order), so
// selection is one binary search per scored row. A v1 blob parses into
// a headless bank whose generation is the model version.
struct Bank {
    Model base;
    uint32_t generation = 0;
    std::vector<std::pair<uint32_t, Model>> heads;  // sorted by hash

    const Model* select(uint32_t route_hash) const {
        size_t lo = 0, hi = heads.size();
        while (lo < hi) {
            const size_t mid = (lo + hi) / 2;
            if (heads[mid].first < route_hash) lo = mid + 1;
            else hi = mid;
        }
        if (lo < heads.size() && heads[lo].first == route_hash)
            return &heads[lo].second;
        return nullptr;
    }
};

// bounds-checked little-endian reader
struct Cursor {
    const uint8_t* p;
    size_t len, off = 0;
    bool ok = true;
    Cursor(const uint8_t* d, size_t n) : p(d), len(n) {}
    bool take(void* out, size_t n) {
        if (!ok || off + n > len) { ok = false; return false; }
        memcpy(out, p + off, n);
        off += n;
        return true;
    }
    uint32_t u32() { uint32_t v = 0; take(&v, 4); return v; }
    float f32() { float v = 0; take(&v, 4); return v; }
    bool floats(std::vector<float>* out, size_t n) {
        if (!ok || off + n * 4 > len) { ok = false; return false; }
        out->resize(n);
        memcpy(out->data(), p + off, n * 4);
        off += n * 4;
        return true;
    }
    bool bytes(std::vector<int8_t>* out, size_t n) {
        if (!ok || off + n > len) { ok = false; return false; }
        out->resize(n);
        memcpy(out->data(), p + off, n);
        off += n;
        return true;
    }
    const uint8_t* raw(size_t n) {
        if (!ok || off + n > len) { ok = false; return nullptr; }
        const uint8_t* r = p + off;
        off += n;
        return r;
    }
};

inline bool fail(char* err, size_t errcap, const char* msg) {
    if (err != nullptr && errcap > 0) {
        strncpy(err, msg, errcap - 1);
        err[errcap - 1] = 0;
    }
    return false;
}

// Parse + fully validate ONE model section (version through layers).
// Geometry is checked end to end (layer chain, bottleneck consistency,
// classifier output width 1) so a published section can never index
// out of bounds at eval time.
inline bool parse_model_section(Cursor* cp, Model* out, char* err,
                                size_t errcap) {
    Cursor& c = *cp;
    Model m;
    m.version = c.u32();
    m.quant = c.u32();
    uint32_t in_dim = c.u32();
    uint32_t n_enc = c.u32(), n_dec = c.u32(), n_cls = c.u32();
    m.recon_weight = c.f32();
    if (!c.ok) return fail(err, errcap, "weight blob header truncated");
    if (m.quant > QUANT_INT4)
        return fail(err, errcap, "unknown weight quantization");
    if (in_dim < 1 || in_dim > MAX_WIDTH)
        return fail(err, errcap, "weight blob in_dim out of range");
    if (n_enc < 1 || n_dec < 1 || n_cls < 1 || n_enc > MAX_LAYERS ||
        n_dec > MAX_LAYERS || n_cls > MAX_LAYERS)
        return fail(err, errcap, "weight blob layer counts out of range");
    if (!(m.recon_weight >= 0.0f && m.recon_weight <= 1.0f))
        return fail(err, errcap, "recon_weight out of [0, 1]");
    m.in_dim = (int)in_dim;
    m.n_enc = (int)n_enc;
    m.n_dec = (int)n_dec;
    m.n_cls = (int)n_cls;
    if (!c.floats(&m.mu, in_dim))
        return fail(err, errcap, "weight blob mu truncated");
    std::vector<float> var;
    if (!c.floats(&var, in_dim))
        return fail(err, errcap, "weight blob var truncated");
    m.inv_std.resize(in_dim);
    for (uint32_t i = 0; i < in_dim; i++) {
        // soft variance floor, matching models.anomaly.normalize_features
        m.inv_std[i] = 1.0f / sqrtf(var[i] + 1e-2f);
        if (!(m.inv_std[i] == m.inv_std[i]))  // NaN guard
            return fail(err, errcap, "weight blob var not finite");
    }
    int total = m.n_enc + m.n_dec + m.n_cls;
    m.layers.resize(total);
    for (int k = 0; k < total; k++) {
        Layer& L = m.layers[k];
        L.rows = (int)c.u32();
        L.cols = (int)c.u32();
        if (!c.ok || L.rows < 1 || L.cols < 1 || L.rows > MAX_WIDTH ||
            L.cols > MAX_WIDTH)
            return fail(err, errcap, "weight blob layer dims out of range");
        if (!c.floats(&L.b, L.cols))
            return fail(err, errcap, "weight blob bias truncated");
        size_t n = (size_t)L.rows * L.cols;
        if (m.quant == QUANT_F32) {
            if (!c.floats(&L.w, n))
                return fail(err, errcap, "weight blob weights truncated");
        } else if (m.quant == QUANT_INT8) {
            if (!c.floats(&L.scale, L.cols))
                return fail(err, errcap, "weight blob scales truncated");
            if (!c.bytes(&L.wq, n))
                return fail(err, errcap, "weight blob weights truncated");
        } else {  // int4: two's-complement nibbles, low nibble first
            if (!c.floats(&L.scale, L.cols))
                return fail(err, errcap, "weight blob scales truncated");
            const uint8_t* packed = c.raw((n + 1) / 2);
            if (packed == nullptr)
                return fail(err, errcap, "weight blob weights truncated");
            L.wq.resize(n);
            for (size_t i = 0; i < n; i++) {
                const uint8_t nib = (i & 1) ? (packed[i / 2] >> 4)
                                            : (packed[i / 2] & 0x0F);
                L.wq[i] = (int8_t)(((int)(nib ^ 8u)) - 8);  // sign-extend
                if (L.wq[i] < -7 || L.wq[i] > 7)
                    return fail(err, errcap,
                                "int4 weight outside [-7, 7]");
            }
        }
    }
    // geometry: enc chain from in_dim to the bottleneck, dec mirrors it
    // back to in_dim, cls maps the bottleneck to one logit
    int w = m.in_dim;
    for (int k = 0; k < m.n_enc; k++) {
        if (m.layers[k].rows != w)
            return fail(err, errcap, "encoder layer chain mismatch");
        w = m.layers[k].cols;
    }
    int bottleneck = w;
    for (int k = 0; k < m.n_dec; k++) {
        if (m.layers[m.n_enc + k].rows != w)
            return fail(err, errcap, "decoder layer chain mismatch");
        w = m.layers[m.n_enc + k].cols;
    }
    if (w != m.in_dim)
        return fail(err, errcap, "decoder does not reconstruct in_dim");
    w = bottleneck;
    for (int k = 0; k < m.n_cls; k++) {
        if (m.layers[m.n_enc + m.n_dec + k].rows != w)
            return fail(err, errcap, "classifier layer chain mismatch");
        w = m.layers[m.n_enc + m.n_dec + k].cols;
    }
    if (w != 1)
        return fail(err, errcap, "classifier head must end at width 1");
    *out = std::move(m);
    return true;
}

// crc + magic framing shared by all three blob kinds; returns the
// payload Cursor on success.
inline bool open_blob(const uint8_t* data, size_t len, const char* magic,
                      uint32_t* crc_out, char* err, size_t errcap) {
    if (len < 8 + 4)
        return fail(err, errcap, "weight blob truncated");
    if (memcmp(data, magic, 8) != 0)
        return fail(err, errcap, "bad weight blob magic");
    uint32_t crc_stored;
    memcpy(&crc_stored, data + len - 4, 4);
    if (crc32_of(data, len - 4) != crc_stored)
        return fail(err, errcap, "weight blob crc mismatch");
    *crc_out = crc_stored;
    return true;
}

// v1 blob -> one Model (the pre-bank format; still the export shape
// when no specialists exist).
inline bool parse_blob(const uint8_t* data, size_t len, Model* out,
                       char* err, size_t errcap) {
    uint32_t crc = 0;
    if (!open_blob(data, len, "L5DWTS01", &crc, err, errcap))
        return false;
    Cursor c(data + 8, len - 8 - 4);
    Model m;
    if (!parse_model_section(&c, &m, err, errcap)) return false;
    if (c.off != c.len)
        return fail(err, errcap, "weight blob has trailing bytes");
    m.crc = crc;
    *out = std::move(m);
    return true;
}

// v2 bank blob -> base + sorted specialist heads. Accepts a v1 blob
// too (headless bank, generation = model version): `L5DWTS01` readers
// and writers keep working unchanged through this one entry point.
inline bool parse_bank_blob(const uint8_t* data, size_t len, Bank* out,
                            char* err, size_t errcap) {
    if (len >= 8 && memcmp(data, "L5DWTS01", 8) == 0) {
        Model m;
        if (!parse_blob(data, len, &m, err, errcap)) return false;
        Bank b;
        b.generation = m.version;
        b.base = std::move(m);
        *out = std::move(b);
        return true;
    }
    uint32_t crc = 0;
    if (!open_blob(data, len, "L5DWTS02", &crc, err, errcap))
        return false;
    Cursor c(data + 8, len - 8 - 4);
    Bank b;
    b.generation = c.u32();
    uint32_t n_heads = c.u32();
    if (!c.ok) return fail(err, errcap, "bank blob header truncated");
    if (n_heads > MAX_HEADS)
        return fail(err, errcap, "bank blob head count out of range");
    if (!parse_model_section(&c, &b.base, err, errcap)) return false;
    b.base.crc = crc;
    b.heads.reserve(n_heads);
    uint32_t prev_hash = 0;
    for (uint32_t k = 0; k < n_heads; k++) {
        uint32_t rh = c.u32();
        if (!c.ok) return fail(err, errcap, "bank blob head truncated");
        if (k > 0 && rh <= prev_hash)
            return fail(err, errcap,
                        "bank blob heads not strictly ascending");
        prev_hash = rh;
        Model head;
        if (!parse_model_section(&c, &head, err, errcap)) return false;
        if (head.in_dim != b.base.in_dim)
            return fail(err, errcap,
                        "bank head in_dim differs from base");
        head.crc = crc;
        b.heads.emplace_back(rh, std::move(head));
    }
    if (c.off != c.len)
        return fail(err, errcap, "bank blob has trailing bytes");
    *out = std::move(b);
    return true;
}

// ---- per-route delta patches -----------------------------------------------

constexpr uint32_t DELTA_OP_UPSERT = 0;
constexpr uint32_t DELTA_OP_REMOVE = 1;

struct DeltaOp {
    uint32_t op = DELTA_OP_UPSERT;
    uint32_t route_hash = 0;
    Model head;  // upsert only
};

struct Delta {
    uint32_t base_generation = 0;
    uint32_t new_generation = 0;
    std::vector<DeltaOp> ops;
};

inline bool parse_delta_blob(const uint8_t* data, size_t len, Delta* out,
                             char* err, size_t errcap) {
    uint32_t crc = 0;
    if (!open_blob(data, len, "L5DWTD01", &crc, err, errcap))
        return false;
    Cursor c(data + 8, len - 8 - 4);
    Delta d;
    d.base_generation = c.u32();
    d.new_generation = c.u32();
    uint32_t n_ops = c.u32();
    if (!c.ok) return fail(err, errcap, "delta blob header truncated");
    if (d.new_generation <= d.base_generation)
        return fail(err, errcap,
                    "delta new_generation must exceed base_generation");
    if (n_ops < 1 || n_ops > MAX_DELTA_OPS)
        return fail(err, errcap, "delta blob op count out of range");
    d.ops.resize(n_ops);
    for (uint32_t k = 0; k < n_ops; k++) {
        DeltaOp& op = d.ops[k];
        op.op = c.u32();
        op.route_hash = c.u32();
        if (!c.ok) return fail(err, errcap, "delta blob op truncated");
        if (op.op == DELTA_OP_UPSERT) {
            if (!parse_model_section(&c, &op.head, err, errcap))
                return false;
            op.head.crc = crc;
        } else if (op.op != DELTA_OP_REMOVE) {
            return fail(err, errcap, "unknown delta op");
        }
    }
    if (c.off != c.len)
        return fail(err, errcap, "delta blob has trailing bytes");
    *out = std::move(d);
    return true;
}

// ---- forward pass ----------------------------------------------------------

// out[j] = act(b[j] + sum_i in[i] * w[i][j]); f32 weights or int8 with
// f32 accumulation. `in` and `out` must not alias.
inline void dense(const Layer& L, const float* in, float* out, bool relu) {
    for (int j = 0; j < L.cols; j++) out[j] = 0.0f;
    if (!L.w.empty()) {
        for (int i = 0; i < L.rows; i++) {
            const float v = in[i];
            const float* wr = &L.w[(size_t)i * L.cols];
            for (int j = 0; j < L.cols; j++) out[j] += v * wr[j];
        }
        for (int j = 0; j < L.cols; j++) out[j] += L.b[j];
    } else {
        for (int i = 0; i < L.rows; i++) {
            const float v = in[i];
            const int8_t* wr = &L.wq[(size_t)i * L.cols];
            for (int j = 0; j < L.cols; j++) out[j] += v * (float)wr[j];
        }
        for (int j = 0; j < L.cols; j++)
            out[j] = out[j] * L.scale[j] + L.b[j];
    }
    if (relu)
        for (int j = 0; j < L.cols; j++)
            if (out[j] < 0.0f) out[j] = 0.0f;
}

// One row through normalize -> autoencoder -> classifier -> blended
// score, mirroring ops/scoring._score_kernel (reconstruction error is
// measured against the NORMALIZED input, which is what the jitted step
// scores after folding normalize_features in).
inline float eval_model(const Model& m, const float* x) {
    float b0[MAX_WIDTH], b1[MAX_WIDTH], zb[MAX_WIDTH], xn[MAX_WIDTH];
    for (int i = 0; i < m.in_dim; i++)
        xn[i] = (x[i] - m.mu[i]) * m.inv_std[i];
    // encoder: relu on every layer (final_act=true in _mlp)
    const float* cur = xn;
    float* dst = b0;
    for (int k = 0; k < m.n_enc; k++) {
        dense(m.layers[k], cur, dst, true);
        cur = dst;
        dst = (dst == b0) ? b1 : b0;
    }
    const int zw = m.layers[m.n_enc - 1].cols;
    memcpy(zb, cur, (size_t)zw * sizeof(float));
    // decoder: relu except the last layer
    cur = zb;
    dst = b0;
    for (int k = 0; k < m.n_dec; k++) {
        dense(m.layers[m.n_enc + k], cur, dst, k < m.n_dec - 1);
        cur = dst;
        dst = (dst == b0) ? b1 : b0;
    }
    float err = 0.0f;
    for (int i = 0; i < m.in_dim; i++) {
        const float d = cur[i] - xn[i];
        err += d * d;
    }
    err /= (float)m.in_dim;
    // classifier head from the bottleneck: relu except the last layer
    cur = zb;
    dst = b0;
    for (int k = 0; k < m.n_cls; k++) {
        dense(m.layers[m.n_enc + m.n_dec + k], cur, dst, k < m.n_cls - 1);
        cur = dst;
        dst = (dst == b0) ? b1 : b0;
    }
    const float logit = cur[0];
    const float recon_score = tanhf(err);
    const float cls_score = 1.0f / (1.0f + expf(-logit));
    return m.recon_weight * recon_score
        + (1.0f - m.recon_weight) * cls_score;
}

// ---- double-buffered weight slab -------------------------------------------

// Publishes go to the inactive buffer; the flip is one release-store of
// `active`. Readers take a per-buffer refcount and RE-CHECK `active`
// before touching weights — a reader that raced a flip backs off and
// retries (counted in `retries`), so it can never evaluate a buffer a
// concurrent publish is rewriting. The publisher in turn drains the
// target buffer's refcount before writing, so it never rewrites under
// a reader that already passed its recheck. No reader ever blocks on a
// lock; the (rare) publisher spin is bounded by one in-flight eval.
struct Slab {
    std::mutex write_mu;  // serializes publishers only
    Bank bufs[2];
    std::atomic<int> active{-1};  // -1 = nothing published yet
    std::atomic<uint32_t> readers[2] = {{0}, {0}};
    std::atomic<uint64_t> swaps{0};
    std::atomic<uint64_t> delta_swaps{0};
    std::atomic<uint64_t> retries{0};
    std::atomic<uint32_t> version{0};
    std::atomic<uint32_t> crc{0};
    std::atomic<uint32_t> generation{0};
    std::atomic<uint32_t> n_heads{0};
};

inline bool slab_has_weights(const Slab* s) {
    return s->active.load(std::memory_order_acquire) >= 0;
}

// Score one row. use_head selects the route's specialist when the bank
// carries one (falling back to the base model otherwise). Returns -1
// when nothing is published, 0 when the base model scored, 1 when a
// specialist head scored.
inline int slab_score_route(Slab* s, uint32_t route_hash, bool use_head,
                            const float* x, float* out) {
    for (;;) {
        const int idx = s->active.load(std::memory_order_acquire);
        if (idx < 0) return -1;
        s->readers[idx].fetch_add(1, std::memory_order_acq_rel);
        if (s->active.load(std::memory_order_acquire) != idx) {
            // a publish flipped (or is flipping) this buffer under us:
            // back off WITHOUT reading any weight bytes and retry
            s->readers[idx].fetch_sub(1, std::memory_order_acq_rel);
            s->retries.fetch_add(1, std::memory_order_relaxed);
            continue;
        }
        const Bank& b = s->bufs[idx];
        const Model* m = use_head ? b.select(route_hash) : nullptr;
        const int specialist = m != nullptr ? 1 : 0;
        const float score = eval_model(m != nullptr ? *m : b.base, x);
        s->readers[idx].fetch_sub(1, std::memory_order_release);
        *out = score;
        return specialist;
    }
}

inline bool slab_score(Slab* s, const float* x, float* out) {
    return slab_score_route(s, 0, false, x, out) >= 0;
}

inline void slab_note_active(Slab* s, int target) {
    // observability mirrors of the target buffer (relaxed: readers of
    // these atomics are stats scrapes, not the eval path)
    s->version.store(s->bufs[target].base.version,
                     std::memory_order_relaxed);
    s->crc.store(s->bufs[target].base.crc, std::memory_order_relaxed);
    s->generation.store(s->bufs[target].generation,
                        std::memory_order_relaxed);
    s->n_heads.store((uint32_t)s->bufs[target].heads.size(),
                     std::memory_order_relaxed);
}

inline void slab_install(Slab* s, Bank&& b) {
    std::lock_guard<std::mutex> g(s->write_mu);
    const int cur = s->active.load(std::memory_order_acquire);
    const int target = cur < 0 ? 0 : 1 - cur;
    // drain stragglers still evaluating the target buffer (bounded:
    // one row eval is microseconds)
    while (s->readers[target].load(std::memory_order_acquire) != 0)
        sched_yield();
    s->bufs[target] = std::move(b);
    slab_note_active(s, target);
    s->active.store(target, std::memory_order_release);
    s->swaps.fetch_add(1, std::memory_order_relaxed);
}

inline void slab_install(Slab* s, Model&& m) {
    Bank b;
    b.generation = m.version;
    b.base = std::move(m);
    slab_install(s, std::move(b));
}

// Apply a parsed per-route delta to the ACTIVE bank under the same
// double-buffered reader-recheck discipline as a full publish: the
// patched copy is built in the inactive buffer (drained of straggler
// readers first), then one release-store flips every reader to it —
// readers never observe a half-patched bank. Rejected (false, with a
// reason) when nothing is published yet, the generation fence fails,
// an upsert widens in_dim, or a remove names an absent head — a
// misdirected rollback must be loud, not a silent no-op.
inline bool slab_apply_delta(Slab* s, const Delta& d, char* err,
                             size_t errcap) {
    std::lock_guard<std::mutex> g(s->write_mu);
    const int cur = s->active.load(std::memory_order_acquire);
    if (cur < 0)
        return fail(err, errcap, "delta publish with no bank installed");
    if (s->bufs[cur].generation != d.base_generation)
        return fail(err, errcap, "delta base generation mismatch");
    const int target = 1 - cur;
    while (s->readers[target].load(std::memory_order_acquire) != 0)
        sched_yield();
    Bank nb = s->bufs[cur];  // deep copy; models are small
    for (const DeltaOp& op : d.ops) {
        if (op.op == DELTA_OP_UPSERT) {
            if (op.head.in_dim != nb.base.in_dim)
                return fail(err, errcap,
                            "delta head in_dim differs from base");
            size_t lo = 0, hi = nb.heads.size();
            while (lo < hi) {
                const size_t mid = (lo + hi) / 2;
                if (nb.heads[mid].first < op.route_hash) lo = mid + 1;
                else hi = mid;
            }
            if (lo < nb.heads.size() &&
                nb.heads[lo].first == op.route_hash) {
                nb.heads[lo].second = op.head;
            } else {
                if (nb.heads.size() >= (size_t)MAX_HEADS)
                    return fail(err, errcap, "bank is full");
                nb.heads.insert(nb.heads.begin() + lo,
                                {op.route_hash, op.head});
            }
        } else {  // remove
            bool found = false;
            for (size_t i = 0; i < nb.heads.size(); i++) {
                if (nb.heads[i].first == op.route_hash) {
                    nb.heads.erase(nb.heads.begin() + i);
                    found = true;
                    break;
                }
            }
            if (!found)
                return fail(err, errcap,
                            "delta removes an absent head");
        }
    }
    nb.generation = d.new_generation;
    s->bufs[target] = std::move(nb);
    slab_note_active(s, target);
    s->active.store(target, std::memory_order_release);
    s->swaps.fetch_add(1, std::memory_order_relaxed);
    s->delta_swaps.fetch_add(1, std::memory_order_relaxed);
    return true;
}

// ---- featurizer ------------------------------------------------------------

// Per-route featurizer state. The dst-path hash column/sign is pushed
// from Python (fp_set_route_feature: the controller knows the dst path,
// the engine does not); the latency EWMA is the robust drift baseline
// of models.features.DstTemporal, updated per retired request. Guarded
// by the engine's `mu` like the rest of the Route.
struct RouteFeat {
    int col = -1;        // dst-path hash column (-1: not pushed yet)
    float sign = 0.0f;
    uint32_t rhash = 0;  // specialist-bank route hash (0: not pushed —
                         // rows score on the base model)
    bool ewma_init = false;
    float ewma = 0.0f;
    float dev = 0.25f;
};

// Returns the drift (lat - EWMA before update) and applies the robust
// update: increments winsorized at 3 deviation-scales so anomalies
// barely drag the baseline toward themselves (DstTemporal's lat_alpha
// 0.05 / dev_clip 3.0 / dev_alpha 0.05).
inline float feat_drift_update(RouteFeat* rf, float lat_ms) {
    if (!rf->ewma_init) {
        rf->ewma_init = true;
        rf->ewma = lat_ms;
        rf->dev = fmaxf(fabsf(lat_ms) * 0.1f, 0.25f);
        return 0.0f;
    }
    const float drift = lat_ms - rf->ewma;
    const float dev = rf->dev;
    const float lim = 3.0f * fmaxf(dev, 0.25f);
    float inc = drift;
    if (inc > lim) inc = lim;
    if (inc < -lim) inc = -lim;
    rf->ewma += 0.05f * inc;
    const float ad = fminf(fabsf(drift), lim);
    rf->dev = dev + 0.05f * (ad - dev);
    return drift;
}

// One engine row -> FEATURE_DIM model features; must stay bit-for-bit
// in step with telemetry/linerate.NativeFeaturizer.encode_block (the
// Python encoder for the same raw rows — pinned by the parity test).
inline void featurize(float lat_ms, int status, float req_b, float rsp_b,
                      int col, float sign, float drift, float* x) {
    memset(x, 0, FEATURE_DIM * sizeof(float));
    x[0] = log1pf(fmaxf(lat_ms, 0.0f));
    const int sc = status / 100;
    if (sc >= 1 && sc <= 5) x[STATUS_ONEHOT_OFF + sc - 1] = 1.0f;
    x[8] = log1pf(fmaxf(req_b, 0.0f));
    x[9] = log1pf(fmaxf(rsp_b, 0.0f));
    x[10] = log1pf(1.0f);  // engine rows carry no concurrency
    if (col >= 0 && col < FEATURE_DIM) x[col] += sign;
    x[31] = 1.0f;
    const float ad = fabsf(drift);
    const float s = drift > 0.0f ? 1.0f : (drift < 0.0f ? -1.0f : 0.0f);
    x[32] = s * log1pf(ad);
}

// One mid-stream sample -> FEATURE_DIM model features, reusing the
// request layout with stream-lifetime semantics: x[0] carries the
// inter-frame gap EWMA where a request row carries latency, req_b the
// bytes-per-frame EWMA, rsp_b the cumulative byte count, the drift
// slot the gap deviation, and the status one-hot flags anomaly frames
// (5xx class) vs nominal cadence (2xx class). Mirrored by
// linkerd_tpu.streams.sentinel for the Python-path fallback scorer.
inline void featurize_stream(float gap_ewma_ms, float bpf_ewma,
                             float total_bytes, float gap_dev_ms,
                             uint32_t anomalies, int col, float sign,
                             float* x) {
    featurize(gap_ewma_ms, anomalies > 0 ? 500 : 200, bpf_ewma,
              total_bytes, col, sign, gap_dev_ms, x);
}

// ---- per-engine accounting -------------------------------------------------

struct ScoreStats {  // guarded by the engine's mu
    uint64_t scored = 0;      // rows scored in-engine
    uint64_t specialist = 0;  // of those, rows a per-route head scored
    uint64_t unscored = 0;  // rows passed through (no weights / no feat)
    uint64_t ns_hist[SCORE_HIST_BUCKETS] = {0};
    void record(uint64_t ns, bool by_specialist = false) {
        int b = 0;
        uint64_t v = ns;
        while (v > 1 && b < SCORE_HIST_BUCKETS - 1) { v >>= 1; b++; }
        ns_hist[b]++;
        scored++;
        if (by_specialist) specialist++;
    }
};

inline uint64_t now_ns() {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1'000'000'000ull + (uint64_t)ts.tv_nsec;
}

// Append the engine's "native_scorer" stats block (caller holds the
// engine mu for the ScoreStats half; slab fields are atomics).
inline void stats_json(const Slab& slab, const ScoreStats& st,
                       std::string* s) {
    char tmp[384];
    snprintf(tmp, sizeof(tmp),
             "\"native_scorer\":{\"weights\":%s,\"version\":%u,"
             "\"crc\":%u,\"generation\":%u,\"heads\":%u,"
             "\"swaps\":%llu,\"delta_swaps\":%llu,\"retries\":%llu,"
             "\"scored\":%llu,\"specialist_scored\":%llu,"
             "\"unscored\":%llu,\"score_ns_hist\":[",
             slab.active.load(std::memory_order_acquire) >= 0
                 ? "true" : "false",
             slab.version.load(std::memory_order_relaxed),
             slab.crc.load(std::memory_order_relaxed),
             slab.generation.load(std::memory_order_relaxed),
             slab.n_heads.load(std::memory_order_relaxed),
             (unsigned long long)slab.swaps.load(std::memory_order_relaxed),
             (unsigned long long)slab.delta_swaps.load(
                 std::memory_order_relaxed),
             (unsigned long long)slab.retries.load(
                 std::memory_order_relaxed),
             (unsigned long long)st.scored,
             (unsigned long long)st.specialist,
             (unsigned long long)st.unscored);
    *s += tmp;
    for (int i = 0; i < SCORE_HIST_BUCKETS; i++) {
        if (i) *s += ",";
        snprintf(tmp, sizeof(tmp), "%llu",
                 (unsigned long long)st.ns_hist[i]);
        *s += tmp;
    }
    *s += "]}";
}

// ---- deterministic test blob (stress drivers + C-level tests) --------------

inline void put_u32(std::vector<uint8_t>* v, uint32_t x) {
    const uint8_t* p = (const uint8_t*)&x;
    v->insert(v->end(), p, p + 4);
}

inline void put_f32(std::vector<uint8_t>* v, float f) {
    const uint8_t* p = (const uint8_t*)&f;
    v->insert(v->end(), p, p + 4);
}

// One model section with seeded pseudo-random weights (the shared body
// of every deterministic test blob below).
inline void put_test_section(std::vector<uint8_t>* out, uint32_t version,
                             int quant, uint32_t seed) {
    const int in_dim = FEATURE_DIM;
    const int dims_enc[] = {in_dim, 32, 8};    // two enc layers
    const int dims_dec[] = {8, 32, in_dim};    // mirrored back
    const int dims_cls[] = {8, 16, 1};
    put_u32(out, version);
    put_u32(out, (uint32_t)quant);
    put_u32(out, (uint32_t)in_dim);
    put_u32(out, 2);
    put_u32(out, 2);
    put_u32(out, 2);
    put_f32(out, 0.5f);
    uint32_t st = seed * 2654435761u + 1u;
    auto rnd = [&st]() {
        st = st * 1664525u + 1013904223u;
        return ((float)(st >> 8) / (float)(1u << 24) - 0.5f) * 0.2f;
    };
    for (int i = 0; i < in_dim; i++) put_f32(out, rnd());        // mu
    for (int i = 0; i < in_dim; i++) put_f32(out, 1.0f);         // var
    auto layer = [&](int rows, int cols) {
        put_u32(out, (uint32_t)rows);
        put_u32(out, (uint32_t)cols);
        for (int j = 0; j < cols; j++) put_f32(out, rnd());      // bias
        if (quant == (int)QUANT_F32) {
            for (int i = 0; i < rows * cols; i++) put_f32(out, rnd());
        } else if (quant == (int)QUANT_INT8) {
            for (int j = 0; j < cols; j++) put_f32(out, 0.01f);  // scale
            for (int i = 0; i < rows * cols; i++)
                out->push_back((uint8_t)(int8_t)(int)(rnd() * 600.0f));
        } else {  // int4: packed nibbles in [-7, 7], low nibble first
            for (int j = 0; j < cols; j++) put_f32(out, 0.02f);  // scale
            const int n = rows * cols;
            for (int i = 0; i < n; i += 2) {
                int a = (int)(rnd() * 60.0f);
                int bql = (i + 1 < n) ? (int)(rnd() * 60.0f) : 0;
                if (a < -7) a = -7;
                if (a > 7) a = 7;
                if (bql < -7) bql = -7;
                if (bql > 7) bql = 7;
                out->push_back((uint8_t)((a & 0x0F) |
                                         ((bql & 0x0F) << 4)));
            }
        }
    };
    for (int k = 0; k < 2; k++) layer(dims_enc[k], dims_enc[k + 1]);
    for (int k = 0; k < 2; k++) layer(dims_dec[k], dims_dec[k + 1]);
    for (int k = 0; k < 2; k++) layer(dims_cls[k], dims_cls[k + 1]);
}

// A small, valid v1 blob with seeded pseudo-random weights; the stress
// drivers publish alternating seeds while traffic scores concurrently.
inline void build_test_blob(std::vector<uint8_t>* out, uint32_t version,
                            int quant, uint32_t seed) {
    out->clear();
    const char magic[8] = {'L', '5', 'D', 'W', 'T', 'S', '0', '1'};
    out->insert(out->end(), magic, magic + 8);
    put_test_section(out, version, quant, seed);
    put_u32(out, crc32_of(out->data(), out->size()));
}

// A valid v2 bank blob: seeded base + n_heads specialists keyed
// 1000+k (ascending, as the wire format requires).
inline void build_test_bank_blob(std::vector<uint8_t>* out,
                                 uint32_t generation, int quant,
                                 uint32_t seed, uint32_t n_heads) {
    out->clear();
    const char magic[8] = {'L', '5', 'D', 'W', 'T', 'S', '0', '2'};
    out->insert(out->end(), magic, magic + 8);
    put_u32(out, generation);
    put_u32(out, n_heads);
    put_test_section(out, generation, quant, seed);
    for (uint32_t k = 0; k < n_heads; k++) {
        put_u32(out, 1000u + k);
        put_test_section(out, generation, quant, seed + 17u * (k + 1));
    }
    put_u32(out, crc32_of(out->data(), out->size()));
}

// A valid delta patch upserting one seeded head at `route_hash` (the
// stress drivers' delta leg; remove=true emits a remove op instead).
inline void build_test_delta_blob(std::vector<uint8_t>* out,
                                  uint32_t base_gen, uint32_t new_gen,
                                  uint32_t route_hash, int quant,
                                  uint32_t seed, bool remove = false) {
    out->clear();
    const char magic[8] = {'L', '5', 'D', 'W', 'T', 'D', '0', '1'};
    out->insert(out->end(), magic, magic + 8);
    put_u32(out, base_gen);
    put_u32(out, new_gen);
    put_u32(out, 1);
    put_u32(out, remove ? DELTA_OP_REMOVE : DELTA_OP_UPSERT);
    put_u32(out, route_hash);
    if (!remove) put_test_section(out, new_gen, quant, seed);
    put_u32(out, crc32_of(out->data(), out->size()));
}

}  // namespace l5dscore
