// Shared HTTP/2 primitives for the native data plane: frame helpers and a
// full HPACK codec (RFC 7541: static + dynamic tables, integer/string
// primitives, Huffman decode from the generated Appendix-B table).
//
// Used by h2_fastpath.cpp (the h2/gRPC proxy engine) and h2bench.cpp (the
// out-of-process echo server / load generator). The reference's analogue
// is Netty's HPACK codec consumed by its patched frame codec
// (finagle/h2/src/main/scala/.../netty4/H2FrameCodec.scala); this is an
// independent implementation of the same RFCs, kept deliberately small:
// the proxy re-encodes header lists with incremental indexing (dynamic
// table) and no Huffman on output — legal per RFC 7541 and cheap, while
// decode accepts everything a conforming peer may send.
#pragma once

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <utility>
#include <vector>

#include "huffman_table.h"

namespace h2 {

// ---- frame constants (RFC 7540 §6) ----
enum FrameType : uint8_t {
    DATA = 0x0, HEADERS = 0x1, PRIORITY = 0x2, RST_STREAM = 0x3,
    SETTINGS = 0x4, PUSH_PROMISE = 0x5, PING = 0x6, GOAWAY = 0x7,
    WINDOW_UPDATE = 0x8, CONTINUATION = 0x9,
};

constexpr uint8_t FLAG_END_STREAM = 0x1;
constexpr uint8_t FLAG_ACK = 0x1;
constexpr uint8_t FLAG_END_HEADERS = 0x4;
constexpr uint8_t FLAG_PADDED = 0x8;
constexpr uint8_t FLAG_PRIORITY = 0x20;

enum SettingsId : uint16_t {
    S_HEADER_TABLE_SIZE = 0x1, S_ENABLE_PUSH = 0x2,
    S_MAX_CONCURRENT_STREAMS = 0x3, S_INITIAL_WINDOW_SIZE = 0x4,
    S_MAX_FRAME_SIZE = 0x5, S_MAX_HEADER_LIST_SIZE = 0x6,
};

enum ErrCode : uint32_t {
    NO_ERROR = 0x0, PROTOCOL_ERROR = 0x1, INTERNAL_ERROR = 0x2,
    FLOW_CONTROL_ERROR = 0x3, SETTINGS_TIMEOUT = 0x4, STREAM_CLOSED = 0x5,
    FRAME_SIZE_ERROR = 0x6, REFUSED_STREAM = 0x7, CANCEL = 0x8,
    COMPRESSION_ERROR = 0x9, CONNECT_ERROR = 0xA, ENHANCE_YOUR_CALM = 0xB,
};

constexpr uint32_t DEFAULT_MAX_FRAME = 16384;
constexpr int64_t DEFAULT_WINDOW = 65535;
constexpr const char* PREFACE = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
constexpr size_t PREFACE_LEN = 24;

inline void put_u32(std::string* out, uint32_t v) {
    char b[4] = {(char)(v >> 24), (char)(v >> 16), (char)(v >> 8), (char)v};
    out->append(b, 4);
}

inline uint32_t get_u32(const uint8_t* p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | p[3];
}

// Append a 9-byte frame header (RFC 7540 §4.1).
inline void frame_head(std::string* out, size_t len, uint8_t type,
                       uint8_t flags, uint32_t stream_id) {
    char b[9] = {(char)(len >> 16), (char)(len >> 8), (char)len,
                 (char)type, (char)flags,
                 (char)(stream_id >> 24), (char)(stream_id >> 16),
                 (char)(stream_id >> 8), (char)stream_id};
    out->append(b, 9);
}

inline void write_frame(std::string* out, uint8_t type, uint8_t flags,
                        uint32_t stream_id, const char* payload,
                        size_t len) {
    frame_head(out, len, type, flags, stream_id);
    if (len) out->append(payload, len);
}

inline void write_settings(std::string* out,
                           const std::vector<std::pair<uint16_t, uint32_t>>&
                               kv,
                           bool ack) {
    std::string payload;
    for (auto& s : kv) {
        char b[6] = {(char)(s.first >> 8), (char)s.first,
                     (char)(s.second >> 24), (char)(s.second >> 16),
                     (char)(s.second >> 8), (char)s.second};
        payload.append(b, 6);
    }
    write_frame(out, SETTINGS, ack ? FLAG_ACK : 0, 0, payload.data(),
                payload.size());
}

inline void write_window_update(std::string* out, uint32_t stream_id,
                                uint32_t inc) {
    frame_head(out, 4, WINDOW_UPDATE, 0, stream_id);
    put_u32(out, inc);
}

inline void write_rst(std::string* out, uint32_t stream_id, uint32_t code) {
    frame_head(out, 4, RST_STREAM, 0, stream_id);
    put_u32(out, code);
}

inline void write_goaway(std::string* out, uint32_t last_stream,
                         uint32_t code) {
    frame_head(out, 8, GOAWAY, 0, 0);
    put_u32(out, last_stream);
    put_u32(out, code);
}

// ---- Huffman decode (RFC 7541 §5.2 + Appendix B) ----
// Bit-trie over the canonical code; built once from the generated table
// (native/build.py emits huffman_table.h from hpack.py, the single source
// of truth).
struct HuffTrie {
    struct Node { int32_t child[2] = {-1, -1}; int16_t sym = -1; };
    std::vector<Node> nodes;
    HuffTrie() {
        nodes.emplace_back();
        for (int sym = 0; sym < 257; sym++) {
            uint32_t code = HUFF_CODES[sym];
            int bits = HUFF_BITS[sym];
            int32_t n = 0;
            for (int i = bits - 1; i >= 0; i--) {
                int b = (code >> i) & 1;
                if (i == 0) {
                    // leaf
                    if (nodes[(size_t)n].child[b] < 0) {
                        nodes[(size_t)n].child[b] = (int32_t)nodes.size();
                        nodes.emplace_back();
                    }
                    nodes[(size_t)nodes[(size_t)n].child[b]].sym =
                        (int16_t)sym;
                } else {
                    if (nodes[(size_t)n].child[b] < 0) {
                        nodes[(size_t)n].child[b] = (int32_t)nodes.size();
                        nodes.emplace_back();
                    }
                    n = nodes[(size_t)n].child[b];
                }
            }
        }
    }
};

inline const HuffTrie& huff_trie() {
    static HuffTrie t;
    return t;
}

// false => malformed (COMPRESSION_ERROR).
inline bool huff_decode(const uint8_t* p, size_t n, std::string* out) {
    const HuffTrie& t = huff_trie();
    int32_t node = 0;
    int pad_bits = 0;
    bool pad_ones = true;
    for (size_t i = 0; i < n; i++) {
        uint8_t byte = p[i];
        for (int k = 7; k >= 0; k--) {
            int b = (byte >> k) & 1;
            pad_bits++;
            pad_ones = pad_ones && b == 1;
            node = t.nodes[(size_t)node].child[b];
            if (node < 0) return false;
            int16_t sym = t.nodes[(size_t)node].sym;
            if (sym >= 0) {
                if (sym == 256) return false;  // EOS in data
                out->push_back((char)sym);
                node = 0;
                pad_bits = 0;
                pad_ones = true;
            }
        }
    }
    return pad_bits < 8 && pad_ones;
}

// ---- HPACK (RFC 7541) ----
using Hdr = std::pair<std::string, std::string>;

// RFC 7541 Appendix A: 61-entry static table.
inline const std::vector<Hdr>& hpack_static() {
    static const std::vector<Hdr> t = {
        {":authority", ""}, {":method", "GET"}, {":method", "POST"},
        {":path", "/"}, {":path", "/index.html"}, {":scheme", "http"},
        {":scheme", "https"}, {":status", "200"}, {":status", "204"},
        {":status", "206"}, {":status", "304"}, {":status", "400"},
        {":status", "404"}, {":status", "500"}, {"accept-charset", ""},
        {"accept-encoding", "gzip, deflate"}, {"accept-language", ""},
        {"accept-ranges", ""}, {"accept", ""},
        {"access-control-allow-origin", ""}, {"age", ""}, {"allow", ""},
        {"authorization", ""}, {"cache-control", ""},
        {"content-disposition", ""}, {"content-encoding", ""},
        {"content-language", ""}, {"content-length", ""},
        {"content-location", ""}, {"content-range", ""},
        {"content-type", ""}, {"cookie", ""}, {"date", ""}, {"etag", ""},
        {"expect", ""}, {"expires", ""}, {"from", ""}, {"host", ""},
        {"if-match", ""}, {"if-modified-since", ""}, {"if-none-match", ""},
        {"if-range", ""}, {"if-unmodified-since", ""},
        {"last-modified", ""}, {"link", ""}, {"location", ""},
        {"max-forwards", ""}, {"proxy-authenticate", ""},
        {"proxy-authorization", ""}, {"range", ""}, {"referer", ""},
        {"refresh", ""}, {"retry-after", ""}, {"server", ""},
        {"set-cookie", ""}, {"strict-transport-security", ""},
        {"transfer-encoding", ""}, {"user-agent", ""}, {"vary", ""},
        {"via", ""}, {"www-authenticate", ""},
    };
    return t;
}

inline size_t hpack_entry_size(const Hdr& h) {
    return h.first.size() + h.second.size() + 32;
}

struct HpackTable {
    // newest at front (index 62 in the combined address space)
    std::vector<Hdr> entries;
    size_t size = 0;
    size_t max_size = 4096;

    void add(Hdr h) {
        size_t need = hpack_entry_size(h);
        entries.insert(entries.begin(), std::move(h));
        size += need;
        evict();
        if (need > max_size) {
            entries.clear();
            size = 0;
        }
    }
    void resize(size_t m) {
        max_size = m;
        evict();
    }
    void evict() {
        while (size > max_size && !entries.empty()) {
            size -= hpack_entry_size(entries.back());
            entries.pop_back();
        }
    }
    // 1-based combined index; false => out of range
    bool get(uint64_t idx, Hdr* out) const {
        const auto& st = hpack_static();
        if (idx >= 1 && idx <= st.size()) {
            *out = st[idx - 1];
            return true;
        }
        uint64_t d = idx - st.size() - 1;
        if (d < entries.size()) {
            *out = entries[(size_t)d];
            return true;
        }
        return false;
    }
};

struct HpackDecoder {
    HpackTable table;
    size_t settings_max = 4096;  // our advertised SETTINGS_HEADER_TABLE_SIZE
    // steady-state fast path (mirrors hpack.py Decoder._cache): an
    // identical block decodes identically while the dynamic table is
    // unchanged; blocks that mutate the table invalidate everything
    std::unordered_map<std::string, std::vector<Hdr>> cache;
    size_t cache_bytes = 0;
    static constexpr size_t CACHE_CAP = 256;
    static constexpr size_t CACHE_MAX_BLOCK = 2048;
    static constexpr size_t CACHE_MAX_BYTES = 128 * 1024;

    bool decode(const uint8_t* p, size_t n, std::vector<Hdr>* out) {
        std::string key;
        if (n <= CACHE_MAX_BLOCK) {
            key.assign((const char*)p, n);
            auto it = cache.find(key);
            if (it != cache.end()) {
                out->insert(out->end(), it->second.begin(),
                            it->second.end());
                return true;
            }
        }
        size_t base = out->size();
        bool mutated = false;
        if (!decode_uncached(p, n, out, &mutated)) return false;
        if (mutated) {
            cache.clear();
            cache_bytes = 0;
        } else if (!key.empty()) {
            if (cache.size() >= CACHE_CAP ||
                cache_bytes >= CACHE_MAX_BYTES) {
                cache.clear();
                cache_bytes = 0;
            }
            cache.emplace(std::move(key),
                          std::vector<Hdr>(out->begin() + (long)base,
                                           out->end()));
            cache_bytes += n;
        }
        return true;
    }

    // false => COMPRESSION_ERROR
    bool decode_uncached(const uint8_t* p, size_t n, std::vector<Hdr>* out,
                         bool* mutated) {
        size_t pos = 0;
        while (pos < n) {
            uint8_t b = p[pos];
            if (b & 0x80) {  // indexed
                uint64_t idx;
                if (!dec_int(p, n, &pos, 7, &idx) || idx == 0) return false;
                Hdr h;
                if (!table.get(idx, &h)) return false;
                out->push_back(std::move(h));
            } else if (b & 0x40) {  // literal w/ incremental indexing
                uint64_t idx;
                if (!dec_int(p, n, &pos, 6, &idx)) return false;
                Hdr h;
                if (!read_literal(p, n, &pos, idx, &h)) return false;
                table.add(h);
                *mutated = true;
                out->push_back(std::move(h));
            } else if (b & 0x20) {  // dynamic table size update
                uint64_t sz;
                if (!dec_int(p, n, &pos, 5, &sz)) return false;
                if (sz > settings_max) return false;
                table.resize((size_t)sz);
                *mutated = true;
            } else {  // literal w/o indexing (0x00) / never indexed (0x10)
                uint64_t idx;
                if (!dec_int(p, n, &pos, 4, &idx)) return false;
                Hdr h;
                if (!read_literal(p, n, &pos, idx, &h)) return false;
                out->push_back(std::move(h));
            }
        }
        return true;
    }

 private:
    static bool dec_int(const uint8_t* p, size_t n, size_t* pos,
                        int prefix, uint64_t* out) {
        if (*pos >= n) return false;
        uint64_t limit = (1u << prefix) - 1;
        uint64_t v = p[(*pos)++] & limit;
        if (v < limit) {
            *out = v;
            return true;
        }
        int shift = 0;
        for (;;) {
            if (*pos >= n || shift > 35) return false;
            uint8_t b = p[(*pos)++];
            v += (uint64_t)(b & 0x7F) << shift;
            shift += 7;
            if (!(b & 0x80)) {
                *out = v;
                return true;
            }
        }
    }
    bool read_str(const uint8_t* p, size_t n, size_t* pos,
                  std::string* out) {
        if (*pos >= n) return false;
        bool huff = p[*pos] & 0x80;
        uint64_t len;
        if (!dec_int(p, n, pos, 7, &len)) return false;
        if (*pos + len > n) return false;
        if (huff) {
            if (!huff_decode(p + *pos, (size_t)len, out)) return false;
        } else {
            out->append((const char*)(p + *pos), (size_t)len);
        }
        *pos += (size_t)len;
        return true;
    }
    bool read_literal(const uint8_t* p, size_t n, size_t* pos,
                      uint64_t name_idx, Hdr* out) {
        if (name_idx) {
            Hdr h;
            if (!table.get(name_idx, &h)) return false;
            out->first = std::move(h.first);
        } else {
            if (!read_str(p, n, pos, &out->first)) return false;
        }
        return read_str(p, n, pos, &out->second);
    }
};

struct HpackEncoder {
    HpackTable table;
    int64_t pending_resize = -1;

    // full static-table lookup maps, shared & immutable
    static const std::unordered_map<std::string, int>& static_full() {
        static const std::unordered_map<std::string, int> m = [] {
            std::unordered_map<std::string, int> r;
            const auto& st = hpack_static();
            for (size_t i = 0; i < st.size(); i++) {
                std::string k = st[i].first;
                k.push_back('\0');
                k += st[i].second;
                r.emplace(std::move(k), (int)i + 1);
            }
            return r;
        }();
        return m;
    }
    static const std::unordered_map<std::string, int>& static_name() {
        static const std::unordered_map<std::string, int> m = [] {
            std::unordered_map<std::string, int> r;
            const auto& st = hpack_static();
            for (size_t i = 0; i < st.size(); i++)
                r.emplace(st[i].first, (int)i + 1);
            return r;
        }();
        return m;
    }

    // steady-state cache (mirrors hpack.py Encoder._cache): a header
    // list that encodes without inserting into the dynamic table yields
    // the same block until the table next changes
    std::unordered_map<std::string, std::string> cache;
    static constexpr size_t CACHE_CAP = 256;

    // Honor peer SETTINGS_HEADER_TABLE_SIZE (emit a size update next block)
    void set_max_table_size(size_t sz) {
        if (sz > 4096) sz = 4096;
        pending_resize = (int64_t)sz;
        table.resize(sz);
        cache.clear();
    }

    void encode(const std::vector<Hdr>& headers, std::string* out) {
        // collision-free key: length-prefixed fields (header values may
        // contain ANY octet, so separator bytes alone would collide)
        std::string key;
        key.reserve(64);
        for (const auto& h : headers) {
            put_u32(&key, (uint32_t)h.first.size());
            key += h.first;
            put_u32(&key, (uint32_t)h.second.size());
            key += h.second;
        }
        if (pending_resize < 0) {
            auto it = cache.find(key);
            if (it != cache.end()) {
                out->append(it->second);
                return;
            }
        }
        size_t base = out->size();
        bool inserted = false;
        if (pending_resize >= 0) {
            enc_int((uint64_t)pending_resize, 5, 0x20, out);
            pending_resize = -1;
            inserted = true;  // the size-update prefix must not be cached
        }
        for (const auto& h : headers) {
            int full = 0, name = 0;
            {
                std::string k = h.first;
                k.push_back('\0');
                k += h.second;
                auto it = static_full().find(k);
                if (it != static_full().end()) full = it->second;
            }
            if (!full) {
                auto it = static_name().find(h.first);
                if (it != static_name().end()) name = it->second;
                const auto& st = hpack_static();
                for (size_t i = 0; i < table.entries.size(); i++) {
                    const Hdr& e = table.entries[i];
                    if (e.first == h.first) {
                        int idx = (int)(st.size() + i + 1);
                        if (e.second == h.second) {
                            full = idx;
                            break;
                        }
                        if (!name) name = idx;
                    }
                }
            }
            if (full) {
                enc_int((uint64_t)full, 7, 0x80, out);
                continue;
            }
            // literal with incremental indexing, no Huffman
            if (name) {
                enc_int((uint64_t)name, 6, 0x40, out);
            } else {
                out->push_back(0x40);
                enc_str(h.first, out);
            }
            enc_str(h.second, out);
            table.add(h);  // oversized entries clear the table (RFC §4.4)
            inserted = true;
        }
        if (inserted) {
            // dynamic indices shifted: cached blocks are stale
            cache.clear();
        } else {
            if (cache.size() >= CACHE_CAP) cache.clear();
            cache.emplace(std::move(key),
                          out->substr(base));
        }
    }

 private:
    static void enc_int(uint64_t v, int prefix, uint8_t flags,
                        std::string* out) {
        uint64_t limit = (1u << prefix) - 1;
        if (v < limit) {
            out->push_back((char)(flags | v));
            return;
        }
        out->push_back((char)(flags | limit));
        v -= limit;
        while (v >= 128) {
            out->push_back((char)((v & 0x7F) | 0x80));
            v >>= 7;
        }
        out->push_back((char)v);
    }
    static void enc_str(const std::string& s, std::string* out) {
        enc_int(s.size(), 7, 0x00, out);
        out->append(s);
    }
};

// Strip PADDED (+PRIORITY for HEADERS) from a frame payload. Returns 0
// on success or the RFC 7540 error code to fail the connection with:
// PROTOCOL_ERROR for bad padding (§6.1/6.2), FRAME_SIZE_ERROR when the
// frame is too small for its mandatory PRIORITY section (§4.2). Shared
// by the proxy (both directions) and the bench tool so padding
// validation cannot drift between copies.
inline uint32_t strip_payload(uint8_t flags, bool headers,
                              const uint8_t* p, size_t len, size_t* off,
                              size_t* n) {
    *off = 0;
    *n = len;
    if (flags & FLAG_PADDED) {
        if (!len) return PROTOCOL_ERROR;
        uint8_t pad = p[0];
        if ((size_t)pad + 1 > len) return PROTOCOL_ERROR;
        *off = 1;
        *n = len - 1 - pad;
    }
    if (headers && (flags & FLAG_PRIORITY)) {
        if (*n < 5) return FRAME_SIZE_ERROR;
        *off += 5;
        *n -= 5;
    }
    return 0;
}

// ---- per-connection protocol state shared by proxy & bench ----
struct Session {
    HpackDecoder dec;
    HpackEncoder enc;
    // peer's advertised settings (apply to our sends)
    uint32_t peer_max_frame = DEFAULT_MAX_FRAME;
    int64_t peer_init_win = DEFAULT_WINDOW;
    uint32_t peer_max_streams = 0x7FFFFFFF;
    // connection-level flow control
    int64_t send_win = DEFAULT_WINDOW;  // how much we may send
    uint64_t recv_unacked = 0;          // received but not yet WINDOW_UPDATEd
    // how much the peer may still send us (our advertised window minus
    // consumed DATA): receive-side enforcement — going negative is a
    // FLOW_CONTROL_ERROR on the peer (RFC 7540 §6.9)
    int64_t recv_win = DEFAULT_WINDOW;
    bool preface_seen = false;          // server side: peer preface consumed
    bool settings_acked = false;
    // header-block accumulation (HEADERS..CONTINUATION)
    bool in_headers = false;
    uint32_t hb_stream = 0;
    uint8_t hb_flags = 0;
    std::string hb_buf;
};

}  // namespace h2
