// fastpath: native HTTP/1.1 proxy data-plane engine.
//
// The reference runs its data plane on Netty's native epoll transport
// (project/Deps.scala:24); this is the analogous move for the TPU build:
// the per-request hot loop (accept -> parse head -> route by Host ->
// forward -> stream response) runs in a C++ epoll thread, while Python
// stays the control plane — it resolves logical names through the normal
// binding path (identifier/dtab/namer) and installs concrete routes via
// fp_set_route. Route misses park the connection and surface the host to
// Python through fp_drain_misses; stats and per-request feature rows (for
// the io.l5d.jaxAnomaly telemeter) are drained through fp_stats_json /
// fp_drain_features. Parity anchors: RoutingFactory.scala:154-187 (the
// identify->bind->dispatch loop), Router.scala:313-318 (client stack),
// CHANGES.md:564-565 (the 40k+ qps / sub-1ms p99 figure this exists to
// beat on one core).
//
// Scope: HTTP/1.1 keep-alive + pipelining, Content-Length / chunked /
// bodyless / EOF-delimited messages, per-endpoint upstream pooling,
// least-inflight endpoint pick, Via header append, 400 on unroutable
// host (matching the Python path's unbound behavior), 502 on upstream
// failure. Routers opt in via `fastPath: true`; everything else stays on
// the Python path.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <cstdint>
#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

#include "scorer.h"
#include "stream_track.h"
#include "tenant_guard.h"
#include "tls_engine.h"

namespace {

constexpr size_t MAX_HEAD = 72 * 1024;
constexpr int MAX_EVENTS = 256;
constexpr uint64_t EXCHANGE_TIMEOUT_US = 30'000'000;
constexpr uint64_t ROUTE_WAIT_TIMEOUT_US = 2'000'000;
// an IDLE pooled conn no endpoint references (route churn orphaned it)
// is closed after this much idle time (same constant in h2_fastpath)
constexpr uint64_t ORPHAN_IDLE_TIMEOUT_US = 60'000'000;
constexpr int LAT_BUCKETS = 28;  // log2 us buckets
// Backpressure water marks: when a conn's out-buffer exceeds HIGH, stop
// reading from the peer that produces into it until it drains below LOW.
constexpr size_t OUT_HIGH_WATER = 1 << 20;
constexpr size_t OUT_LOW_WATER = 64 * 1024;
// Bytes a client may buffer beyond the current request (pipelining /
// parked-for-route). Beyond this the conn is abusive: close it.
constexpr size_t MAX_BUFFERED_IN = 1 << 20;
// Handshake budget: a TLS peer that hasn't completed its handshake in
// this window is closed by the sweep (a slow handshaker must not pin
// conn slots; the loop itself never blocks — everything is memory-BIO).
constexpr uint64_t TLS_HS_TIMEOUT_US = 5'000'000;

uint64_t now_us() {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (uint64_t)ts.tv_sec * 1'000'000 + ts.tv_nsec / 1000;
}

void set_nodelay(int fd) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

struct Endpoint {
    uint32_t ip_be = 0;  // network byte order
    uint16_t port = 0;
    int inflight = 0;
    std::vector<int> idle;  // pooled upstream fds (LIFO)
};

struct RouteStats {
    uint64_t requests = 0, success = 0, f4xx = 0, f5xx = 0, conn_fail = 0;
    uint64_t lat_hist[LAT_BUCKETS] = {0};
    void record(int status, uint64_t lat_us) {
        requests++;
        if (status >= 500) f5xx++;
        else if (status >= 400) f4xx++;
        else success++;
        int b = 0;
        uint64_t v = lat_us;
        while (v > 1 && b < LAT_BUCKETS - 1) { v >>= 1; b++; }
        lat_hist[b]++;
    }
};

struct Route {
    uint64_t id = 0;
    std::vector<Endpoint> eps;
    uint32_t next = 0;
    RouteStats stats;
    // in-data-plane scorer state: dst-path hash column (pushed from
    // Python via fp_set_route_feature) + the robust latency-drift EWMA
    l5dscore::RouteFeat feat;
};

struct FeatureRow {
    float route_id, latency_ms, status, req_bytes, rsp_bytes, ts_s;
    // in-data-plane scoring result: `scored` 1.0 when the engine
    // evaluated the native model for this row (score then holds the
    // anomaly score); 0.0 rows fall back to the JAX tier in Python
    float score, scored;
    // tenant hash folded to 24 bits (f32-integer-exact); 0 = no tenant
    float tenant;
    // stream-lifetime key: kind (0 request / 2 tunnel sample), 24-bit
    // stream key (0 = not a stream row), frame seq at sample time —
    // tunnel rows repeat the same key with a growing frame_seq
    float kind, stream, frame_seq;
};

enum class BodyKind { NONE, LENGTH, CHUNKED, EOF_DELIM };

// Incremental body-framing tracker: feed() consumes forwarded bytes and
// reports how many belong to the current message (streamed passthrough,
// mirroring the Python codec's framing rules, protocol/http/codec.py).
struct BodyTracker {
    BodyKind kind = BodyKind::NONE;
    uint64_t remaining = 0;
    enum class C { SIZE, DATA, DATA_CR, DATA_LF, TRAILER, DONE };
    C cstate = C::SIZE;
    std::string linebuf;

    bool done() const {
        if (kind == BodyKind::NONE) return true;
        if (kind == BodyKind::LENGTH) return remaining == 0;
        if (kind == BodyKind::CHUNKED) return cstate == C::DONE;
        return false;  // EOF_DELIM
    }

    // Bytes of `data` belonging to this message, or -1 on bad chunking.
    long feed(const char* data, size_t len) {
        if (kind == BodyKind::NONE) return 0;
        if (kind == BodyKind::EOF_DELIM) return (long)len;
        if (kind == BodyKind::LENGTH) {
            uint64_t take = len < remaining ? len : remaining;
            remaining -= take;
            return (long)take;
        }
        size_t i = 0;
        while (i < len && cstate != C::DONE) {
            char c = data[i];
            switch (cstate) {
            case C::SIZE:
                if (c == '\n') {
                    // parse the size in place: the old substr+strtoull
                    // pattern heap-allocated twice per chunk header
                    size_t sl = linebuf.find(';');
                    if (sl == std::string::npos) sl = linebuf.size();
                    while (sl > 0 && (linebuf[sl - 1] == '\r' ||
                                      linebuf[sl - 1] == ' '))
                        sl--;
                    uint64_t sz = 0;
                    size_t d = 0;
                    for (; d < sl; d++) {
                        char h = linebuf[d];
                        int v;
                        if (h >= '0' && h <= '9') v = h - '0';
                        else if (h >= 'a' && h <= 'f') v = h - 'a' + 10;
                        else if (h >= 'A' && h <= 'F') v = h - 'A' + 10;
                        else break;
                        if (sz > (UINT64_MAX >> 4)) return -1;
                        sz = (sz << 4) | (uint64_t)v;
                    }
                    if (d == 0) return -1;
                    linebuf.clear();
                    if (sz == 0) cstate = C::TRAILER;
                    else { remaining = sz; cstate = C::DATA; }
                } else {
                    if (linebuf.size() > 64) return -1;
                    linebuf.push_back(c);
                }
                i++;
                break;
            case C::DATA: {
                uint64_t take = (len - i) < remaining
                    ? (uint64_t)(len - i) : remaining;
                remaining -= take;
                i += (size_t)take;
                if (remaining == 0) cstate = C::DATA_CR;
                break;
            }
            case C::DATA_CR:
                if (c != '\r') return -1;
                cstate = C::DATA_LF; i++;
                break;
            case C::DATA_LF:
                if (c != '\n') return -1;
                cstate = C::SIZE; i++;
                break;
            case C::TRAILER:
                if (c == '\n') {
                    // end-of-trailers test in place (no per-line copy)
                    bool last = linebuf.empty() ||
                        (linebuf.size() == 1 && linebuf[0] == '\r');
                    linebuf.clear();
                    if (last) cstate = C::DONE;
                } else {
                    if (linebuf.size() > 8192) return -1;
                    linebuf.push_back(c);
                }
                i++;
                break;
            default:
                return -1;
            }
        }
        return (long)i;
    }
};

struct ParsedHead {
    std::string method, uri, version;
    std::vector<std::pair<std::string, std::string>> headers;
    int status = 0;
    size_t head_len = 0;
};

void lower(std::string& s) {
    for (auto& c : s) if (c >= 'A' && c <= 'Z') c += 32;
}

// Case-insensitive ASCII substring probe with zero copies. Header-value
// token tests ("chunked", "close", "upgrade") run on every request; the
// old copy+lower() pattern paid a heap allocation per probe.
bool ihas(const std::string& hay, const char* needle) {
    const size_t nn = strlen(needle);
    if (nn == 0 || hay.size() < nn) return nn == 0;
    for (size_t i = 0; i + nn <= hay.size(); i++) {
        size_t j = 0;
        for (; j < nn; j++) {
            char a = hay[i + j];
            if (a >= 'A' && a <= 'Z') a += 32;
            if (a != needle[j]) break;
        }
        if (j == nn) return true;
    }
    return false;
}

bool parse_head(const std::string& buf, bool is_response, ParsedHead* out) {
    size_t end = buf.find("\r\n\r\n");
    if (end == std::string::npos) return false;
    out->head_len = end + 4;
    size_t pos = 0;
    size_t eol = buf.find("\r\n", pos);
    std::string line = buf.substr(pos, eol - pos);
    if (is_response) {
        size_t s1 = line.find(' ');
        if (s1 == std::string::npos) return false;
        out->version = line.substr(0, s1);
        if (out->version.compare(0, 5, "HTTP/") != 0) return false;
        out->status = atoi(line.c_str() + s1 + 1);
        if (out->status < 100) return false;
    } else {
        size_t s1 = line.find(' ');
        size_t s2 = s1 == std::string::npos
            ? std::string::npos : line.find(' ', s1 + 1);
        if (s2 == std::string::npos) return false;
        out->method = line.substr(0, s1);
        out->uri = line.substr(s1 + 1, s2 - s1 - 1);
        out->version = line.substr(s2 + 1);
        if (out->version != "HTTP/1.1" && out->version != "HTTP/1.0")
            return false;
        if (out->method.empty() || out->uri.empty()) return false;
    }
    pos = eol + 2;
    while (pos < end) {
        eol = buf.find("\r\n", pos);
        if (eol == pos) break;
        size_t colon = buf.find(':', pos);
        if (colon == std::string::npos || colon > eol) return false;
        std::string name = buf.substr(pos, colon - pos);
        if (name.empty()) return false;
        size_t vstart = colon + 1;
        while (vstart < eol && (buf[vstart] == ' ' || buf[vstart] == '\t'))
            vstart++;
        size_t vend = eol;
        while (vend > vstart && (buf[vend - 1] == ' ' ||
                                 buf[vend - 1] == '\t'))
            vend--;
        lower(name);
        out->headers.emplace_back(std::move(name),
                                  buf.substr(vstart, vend - vstart));
        pos = eol + 2;
    }
    return true;
}

const std::string* get_header(const ParsedHead& h, const char* name) {
    for (auto& kv : h.headers)
        if (kv.first == name) return &kv.second;
    return nullptr;
}

bool request_body(const ParsedHead& h, BodyTracker* t) {
    const std::string* te = get_header(h, "transfer-encoding");
    if (te) {
        if (!ihas(*te, "chunked")) return false;
        if (get_header(h, "content-length")) return false;  // smuggling
        t->kind = BodyKind::CHUNKED;
        return true;
    }
    const std::string* cl = get_header(h, "content-length");
    if (cl) {
        char* end = nullptr;
        unsigned long long n = strtoull(cl->c_str(), &end, 10);
        if (end == cl->c_str() || *end) return false;
        t->kind = n ? BodyKind::LENGTH : BodyKind::NONE;
        t->remaining = n;
        return true;
    }
    t->kind = BodyKind::NONE;
    return true;
}

bool response_body(const ParsedHead& h, const std::string& req_method,
                   BodyTracker* t) {
    if (req_method == "HEAD" || h.status == 204 || h.status == 304 ||
        (h.status >= 100 && h.status < 200)) {
        t->kind = BodyKind::NONE;
        return true;
    }
    const std::string* te = get_header(h, "transfer-encoding");
    if (te) {
        if (!ihas(*te, "chunked")) return false;
        t->kind = BodyKind::CHUNKED;
        return true;
    }
    const std::string* cl = get_header(h, "content-length");
    if (cl) {
        char* end = nullptr;
        unsigned long long n = strtoull(cl->c_str(), &end, 10);
        if (end == cl->c_str() || *end) return false;
        t->kind = n ? BodyKind::LENGTH : BodyKind::NONE;
        t->remaining = n;
        return true;
    }
    t->kind = BodyKind::EOF_DELIM;
    return true;
}

struct Conn;

struct Engine {
    int epfd = -1;
    int wakefd = -1;
    std::atomic<bool> running{true};
    pthread_t thread;
    bool thread_started = false;

    std::mutex mu;  // guards routes, misses, features, parked
    std::unordered_map<std::string, Route> routes;
    uint64_t next_route_id = 1;
    std::deque<std::string> misses;
    std::vector<FeatureRow> features;
    size_t features_cap = 65536;
    uint64_t features_dropped = 0;
    // in-data-plane scorer: weight slab has its own (lock-free reader)
    // sync; score_stats is guarded by mu like the feature buffer.
    // `slab` is the slab this engine scores/publishes through — its own
    // embedded one by default, or (multi-worker sharding) one external
    // process-wide slab shared READ-ONLY by every worker's epoll thread
    // (fp_attach_slab, called before fp_start): one publish flips the
    // active buffer for all workers atomically, and the per-buffer
    // reader refcounts aggregate every worker's in-flight evals.
    l5dscore::Slab scorer_slab;
    l5dscore::Slab* slab = &scorer_slab;
    l5dscore::ScoreStats score_stats;
    // tenant accounting + per-tenant quotas (guarded by mu); the
    // extraction mode and guard knobs are installed BEFORE fp_start
    // (wrapper-asserted), so the loop thread reads them unlocked
    l5dtg::TenantTable tenants;
    l5dtg::QuotaMap quotas;
    l5dtg::TenantExtract tenant_ex;
    l5dtg::GuardCfg guard_cfg;
    l5dtg::GuardStats guard;
    // tunnel sentinel: cfg installed BEFORE fp_start (loop reads it
    // unlocked, like guard_cfg); the table and the pending-close queue
    // (Python-side actuation) are guarded by mu
    l5dstream::StreamCfg stream_cfg;
    l5dstream::StreamTable stream_tab;
    std::vector<uint32_t> pending_rst;

    // loop-thread-only state
    std::unordered_map<int, Conn*> conns;
    std::vector<int> listeners;
    // loop-thread-only tunnel-key index (Python closes by key)
    std::unordered_map<uint32_t, Conn*> by_skey;
    uint32_t next_skey = 1;
    std::unordered_map<std::string, std::vector<Conn*>> parked;
    // TLS: contexts are installed from Python BEFORE fp_start (the
    // wrapper asserts), so the loop thread reads them without locking;
    // TlsStats is written by the loop thread under mu (stats readers
    // snapshot under the same mutex).
    l5dtls::Ctx* tls_srv = nullptr;  // accept-leg termination
    l5dtls::Ctx* tls_cli = nullptr;  // upstream-leg origination
    bool tls_cli_verify = false;
    std::unordered_set<int> tls_listeners;
    l5dtls::TlsStats tls_stats;
    // upstream session cache ("ip:port" -> last session) so fresh
    // origination conns resume instead of full-handshaking (loop only)
    std::unordered_map<std::string, l5dtls::SSL_SESSION*> tls_sessions;
    // written by the loop thread, read by fp_stats_json callers: atomic
    std::atomic<uint64_t> accepted{0};
    uint64_t last_sweep_us = 0;
    // loop-thread-only defense state
    l5dtg::SourceTable sources;
    uint32_t hs_inflight = 0;  // accept-leg TLS handshakes in flight
    // write coalescing (h2's discipline ported to h1): conns with bytes
    // staged this wakeup, flushed once per epoll round. defer_ok is
    // false outside the loop's run window so startup/teardown writes
    // degrade to immediate flushes.
    bool defer_ok = false;
    std::vector<Conn*> dirty;
    // one clock read per wakeup: loop_main stamps this right after
    // epoll_wait returns; every loop-thread timestamp consumer reads
    // the stamp (loop_now) instead of issuing its own clock_gettime
    uint64_t now_cache_us = now_us();
    // feature timestamps are relative to engine creation:
    // float32 seconds-since-boot quantizes to >60ms after
    // ~12 days of uptime, breaking inter-arrival math
    uint64_t t0_us = now_us();
};

struct Conn {
    enum class Kind { CLIENT, UPSTREAM };
    enum class St {
        READ_HEAD, WAIT_ROUTE, FORWARD_BODY, READ_RSP, TUNNEL, IDLE,
        CLOSED,
    };
    Kind kind = Kind::CLIENT;
    St st = St::READ_HEAD;
    int fd = -1;
    // byte-tunnel sentinel state (client conns; set at tunnel entry):
    // per-read feature accumulation, native hysteresis, the 24-bit
    // stream key tunnel feature rows carry, and the specialist head
    // pinned when the tunnel's route dispatched
    l5dstream::StreamAccum acc;
    l5dstream::StreamGov gov;
    uint32_t skey = 0;  // 0 = not a tracked tunnel
    uint32_t srhash = 0;
    uint64_t last_frame_us = 0;
    uint64_t tunnel_bytes = 0;
    bool upgrade_req = false;  // request carried Connection: upgrade
    std::string in;
    std::string out;
    std::string req_stash;  // staged request bytes while routing/connecting
    bool want_write = false;
    bool paused = false;  // EPOLLIN off: peer's out-buffer over high water
    bool close_after = false;         // close once current rsp written
    bool close_when_flushed = false;  // close as soon as out drains
    uint64_t deadline_us = 0;

    // exchange state (client conns)
    std::string route_key;
    uint64_t route_id = 0;
    Conn* peer = nullptr;
    BodyTracker req_body, rsp_body;
    std::string req_method;
    uint64_t t_start_us = 0;
    uint64_t req_bytes = 0, rsp_bytes = 0;
    // tenant isolation (client conns): current request's tenant hash,
    // whether it holds a per-tenant inflight slot, and the slowloris
    // budgets the sweep enforces (hdr_start: a partial head has been
    // accumulating since then; body_progress: last request-body byte)
    uint32_t tenant = 0;
    bool tenant_counted = false;
    bool served_one = false;  // completed >=1 head: keep-alive may idle
    uint64_t hdr_start_us = 0;
    uint64_t body_progress_us = 0;
    bool hs_pending = false;  // counted in Engine::hs_inflight
    bool flush_queued = false;  // sitting in Engine::dirty

    // upstream conns
    uint32_t ep_ip_be = 0;
    uint16_t ep_port = 0;
    uint64_t idle_since_us = 0;  // when the conn entered IDLE (pool)
    bool connecting = false;
    bool rsp_head_parsed = false;
    bool rsp_eof_delim = false;
    int rsp_status = 0;

    // TLS adapter (null = cleartext). `out` always holds wire bytes;
    // app plaintext stages in tls->plain_out until flush encrypts it.
    l5dtls::TlsIo* tls = nullptr;

    ~Conn() { delete tls; }
};

// App-data write target: plaintext staging for TLS conns, the wire
// buffer directly for cleartext ones.
std::string* wbuf(Conn* c) {
    return c->tls != nullptr ? &c->tls->plain_out : &c->out;
}

// Total un-sent bytes for watermark decisions (wire + staged plain).
size_t outsz(const Conn* c) {
    return c->out.size()
        + (c->tls != nullptr ? c->tls->plain_out.size() : 0);
}

// The loop thread's clock: one clock_gettime per wakeup (the loop_main
// stamp), not one per timestamp consumer. Hot-path code reads the
// stamp; cold/control-plane code keeps calling now_us() directly.
uint64_t loop_now(Engine* e) { return e->now_cache_us; }

void ep_mod(Engine* e, Conn* c) {
    epoll_event ev{};
    ev.events = (c->paused ? 0 : EPOLLIN)
        | (c->want_write ? EPOLLOUT : 0) | EPOLLRDHUP;
    ev.data.fd = c->fd;
    epoll_ctl(e->epfd, EPOLL_CTL_MOD, c->fd, &ev);
}

void ep_add(Engine* e, Conn* c) {
    epoll_event ev{};
    ev.events = (c->paused ? 0 : EPOLLIN)
        | (c->want_write ? EPOLLOUT : 0) | EPOLLRDHUP;
    ev.data.fd = c->fd;
    epoll_ctl(e->epfd, EPOLL_CTL_ADD, c->fd, &ev);
    e->conns[c->fd] = c;
}

// Pause reading from `producer` while `consumer`'s out-buffer is over the
// high-water mark (resumed by flush_out when it drains below low water).
void maybe_pause_producer(Engine* e, Conn* consumer) {
    Conn* producer = consumer->peer;
    if (producer != nullptr && !producer->paused &&
        outsz(consumer) > OUT_HIGH_WATER) {
        producer->paused = true;
        ep_mod(e, producer);
    }
}

void push_feature(Engine* e, uint64_t route_id, uint64_t lat_us, int status,
                  uint64_t req_b, uint64_t rsp_b, float score, int scored,
                  int specialist, uint64_t score_ns, uint32_t tenant,
                  int kind = l5dstream::ROW_REQUEST, uint32_t skey = 0,
                  uint32_t fseq = 0) {
    std::lock_guard<std::mutex> g(e->mu);
    if (scored)
        e->score_stats.record(score_ns, specialist != 0);
    else
        e->score_stats.unscored++;
    // per-tenant aggregates ride the same mu hold as the feature push
    // (request rows only — a tunnel's tenant slot settles at close)
    if (tenant && kind == l5dstream::ROW_REQUEST)
        e->tenants.observe(tenant, status, score, scored != 0, loop_now(e));
    if (e->features.size() >= e->features_cap) {
        e->features_dropped++;
        return;
    }
    FeatureRow r;
    r.route_id = (float)route_id;
    r.latency_ms = (float)lat_us / 1000.0f;
    r.status = (float)status;
    r.req_bytes = (float)req_b;
    r.rsp_bytes = (float)rsp_b;
    r.ts_s = (float)((double)(loop_now(e) - e->t0_us) / 1e6);
    r.score = score;
    r.scored = scored ? 1.0f : 0.0f;
    r.tenant = l5dtg::tenant_feature(tenant);
    r.kind = (float)kind;
    r.stream = (float)skey;
    r.frame_seq = (float)fseq;
    e->features.push_back(r);
}

// Release the client's per-tenant inflight slot (idempotent via the
// tenant_counted flag; finish_exchange and conn_close both call it).
void tenant_release(Engine* e, Conn* c) {
    if (!c->tenant_counted) return;
    c->tenant_counted = false;
    std::lock_guard<std::mutex> g(e->mu);
    l5dtg::TenantStats* ts = e->tenants.peek(c->tenant);
    if (ts != nullptr && ts->inflight > 0) ts->inflight--;
}

// A TLS handshake finished (either way): clear its sweep deadline and
// release its slot in the accept-leg churn-backpressure counter.
void hs_complete(Engine* e, Conn* c) {
    c->tls->hs_deadline_us = 0;
    // accept-leg conns cache their SNI here, once per handshake —
    // tenant extraction used to call server_sni() (shim call + string
    // alloc) on EVERY request of a keep-alive conn
    if (c->tls->sess->is_server && c->tls->sni.empty())
        c->tls->sni = l5dtls::server_sni(c->tls->sess);
    if (c->hs_pending) {
        c->hs_pending = false;
        if (e->hs_inflight > 0) e->hs_inflight--;
        // the header budget starts now that the handshake is done
        if (e->guard_cfg.header_budget_us != 0 && !c->served_one &&
            c->hdr_start_us == 0)
            c->hdr_start_us = loop_now(e);
    }
}

void conn_close(Engine* e, Conn* c);
void process_client_buffer(Engine* e, Conn* c);

// Record a handshake outcome in the engine's TLS stats (idempotent per
// conn via TlsIo::accounted; mu guards against concurrent stats reads).
void tls_account(Engine* e, Conn* c, bool failed) {
    std::lock_guard<std::mutex> g(e->mu);
    l5dtls::account_handshake(c->tls, &e->tls_stats,
                              c->tls->sess->is_server, failed);
}

// flush c->out; returns false if the conn errored (and was freed)
bool flush_out(Engine* e, Conn* c) {
    if (c->tls != nullptr) {
        bool was_hs = !c->tls->sess->hs_done;
        if (!l5dtls::encrypt_pending(c->tls, &c->out)) {
            tls_account(e, c, /*failed=*/was_hs);
            // best effort: let the TLS alert reach the peer
            if (!c->out.empty())
                (void)::send(c->fd, c->out.data(), c->out.size(),
                             MSG_NOSIGNAL);
            conn_close(e, c);
            return false;
        }
        if (was_hs && c->tls->sess->hs_done) {
            hs_complete(e, c);
            tls_account(e, c, false);
        }
    }
    while (!c->out.empty()) {
        ssize_t n = ::send(c->fd, c->out.data(), c->out.size(),
                           MSG_NOSIGNAL);
        if (n > 0) {
            c->out.erase(0, (size_t)n);
        } else if (n < 0 && errno == EINTR) {
            continue;  // signal during send: the conn is healthy, retry
        } else if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
            break;
        } else {
            conn_close(e, c);
            return false;
        }
    }
    if (c->out.empty() && c->close_when_flushed &&
        (c->tls == nullptr || c->tls->plain_out.empty())) {
        if (c->tls != nullptr && c->tls->sess->hs_done &&
            !c->tls->shutdown_sent) {
            // graceful TLS close so EOF-delimited bodies end cleanly
            c->tls->shutdown_sent = true;
            l5dtls::shutdown(c->tls->sess, &c->out);
            while (!c->out.empty()) {
                ssize_t n = ::send(c->fd, c->out.data(), c->out.size(),
                                   MSG_NOSIGNAL);
                if (n <= 0) break;
                c->out.erase(0, (size_t)n);
            }
        }
        conn_close(e, c);
        return false;
    }
    bool ww = !c->out.empty();
    if (ww != c->want_write) {
        c->want_write = ww;
        ep_mod(e, c);
    }
    // resume a paused producer once this buffer drains
    if (outsz(c) < OUT_LOW_WATER && c->peer != nullptr &&
        c->peer->paused) {
        c->peer->paused = false;
        ep_mod(e, c->peer);
    }
    return true;
}

// Mark a conn for the end-of-wakeup flush pass: every byte a wakeup
// produces for a socket (pipelined responses, relay chunks, handshake
// records) leaves in ONE send() — and for TLS conns one record batch —
// instead of one per append site. Outside the loop's run window it
// degrades to an immediate flush so teardown writes reach the wire.
void queue_flush(Engine* e, Conn* c) {
    if (!e->defer_ok) {
        flush_out(e, c);
        return;
    }
    if (!c->flush_queued) {
        c->flush_queued = true;
        e->dirty.push_back(c);
    }
}

// h1 frees conns inline (no graveyard), so every free must null out a
// pending dirty slot — drain_dirty's cursor must never touch a freed
// conn (a flush can cascade into closing the conn's PEER, which may
// itself be queued).
void purge_dirty(Engine* e, Conn* c) {
    if (!c->flush_queued) return;
    c->flush_queued = false;
    for (auto& p : e->dirty)
        if (p == c) { p = nullptr; break; }
}

void drain_dirty(Engine* e) {
    // index loop over the live vector: flush_out may cascade closes
    // (nulling entries anywhere) and queue new conns (growing the tail)
    for (size_t i = 0; i < e->dirty.size(); i++) {
        Conn* c = e->dirty[i];
        if (c == nullptr) continue;
        e->dirty[i] = nullptr;
        c->flush_queued = false;
        flush_out(e, c);
    }
    e->dirty.clear();
}

// Queue a synthesized response. Returns false if the conn was freed.
bool send_simple(Engine* e, Conn* c, int status, const char* reason,
                 const char* extra_hdr, const std::string& body,
                 bool close_conn) {
    char head[512];
    int n = snprintf(head, sizeof(head),
                     "HTTP/1.1 %d %s\r\n%s%sContent-Length: %zu\r\n\r\n",
                     status, reason, extra_hdr,
                     close_conn ? "Connection: close\r\n" : "",
                     body.size());
    wbuf(c)->append(head, (size_t)n);
    wbuf(c)->append(body);
    if (close_conn) c->close_when_flushed = true;
    return flush_out(e, c);
}

void stash_upstream_session(Engine* e, Conn* up) {
    if (up->tls == nullptr || up->kind != Conn::Kind::UPSTREAM) return;
    l5dtls::stash_session(
        &e->tls_sessions,
        l5dtls::session_key(up->ep_ip_be, up->ep_port, up->tls->sni),
        up->tls->sess);
}

// Wrap a fresh origination socket in TLS when the engine has a client
// context (SNI/verify name = the route host; cached session offered).
void tls_wrap_upstream(Engine* e, Conn* up, const std::string& host) {
    if (e->tls_cli == nullptr) return;
    l5dtls::SSL_SESSION* resume = nullptr;
    auto it = e->tls_sessions.find(
        l5dtls::session_key(up->ep_ip_be, up->ep_port, host));
    if (it != e->tls_sessions.end()) resume = it->second;
    l5dtls::Sess* s = l5dtls::new_session(
        e->tls_cli, host.c_str(), e->tls_cli_verify, resume);
    if (s == nullptr) return;  // shim gone mid-flight: dial cleartext
    up->tls = new l5dtls::TlsIo();
    up->tls->sess = s;
    up->tls->sni = host;
    up->tls->hs_deadline_us = loop_now(e) + TLS_HS_TIMEOUT_US;
}

void unregister_parked(Engine* e, Conn* c) {
    auto it = e->parked.find(c->route_key);
    if (it == e->parked.end()) return;
    auto& v = it->second;
    for (size_t i = 0; i < v.size(); i++)
        if (v[i] == c) { v.erase(v.begin() + i); break; }
    if (v.empty()) e->parked.erase(it);
}

// Return an upstream conn to its endpoint pool (or close it).
void release_upstream(Engine* e, Conn* up, bool reusable) {
    up->peer = nullptr;
    bool pooled = false;
    {
        std::lock_guard<std::mutex> g(e->mu);
        for (auto& kv : e->routes) {
            Route& r = kv.second;
            if (r.id != up->route_id) continue;
            for (auto& ep : r.eps) {
                if (ep.ip_be == up->ep_ip_be && ep.port == up->ep_port) {
                    if (ep.inflight > 0) ep.inflight--;
                    if (reusable && up->fd >= 0 && ep.idle.size() < 64) {
                        up->st = Conn::St::IDLE;
                        up->in.clear();
                        up->deadline_us = 0;
                        up->idle_since_us = loop_now(e);
                        up->rsp_head_parsed = false;
                        if (up->paused) {
                            up->paused = false;
                            ep_mod(e, up);
                        }
                        ep.idle.push_back(up->fd);
                        pooled = true;
                    }
                    break;
                }
            }
            break;
        }
    }
    if (pooled) return;
    if (up->fd >= 0) {
        stash_upstream_session(e, up);
        epoll_ctl(e->epfd, EPOLL_CTL_DEL, up->fd, nullptr);
        e->conns.erase(up->fd);
        ::close(up->fd);
    }
    purge_dirty(e, up);
    delete up;
}

void conn_close(Engine* e, Conn* c) {
    if (c->st == Conn::St::CLOSED) return;
    bool was_wait_route = (c->st == Conn::St::WAIT_ROUTE);
    c->st = Conn::St::CLOSED;
    tenant_release(e, c);
    if (c->skey != 0) {
        e->by_skey.erase(c->skey);
        std::lock_guard<std::mutex> g(e->mu);
        l5dstream::StreamStats* ss = e->stream_tab.peek(c->skey);
        if (ss != nullptr && ss->inflight > 0) ss->inflight--;
        c->skey = 0;
    }
    if (c->hs_pending) {
        c->hs_pending = false;
        if (e->hs_inflight > 0) e->hs_inflight--;
    }
    if (c->fd >= 0) {
        stash_upstream_session(e, c);
        epoll_ctl(e->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
        e->conns.erase(c->fd);
        ::close(c->fd);
        c->fd = -1;
    }
    if (was_wait_route) unregister_parked(e, c);
    if (c->peer != nullptr) {
        Conn* p = c->peer;
        c->peer = nullptr;
        p->peer = nullptr;
        if (p->kind == Conn::Kind::UPSTREAM) {
            release_upstream(e, p, false);
        } else {
            // upstream died mid-exchange
            if (p->st == Conn::St::READ_RSP && p->rsp_bytes == 0) {
                tenant_release(e, p);  // exchange over: free the slot
                if (send_simple(e, p, 502, "Bad Gateway",
                                "l5d-err: upstream\r\n",
                                "upstream connection failed", false)) {
                    p->st = Conn::St::READ_HEAD;
                    p->deadline_us = 0;
                    process_client_buffer(e, p);
                }
            } else {
                // mid-body or mid-response: can't resync, drop the client
                conn_close(e, p);
            }
        }
    }
    purge_dirty(e, c);
    delete c;
}

int pick_endpoint(Route& r) {
    size_t n = r.eps.size();
    if (n == 0) return -1;
    if (n == 1) return 0;
    size_t a = r.next++ % n;
    size_t b = r.next % n;
    return (int)(r.eps[a].inflight <= r.eps[b].inflight ? a : b);
}

// Upstream ready (connected or pooled): pair it and push staged bytes.
void attach_upstream(Engine* e, Conn* client, Conn* up) {
    client->peer = up;
    up->peer = client;
    up->st = Conn::St::READ_RSP;
    up->rsp_head_parsed = false;
    up->rsp_eof_delim = false;
    up->rsp_status = 0;
    up->in.clear();
    up->deadline_us = loop_now(e) + EXCHANGE_TIMEOUT_US;
    client->st = client->req_body.done()
        ? Conn::St::READ_RSP : Conn::St::FORWARD_BODY;
    // zero-progress-body budget starts when we begin waiting for body
    client->body_progress_us =
        client->st == Conn::St::FORWARD_BODY ? loop_now(e) : 0;
    client->deadline_us = 0;
    wbuf(up)->append(client->req_stash);
    client->req_stash.clear();
    queue_flush(e, up);
}

// Dispatch the staged request on `client` (mu NOT held). On failure the
// client gets a synthesized error. Returns 1 if an upstream was attached
// (conn busy), 0 if a response was synthesized and the conn is back in
// READ_HEAD, -1 if the conn is closing or was freed.
int dispatch(Engine* e, Conn* client) {
    Conn* up = nullptr;
    bool found = false;
    {
        std::lock_guard<std::mutex> g(e->mu);
        auto it = e->routes.find(client->route_key);
        if (it != e->routes.end()) {
            Route& r = it->second;
            int idx = pick_endpoint(r);
            if (idx >= 0) {
                found = true;
                Endpoint& ep = r.eps[(size_t)idx];
                client->route_id = r.id;
                ep.inflight++;
                while (!ep.idle.empty()) {
                    int fd = ep.idle.back();
                    ep.idle.pop_back();
                    auto cit = e->conns.find(fd);
                    if (cit == e->conns.end()) continue;
                    Conn* cand = cit->second;
                    // fd numbers can be recycled: verify this conn really
                    // is an idle upstream of THIS endpoint
                    if (cand->st != Conn::St::IDLE ||
                        cand->kind != Conn::Kind::UPSTREAM ||
                        cand->ep_ip_be != ep.ip_be ||
                        cand->ep_port != ep.port)
                        continue;
                    up = cand;
                    up->route_id = r.id;
                    break;
                }
                if (up == nullptr) {
                    up = new Conn();
                    up->kind = Conn::Kind::UPSTREAM;
                    up->route_id = r.id;
                    up->ep_ip_be = ep.ip_be;
                    up->ep_port = ep.port;
                }
            }
        }
    }
    if (!found) {
        tenant_release(e, client);  // no exchange will finish this
        client->req_stash.clear();
        if (send_simple(e, client, 400, "Bad Request",
                        "l5d-err: no route\r\n",
                        "no route for host " + client->route_key, false)) {
            client->st = Conn::St::READ_HEAD;
            client->deadline_us = 0;
            return 0;
        }
        return -1;
    }
    if (up->fd < 0) {
        int fd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
        bool fail = fd < 0;
        if (!fail) {
            set_nodelay(fd);
            sockaddr_in sa{};
            sa.sin_family = AF_INET;
            sa.sin_addr.s_addr = up->ep_ip_be;
            sa.sin_port = htons(up->ep_port);
            int rc = ::connect(fd, (sockaddr*)&sa, sizeof(sa));
            if (rc < 0 && errno != EINPROGRESS) {
                ::close(fd);
                fail = true;
            } else {
                up->fd = fd;
                up->connecting = (rc < 0);
                up->want_write = up->connecting;
                tls_wrap_upstream(e, up, client->route_key);
                ep_add(e, up);
            }
        }
        if (fail) {
            {
                std::lock_guard<std::mutex> g(e->mu);
                auto it = e->routes.find(client->route_key);
                if (it != e->routes.end()) {
                    it->second.stats.conn_fail++;
                    for (auto& ep2 : it->second.eps)
                        if (ep2.ip_be == up->ep_ip_be &&
                            ep2.port == up->ep_port && ep2.inflight > 0)
                            ep2.inflight--;
                }
            }
            delete up;
            tenant_release(e, client);
            client->req_stash.clear();
            send_simple(e, client, 502, "Bad Gateway",
                        "l5d-err: connect\r\n", "connect failed", true);
            return -1;
        }
    }
    attach_upstream(e, client, up);
    return 1;
}

// Parse + begin proxying the request at the head of client->in.
// Returns true if progress was made (head consumed); false if more bytes
// are needed or the conn is busy/closed.
bool try_start_request(Engine* e, Conn* client) {
    if (client->st != Conn::St::READ_HEAD) return false;
    if (client->in.find("\r\n\r\n") == std::string::npos) {
        if (client->in.size() > MAX_HEAD)
            send_simple(e, client, 431, "Request Header Fields Too Large",
                        "", "head too large", true);
        return false;
    }
    ParsedHead h;
    if (!parse_head(client->in, false, &h)) {
        send_simple(e, client, 400, "Bad Request", "", "malformed head",
                    true);
        return false;
    }
    // a complete head arrived: the slowloris header budget is met, and
    // the conn has proven itself a real client (keep-alive may idle)
    client->served_one = true;
    client->hdr_start_us = 0;
    BodyTracker bt;
    if (!request_body(h, &bt)) {
        send_simple(e, client, 400, "Bad Request", "", "bad body framing",
                    true);
        return false;
    }
    const std::string* host = get_header(h, "host");
    std::string key = host ? *host : "";
    // CONNECT carries the target in authority-form (host:port); fall
    // back to it when no Host header rode along
    if (key.empty() && h.method == "CONNECT") key = h.uri;
    size_t colon = key.find(':');
    if (colon != std::string::npos) key.resize(colon);
    lower(key);

    const std::string* conn_hdr = get_header(h, "connection");
    bool close_req = false;
    bool upgrade_req = false;
    if (conn_hdr != nullptr) {
        close_req = ihas(*conn_hdr, "close");
        upgrade_req = ihas(*conn_hdr, "upgrade");
    }
    client->upgrade_req = upgrade_req;

    client->req_method = h.method;
    client->req_body = bt;
    client->rsp_body = BodyTracker{};
    client->route_key = key;
    client->t_start_us = loop_now(e);
    client->req_bytes = h.head_len;
    client->rsp_bytes = 0;
    client->close_after = close_req || h.version == "HTTP/1.0";

    // outbound head: original head minus final CRLF, plus Via
    std::string staged = client->in.substr(0, h.head_len - 2);
    staged += "Via: 1.1 linkerd-tpu\r\n\r\n";
    client->in.erase(0, h.head_len);

    if (!client->req_body.done() && !client->in.empty()) {
        long take = client->req_body.feed(client->in.data(),
                                          client->in.size());
        if (take < 0) {
            send_simple(e, client, 400, "Bad Request", "", "bad chunking",
                        true);
            return false;
        }
        staged.append(client->in.data(), (size_t)take);
        client->req_bytes += (uint64_t)take;
        client->in.erase(0, (size_t)take);
    }

    if (key.empty()) {
        return send_simple(e, client, 400, "Bad Request",
                           "l5d-err: no host\r\n", "missing Host", false);
    }
    if (!l5dtls::valid_authority(key)) {
        // reject before the key can reach routing/parked maps, feature
        // attribution, or the stats JSON (Host is untrusted input)
        return send_simple(e, client, 400, "Bad Request",
                           "l5d-err: bad host\r\n", "invalid Host", false);
    }

    // tenant identity: stamp the request's tenant hash, then enforce
    // the tenant's pushed quota HERE — the isolation decision runs in
    // the data plane, before any upstream work. Sheds are retry-safe
    // (503 + l5d-retryable: the request was never admitted).
    client->tenant = 0;
    switch (e->tenant_ex.kind) {
    case 1: {
        const std::string* tv = get_header(h, e->tenant_ex.header.c_str());
        if (tv != nullptr && !tv->empty())
            client->tenant = l5dtg::tenant_hash(tv->data(), tv->size());
        break;
    }
    case 2:
        client->tenant = l5dtg::hash_path_segment(h.uri,
                                                  e->tenant_ex.segment);
        break;
    case 3:
        if (client->tls != nullptr) {
            // SNI cached at handshake completion (hs_complete)
            const std::string& sni = client->tls->sni;
            if (!sni.empty())
                client->tenant = l5dtg::tenant_hash(sni.data(),
                                                    sni.size());
        }
        break;
    default:
        break;
    }
    if (client->tenant) {
        bool over = false;
        {
            std::lock_guard<std::mutex> g(e->mu);
            l5dtg::TenantStats* ts =
                e->tenants.get(client->tenant, client->t_start_us);
            int q = e->quotas.limit_of(client->tenant);
            if (q >= 0 && ts->inflight >= q) {
                ts->shed++;
                over = true;
            } else {
                ts->inflight++;
                client->tenant_counted = true;
            }
        }
        if (over) {
            e->guard.tenant_shed.fetch_add(1, std::memory_order_relaxed);
            // a shed mid-body can't resync the framing: close after
            return send_simple(e, client, 503, "Service Unavailable",
                               "l5d-retryable: true\r\n"
                               "l5d-err: tenant quota\r\n",
                               "tenant over quota",
                               !client->req_body.done());
        }
    }

    client->req_stash = std::move(staged);
    bool have_route;
    {
        std::lock_guard<std::mutex> g(e->mu);
        have_route = e->routes.count(key) > 0;
        if (!have_route) {
            e->misses.push_back(key);
            e->parked[key].push_back(client);
        }
    }
    if (!have_route) {
        client->st = Conn::St::WAIT_ROUTE;
        client->deadline_us = loop_now(e) + ROUTE_WAIT_TIMEOUT_US;
        return false;  // parked; nothing further until a route arrives
    }
    // 0 => synthesized response, conn ready for the next buffered request
    return dispatch(e, client) == 0;
}

// Drain as many buffered pipelined requests as possible.
void process_client_buffer(Engine* e, Conn* c) {
    while (c->st == Conn::St::READ_HEAD && !c->in.empty())
        if (!try_start_request(e, c)) break;
}

void unpark_route(Engine* e, const std::string& host) {
    std::vector<Conn*> waiters;
    {
        std::lock_guard<std::mutex> g(e->mu);
        auto it = e->parked.find(host);
        if (it == e->parked.end()) return;
        waiters.swap(it->second);
        e->parked.erase(it);
    }
    for (Conn* c : waiters) {
        if (c->st != Conn::St::WAIT_ROUTE) continue;
        if (dispatch(e, c) == 0) process_client_buffer(e, c);
    }
}

void finish_exchange(Engine* e, Conn* up, bool upstream_reusable) {
    Conn* client = up->peer;
    if (client == nullptr) {
        release_upstream(e, up, false);
        return;
    }
    uint64_t lat = loop_now(e) - client->t_start_us;
    // in-data-plane scoring: feature prep (hash col + drift EWMA)
    // rides the SAME mu hold and route scan as the stats record; the
    // dense forward runs OUTSIDE mu against the slab's own reader
    // protocol, so a weight publish never contends with request work
    float feats[l5dscore::FEATURE_DIM];
    bool have_feats = false;
    uint32_t rhash = 0;
    {
        std::lock_guard<std::mutex> g(e->mu);
        for (auto& kv : e->routes) {
            if (kv.second.id == up->route_id) {
                kv.second.stats.record(up->rsp_status, lat);
                l5dscore::RouteFeat& rf = kv.second.feat;
                const float lat_ms = (float)lat / 1000.0f;
                const float drift =
                    l5dscore::feat_drift_update(&rf, lat_ms);
                if (rf.col >= 0 &&
                    l5dscore::slab_has_weights(e->slab)) {
                    l5dscore::featurize(
                        lat_ms, up->rsp_status,
                        (float)client->req_bytes,
                        (float)client->rsp_bytes, rf.col, rf.sign,
                        drift, feats);
                    have_feats = true;
                    rhash = rf.rhash;
                }
                break;
            }
        }
    }
    float score = 0.0f;
    int scored = 0, specialist = 0;
    uint64_t score_ns = 0;
    if (have_feats) {
        const uint64_t t0 = l5dscore::now_ns();
        // per-route head select: the bank serves this route's
        // specialist when one is published, the base model otherwise
        const int rc = l5dscore::slab_score_route(
            e->slab, rhash, rhash != 0, feats, &score);
        if (rc >= 0) {
            scored = 1;
            specialist = rc;
            score_ns = l5dscore::now_ns() - t0;
        }
    }
    push_feature(e, up->route_id, lat, up->rsp_status,
                 client->req_bytes, client->rsp_bytes,
                 score, scored, specialist, score_ns, client->tenant);
    tenant_release(e, client);
    client->peer = nullptr;
    up->peer = nullptr;
    release_upstream(e, up, upstream_reusable);
    if (client->close_after) {
        client->close_when_flushed = true;
        queue_flush(e, client);
        return;
    }
    client->st = Conn::St::READ_HEAD;
    client->deadline_us = 0;
    process_client_buffer(e, client);
}

// ---- stream sentinel: byte tunnels ----------------------------------------
// A 101 upgrade (WebSocket) or CONNECT answer switches the client/
// upstream pair into TUNNEL: bytes relay opaquely, but every read is a
// "frame" for the client conn's StreamAccum, sampled on the configured
// cadence through the same scorer slab as request rows. A sick tunnel
// (native hysteresis: enter/exit, quorum, dwell) is closed outright —
// there is no stream-level RST in h1, the conn IS the stream.

// Score one tunnel sample; returns +1 on a healthy->sick transition.
int tunnel_sample(Engine* e, Conn* c, uint64_t now) {
    c->gov.last_sample_frames = c->acc.frames;
    c->gov.last_sample_us = now;
    float score = 0.0f;
    int scored = 0, specialist = 0;
    uint64_t score_ns = 0;
    if (l5dscore::slab_has_weights(e->slab)) {
        float feats[l5dscore::FEATURE_DIM];
        l5dscore::featurize_stream(c->acc.gap_ewma_ms, c->acc.bpf_ewma,
                                   (float)c->acc.bytes, c->acc.gap_dev_ms,
                                   c->acc.anomalies, -1, 0.0f, feats);
        const uint64_t t0 = l5dscore::now_ns();
        // specialist head pinned at tunnel entry: srhash frozen so one
        // stream is judged by one model for its whole life
        const int rc = l5dscore::slab_score_route(
            e->slab, c->srhash, c->srhash != 0, feats, &score);
        if (rc >= 0) {
            scored = 1;
            specialist = rc;
            score_ns = l5dscore::now_ns() - t0;
        }
    }
    int trans = scored
        ? l5dstream::gov_observe(e->stream_cfg, &c->gov, score, now) : 0;
    push_feature(e, c->route_id,
                 (uint64_t)(c->acc.gap_ewma_ms * 1000.0f),
                 c->gov.sick ? 503 : 0, c->tunnel_bytes, c->acc.bytes,
                 score, scored, specialist, score_ns, c->tenant,
                 l5dstream::ROW_TUNNEL, c->skey, c->acc.frames);
    {
        std::lock_guard<std::mutex> g(e->mu);
        e->stream_tab.observe(c->skey, l5dstream::ROW_TUNNEL, score,
                              scored != 0, c->acc, c->gov.sick, now);
        if (trans > 0) e->stream_tab.sick_transitions++;
    }
    return trans;
}

// Account one tunnel read (either direction) against the client conn's
// accumulator; enforces the byte cap, samples on cadence, and sheds the
// tunnel on a sick transition. Returns false if the conn was freed
// (the close cascades to the upstream leg via conn_close).
bool tunnel_note(Engine* e, Conn* c, float bytes) {
    uint64_t now = loop_now(e);
    float gap_ms = c->last_frame_us == 0
        ? 0.0f : (float)(now - c->last_frame_us) / 1000.0f;
    c->last_frame_us = now;
    l5dstream::accum_frame(&c->acc, l5dstream::FRAME_DATA, gap_ms, bytes);
    c->tunnel_bytes += (uint64_t)bytes;
    if (e->stream_cfg.tunnel_max_bytes != 0 &&
        c->tunnel_bytes > e->stream_cfg.tunnel_max_bytes) {
        {
            std::lock_guard<std::mutex> g(e->mu);
            e->stream_tab.tunnel_bytes_closed++;
        }
        conn_close(e, c);
        return false;
    }
    if (c->skey == 0) return true;  // scoring disabled at tunnel entry
    if (l5dstream::sample_due(e->stream_cfg, c->acc, c->gov, now)) {
        int trans = tunnel_sample(e, c, now);
        if (trans > 0 && e->stream_cfg.action != 0) {
            {
                std::lock_guard<std::mutex> g(e->mu);
                e->stream_tab.rst_sent++;
            }
            conn_close(e, c);
            return false;
        }
    }
    return true;
}

// Switch a paired client/upstream into byte-tunnel mode and relay any
// bytes already buffered on either side. Returns false if a conn was
// freed mid-entry.
bool enter_tunnel(Engine* e, Conn* client, Conn* up) {
    client->st = Conn::St::TUNNEL;
    up->st = Conn::St::TUNNEL;
    client->deadline_us = 0;
    up->deadline_us = 0;  // tunnels outlive the exchange timeout
    client->body_progress_us = 0;
    client->hdr_start_us = 0;
    client->close_after = true;  // a tunneled conn never re-enters h1
    uint64_t now = loop_now(e);
    client->last_frame_us = now;
    client->tunnel_bytes = 0;
    if (e->stream_cfg.enabled) {
        uint32_t k = 0;
        for (int tries = 0; tries < 4 && k == 0; tries++) {
            uint32_t cand = l5dstream::fold_key(e->next_skey++);
            if (e->by_skey.count(cand) == 0) k = cand;
        }
        if (k != 0) {
            client->skey = k;
            e->by_skey[k] = client;
            std::lock_guard<std::mutex> g(e->mu);
            l5dstream::StreamStats* ss = e->stream_tab.get(k, now);
            ss->inflight = 1;
            ss->kind = l5dstream::ROW_TUNNEL;
            e->stream_tab.tunnels_opened++;
            // pin the route's current specialist head for the
            // tunnel's whole life
            for (auto& kv : e->routes)
                if (kv.second.id == client->route_id) {
                    client->srhash = kv.second.feat.rhash;
                    break;
                }
        }
    }
    if (!up->in.empty()) {
        size_t nb = up->in.size();
        wbuf(client)->append(up->in);
        up->in.clear();
        queue_flush(e, client);
        if (!tunnel_note(e, client, (float)nb)) return false;
    }
    if (!client->in.empty()) {
        size_t nb = client->in.size();
        wbuf(up)->append(client->in);
        client->in.clear();
        queue_flush(e, up);
        if (!tunnel_note(e, client, (float)nb)) return false;
    }
    return true;
}

// Python-side actuation: keys queued by fp_rst_stream are resolved on
// the loop thread against by_skey and their tunnels closed.
void drain_pending_rst(Engine* e) {
    // l5d: ignore[hot-alloc] — default-constructed vector allocates nothing; swap() steals the queued buffer, and RST actuation is control-plane cadence, not per-request
    std::vector<uint32_t> keys;
    {
        std::lock_guard<std::mutex> g(e->mu);
        if (e->pending_rst.empty()) return;
        keys.swap(e->pending_rst);
    }
    for (uint32_t k : keys) {
        auto it = e->by_skey.find(k);
        if (it == e->by_skey.end()) continue;
        Conn* c = it->second;
        if (c->st != Conn::St::TUNNEL) continue;
        {
            std::lock_guard<std::mutex> g(e->mu);
            e->stream_tab.rst_sent++;
        }
        conn_close(e, c);
    }
}

// TCP EOF (or TLS close-notify) from an upstream: completes an
// EOF-delimited response, otherwise tears the exchange down. On a TLS
// conn only an authenticated close-notify may complete an
// EOF-delimited body — a bare FIN is indistinguishable from an
// attacker-injected truncation (RFC 8446 §6.1).
void handle_upstream_eof(Engine* e, Conn* up) {
    Conn* client = up->peer;
    bool clean_eof = up->tls == nullptr || up->tls->close_notify;
    if (clean_eof && client != nullptr && up->rsp_head_parsed &&
        up->rsp_eof_delim) {
        // EOF completes the response; client can't be kept alive.
        // finish_exchange(reusable=false) fully disposes `up`.
        client->close_after = true;
        finish_exchange(e, up, false);
    } else {
        conn_close(e, up);
    }
}

void on_upstream_readable(Engine* e, Conn* up) {
    char buf[64 * 1024];
    for (;;) {
        ssize_t n = ::recv(up->fd, buf, sizeof(buf), 0);
        if (n < 0) {
            if (errno == EINTR) continue;  // signal, not a dead conn
            if (errno == EAGAIN || errno == EWOULDBLOCK) return;
            conn_close(e, up);
            return;
        }
        if (n == 0) {
            handle_upstream_eof(e, up);
            return;
        }
        int tls_rc = 0;
        if (up->tls != nullptr) {
            bool was_hs = !up->tls->sess->hs_done;
            tls_rc = l5dtls::ingest(up->tls, buf, (size_t)n, &up->in,
                                    &up->out);
            if (tls_rc < 0) {
                tls_account(e, up, was_hs);
                conn_close(e, up);
                return;
            }
            if (was_hs && up->tls->sess->hs_done) {
                hs_complete(e, up);
                tls_account(e, up, false);
            }
            // handshake records / staged request plaintext
            queue_flush(e, up);
        }
        Conn* client = up->peer;
        if (client == nullptr) {
            // TLS-layer records (tickets) carry no plaintext and are
            // fine on an idle pooled conn; app bytes are not
            if (up->tls != nullptr && up->in.empty() && tls_rc == 0)
                continue;
            conn_close(e, up);  // bytes on an unpaired conn: drop
            return;
        }
        if (up->tls == nullptr) up->in.append(buf, (size_t)n);
        if (up->st == Conn::St::TUNNEL) {
            size_t nb = up->in.size();
            if (nb > 0) {
                wbuf(client)->append(up->in);
                up->in.clear();
                queue_flush(e, client);
                maybe_pause_producer(e, client);
                if (!tunnel_note(e, client, (float)nb)) return;
            }
            if (tls_rc == 1) {
                conn_close(e, up);
                return;
            }
            continue;
        }
        while (!up->rsp_head_parsed) {
            if (up->in.find("\r\n\r\n") == std::string::npos) {
                if (up->in.size() > MAX_HEAD) {
                    conn_close(e, up);
                    return;
                }
                goto more;  // need more bytes
            }
            ParsedHead h;
            if (!parse_head(up->in, true, &h)) {
                conn_close(e, up);
                return;
            }
            BodyTracker bt;
            if (!response_body(h, client->req_method, &bt)) {
                conn_close(e, up);
                return;
            }
            wbuf(client)->append(up->in.data(), h.head_len);
            client->rsp_bytes += h.head_len;
            up->in.erase(0, h.head_len);
            if (h.status >= 100 && h.status < 200 && h.status != 101) {
                queue_flush(e, client);
                continue;  // informational: next head follows
            }
            up->rsp_head_parsed = true;
            up->rsp_status = h.status;
            up->rsp_eof_delim = (bt.kind == BodyKind::EOF_DELIM);
            client->rsp_body = bt;
            // upgrade passthrough: a 101 the client asked for, or a
            // successful CONNECT answer, switches the pair into an
            // opaque byte tunnel (still frame-featurized)
            if ((h.status == 101 && client->upgrade_req) ||
                (client->req_method == "CONNECT" && h.status >= 200 &&
                 h.status < 300)) {
                queue_flush(e, client);
                if (!enter_tunnel(e, client, up)) return;
                goto more;  // next reads take the TUNNEL branch
            }
        }
        if (!up->in.empty()) {
            long take = client->rsp_body.feed(up->in.data(), up->in.size());
            if (take < 0) {
                conn_close(e, up);
                return;
            }
            wbuf(client)->append(up->in.data(), (size_t)take);
            client->rsp_bytes += (uint64_t)take;
            up->in.erase(0, (size_t)take);
        }
        queue_flush(e, client);
        if (client->rsp_body.done()) {
            bool reusable = up->in.empty() && !up->rsp_eof_delim;
            finish_exchange(e, up, reusable);
            return;
        }
        maybe_pause_producer(e, client);  // up produces into client->out
    more:;
        if (tls_rc == 1) {  // close-notify: buffered plaintext consumed
            handle_upstream_eof(e, up);
            return;
        }
    }
}

void on_client_readable(Engine* e, Conn* c) {
    char buf[64 * 1024];
    for (;;) {
        ssize_t n = ::recv(c->fd, buf, sizeof(buf), 0);
        if (n < 0) {
            if (errno == EINTR) continue;  // signal, not a dead conn
            if (errno == EAGAIN || errno == EWOULDBLOCK) return;
            conn_close(e, c);
            return;
        }
        if (n == 0) {
            conn_close(e, c);
            return;
        }
        int tls_rc = 0;
        if (c->tls != nullptr) {
            bool was_hs = !c->tls->sess->hs_done;
            tls_rc = l5dtls::ingest(c->tls, buf, (size_t)n, &c->in,
                                    &c->out);
            if (tls_rc < 0) {
                tls_account(e, c, was_hs);
                if (!c->out.empty())  // let the TLS alert out
                    (void)::send(c->fd, c->out.data(), c->out.size(),
                                 MSG_NOSIGNAL);
                conn_close(e, c);
                return;
            }
            if (was_hs && c->tls->sess->hs_done) {
                hs_complete(e, c);
                tls_account(e, c, false);
            }
            // handshake records / resumption tickets
            queue_flush(e, c);
        } else {
            c->in.append(buf, (size_t)n);
        }
        if (c->st == Conn::St::TUNNEL) {
            if (c->peer == nullptr) {
                conn_close(e, c);
                return;
            }
            size_t nb = c->in.size();
            if (nb > 0) {
                wbuf(c->peer)->append(c->in);
                c->in.clear();
                queue_flush(e, c->peer);
                maybe_pause_producer(e, c->peer);
                if (!tunnel_note(e, c, (float)nb)) return;
            }
            if (tls_rc == 1) {
                conn_close(e, c);
                return;
            }
            continue;
        }
        if (c->st == Conn::St::FORWARD_BODY && c->peer != nullptr) {
            long take = c->req_body.feed(c->in.data(), c->in.size());
            if (take < 0) {
                conn_close(e, c);
                return;
            }
            wbuf(c->peer)->append(c->in.data(), (size_t)take);
            c->req_bytes += (uint64_t)take;
            c->in.erase(0, (size_t)take);
            if (take > 0) c->body_progress_us = loop_now(e);
            queue_flush(e, c->peer);
            maybe_pause_producer(e, c->peer);  // c produces into peer->out
            if (c->req_body.done()) {
                c->st = Conn::St::READ_RSP;
                c->body_progress_us = 0;
            }
        } else if (c->st == Conn::St::READ_HEAD) {
            process_client_buffer(e, c);
            if (c->st == Conn::St::CLOSED) return;
        }
        // slowloris header budget: a partial head (or a fresh conn
        // that has sent nothing) keeps its deadline; an idle keep-alive
        // conn that has completed at least one request may idle freely
        if (c->st == Conn::St::READ_HEAD &&
            e->guard_cfg.header_budget_us != 0) {
            if (c->in.empty() && c->served_one)
                c->hdr_start_us = 0;
            else if (c->hdr_start_us == 0)
                c->hdr_start_us = loop_now(e);
        }
        // WAIT_ROUTE / READ_RSP: extra bytes buffer in c->in (pipelining),
        // bounded — a client shoveling data while parked is abusive
        if ((c->st == Conn::St::WAIT_ROUTE || c->st == Conn::St::READ_RSP)
            && c->in.size() > MAX_BUFFERED_IN) {
            conn_close(e, c);
            return;
        }
        if (tls_rc == 1) {  // clean TLS shutdown from the client
            conn_close(e, c);
            return;
        }
    }
}

void on_listener(Engine* e, int lfd) {
    bool tls = e->tls_srv != nullptr && e->tls_listeners.count(lfd) > 0;
    for (;;) {
        sockaddr_in peer{};
        socklen_t plen = sizeof(peer);
        int fd = ::accept4(lfd, (sockaddr*)&peer, &plen, SOCK_NONBLOCK);
        if (fd < 0) {
            if (errno == EINTR) continue;  // don't drop the pending conn
            return;
        }
        uint64_t now = loop_now(e);
        // per-source accept throttle: a churn-flooding source is shed
        // at accept, before it can consume a handshake or conn slot
        if (peer.sin_family == AF_INET &&
            !e->sources.allow(peer.sin_addr.s_addr, e->guard_cfg, now)) {
            e->guard.accept_throttled.fetch_add(
                1, std::memory_order_relaxed);
            ::close(fd);
            continue;
        }
        // handshake-churn backpressure: shed new TLS conns while too
        // many handshakes are in flight — full handshakes are the
        // expensive path, and letting a flood queue them would thrash
        // the resumption cache for well-behaved peers
        if (tls && e->guard_cfg.max_hs_inflight != 0 &&
            e->hs_inflight >= e->guard_cfg.max_hs_inflight) {
            e->guard.hs_churn_shed.fetch_add(
                1, std::memory_order_relaxed);
            ::close(fd);
            continue;
        }
        set_nodelay(fd);
        Conn* c = new Conn();
        c->kind = Conn::Kind::CLIENT;
        c->fd = fd;
        // slowloris: a fresh conn must produce a complete request head
        // within the header budget (enforced by the sweep). TLS conns
        // arm it on handshake COMPLETION (hs_complete) instead — the
        // handshake has its own budget, and a tight header budget must
        // not misread a slow handshake as a slowloris
        if (e->guard_cfg.header_budget_us != 0 && !tls)
            c->hdr_start_us = now;
        if (tls) {
            l5dtls::Sess* s = l5dtls::new_session(e->tls_srv, nullptr,
                                                  false, nullptr);
            if (s == nullptr) {
                ::close(fd);
                delete c;
                continue;
            }
            c->tls = new l5dtls::TlsIo();
            c->tls->sess = s;
            c->tls->hs_deadline_us = now + TLS_HS_TIMEOUT_US;
            c->hs_pending = true;
            e->hs_inflight++;
        }
        ep_add(e, c);
        e->accepted.fetch_add(1, std::memory_order_relaxed);
    }
}

void sweep_timeouts(Engine* e) {
    uint64_t now = loop_now(e);
    if (now - e->last_sweep_us < 500'000) return;
    e->last_sweep_us = now;
    std::vector<Conn*> expired;
    for (auto& kv : e->conns) {
        Conn* c = kv.second;
        // handshake budget: a TLS peer still mid-handshake past its
        // window is a handshake failure (one list — a conn must not be
        // collected twice, conn_close frees it immediately)
        if (c->tls != nullptr && c->tls->hs_deadline_us != 0 &&
            now > c->tls->hs_deadline_us) {
            tls_account(e, c, /*failed=*/true);
            expired.push_back(c);
        } else if (c->deadline_us != 0 && now > c->deadline_us) {
            expired.push_back(c);
        } else if (c->kind == Conn::Kind::CLIENT &&
                   e->guard_cfg.header_budget_us != 0 &&
                   c->hdr_start_us != 0 &&
                   now - c->hdr_start_us >
                       e->guard_cfg.header_budget_us) {
            // slowloris: head still incomplete past the budget
            e->guard.slowloris_closed.fetch_add(
                1, std::memory_order_relaxed);
            expired.push_back(c);
        } else if (c->kind == Conn::Kind::CLIENT &&
                   c->st == Conn::St::FORWARD_BODY &&
                   e->guard_cfg.body_stall_budget_us != 0 &&
                   c->body_progress_us != 0 &&
                   now - c->body_progress_us >
                       e->guard_cfg.body_stall_budget_us) {
            // zero-progress request body: a trickling attacker must
            // not pin an upstream slot indefinitely
            e->guard.body_stall_closed.fetch_add(
                1, std::memory_order_relaxed);
            expired.push_back(c);
        } else if (c->kind == Conn::Kind::CLIENT &&
                   c->st == Conn::St::TUNNEL &&
                   e->stream_cfg.tunnel_idle_us != 0 &&
                   c->last_frame_us != 0 &&
                   now - c->last_frame_us > e->stream_cfg.tunnel_idle_us) {
            // a byte tunnel with zero activity past its idle budget is
            // shed (tunnels escape the exchange timeout by design)
            {
                std::lock_guard<std::mutex> g(e->mu);
                e->stream_tab.tunnel_idle_closed++;
            }
            expired.push_back(c);
        }
    }
    // endpoint churn orphans pooled IDLE conns: a route update that
    // drops an endpoint leaves its idle fds unreachable (no ep.idle
    // list holds them), so they would leak until the peer closes
    std::vector<Conn*> cands;
    for (auto& kv : e->conns) {
        Conn* c = kv.second;
        if (c->st == Conn::St::IDLE && c->idle_since_us != 0 &&
            now - c->idle_since_us >= ORPHAN_IDLE_TIMEOUT_US)
            cands.push_back(c);
    }
    if (!cands.empty()) {
        // one pass under the lock: an idle entry only counts when it
        // still resolves to a live IDLE conn of THAT endpoint — raw fd
        // equality would let a recycled fd number in a stale idle
        // entry keep a true orphan alive (see the checkout loop's
        // identical validation)
        std::unordered_set<int> referenced;
        {
            std::lock_guard<std::mutex> g(e->mu);
            for (auto& rkv : e->routes)
                for (auto& ep : rkv.second.eps)
                    for (int fd2 : ep.idle) {
                        auto cit = e->conns.find(fd2);
                        if (cit != e->conns.end() &&
                            cit->second->st == Conn::St::IDLE &&
                            cit->second->ep_ip_be == ep.ip_be &&
                            cit->second->ep_port == ep.port)
                            referenced.insert(fd2);
                    }
        }
        for (Conn* c : cands) {
            if (referenced.count(c->fd)) {
                // still warm-pooled: re-stamp so the locked scan runs
                // at most once per timeout window per conn
                c->idle_since_us = now;
            } else {
                conn_close(e, c);
            }
        }
    }
    for (Conn* c : expired) {
        if (c->st == Conn::St::WAIT_ROUTE) {
            unregister_parked(e, c);
            tenant_release(e, c);  // the exchange will never finish
            c->req_stash.clear();
            if (send_simple(e, c, 400, "Bad Request",
                            "l5d-err: no route\r\n",
                            "no route for host " + c->route_key, false)) {
                c->st = Conn::St::READ_HEAD;
                c->deadline_us = 0;
                process_client_buffer(e, c);
            }
        } else {
            conn_close(e, c);
        }
    }
}

void* loop_main(void* arg) {
    Engine* e = (Engine*)arg;
    epoll_event evs[MAX_EVENTS];
    e->defer_ok = true;  // producers may now coalesce writes
    while (e->running.load(std::memory_order_relaxed)) {
        int n = epoll_wait(e->epfd, evs, MAX_EVENTS, 250);
        // ONE clock read per wakeup: everything this round timestamps
        // (deadlines, latency, feature rows) reads this stamp
        e->now_cache_us = now_us();
        for (int i = 0; i < n; i++) {
            int fd = evs[i].data.fd;
            uint32_t ev = evs[i].events;
            if (fd == e->wakefd) {
                uint64_t v;
                ssize_t r = ::read(e->wakefd, &v, sizeof(v));
                (void)r;
                // l5d: ignore[hot-alloc] — wakefd branch: runs only on a control-plane route-update wakeup, not per request
                std::vector<std::string> hosts;
                {
                    std::lock_guard<std::mutex> g(e->mu);
                    for (auto& kv : e->parked)
                        if (e->routes.count(kv.first))
                            hosts.push_back(kv.first);
                }
                for (auto& h : hosts) unpark_route(e, h);
                continue;
            }
            bool is_listener = false;
            for (int lfd : e->listeners)
                if (lfd == fd) {
                    is_listener = true;
                    break;
                }
            if (is_listener) {
                on_listener(e, fd);
                continue;
            }
            auto it = e->conns.find(fd);
            if (it == e->conns.end()) continue;
            Conn* c = it->second;
            if (ev & (EPOLLHUP | EPOLLERR)) {
                conn_close(e, c);
                continue;
            }
            if (ev & EPOLLOUT) {
                if (c->kind == Conn::Kind::UPSTREAM && c->connecting) {
                    int err = 0;
                    socklen_t sl = sizeof(err);
                    getsockopt(c->fd, SOL_SOCKET, SO_ERROR, &err, &sl);
                    if (err != 0) {
                        conn_close(e, c);  // peer gets 502 via conn_close
                        continue;
                    }
                    c->connecting = false;
                }
                if (!flush_out(e, c)) continue;
            }
            if (ev & (EPOLLIN | EPOLLRDHUP)) {
                if (c->kind == Conn::Kind::CLIENT) on_client_readable(e, c);
                else on_upstream_readable(e, c);
            }
        }
        drain_pending_rst(e);
        sweep_timeouts(e);
        // ONE coalesced flush per wakeup: every write this round
        // produced leaves in a single send() batch per conn
        drain_dirty(e);
    }
    drain_dirty(e);         // teardown bytes still flush
    e->defer_ok = false;    // shutdown-path writes go straight out
    return nullptr;
}

}  // namespace

extern "C" {

void* fp_create() {
    Engine* e = new Engine();
    e->epfd = epoll_create1(0);
    e->wakefd = eventfd(0, EFD_NONBLOCK);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = e->wakefd;
    epoll_ctl(e->epfd, EPOLL_CTL_ADD, e->wakefd, &ev);
    return e;
}

int fp_start(void* ep) {
    Engine* e = (Engine*)ep;
    if (e->thread_started) return 0;
    if (pthread_create(&e->thread, nullptr, loop_main, e) != 0) return -1;
    e->thread_started = true;
    return 0;
}

static int fp_listen_impl(Engine* e, const char* ip, int port,
                          int reuseport) {
    int fd = ::socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
    if (fd < 0) return -1;
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    if (reuseport)
        setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one));
    sockaddr_in sa{};
    sa.sin_family = AF_INET;
    sa.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, ip, &sa.sin_addr) != 1) {
        ::close(fd);
        return -1;
    }
    if (bind(fd, (sockaddr*)&sa, sizeof(sa)) < 0 || listen(fd, 1024) < 0) {
        ::close(fd);
        return -1;
    }
    socklen_t sl = sizeof(sa);
    getsockname(fd, (sockaddr*)&sa, &sl);
    epoll_event ev{};
    ev.events = EPOLLIN;
    ev.data.fd = fd;
    epoll_ctl(e->epfd, EPOLL_CTL_ADD, fd, &ev);
    e->listeners.push_back(fd);
    return (int)ntohs(sa.sin_port);
}

// Bind a listener; returns the bound port or -1. Call before fp_start.
int fp_listen(void* ep, const char* ip, int port) {
    return fp_listen_impl((Engine*)ep, ip, port, 0);
}

// Like fp_listen, but SO_REUSEPORT: N per-core worker engines each
// bind the SAME ip:port and the kernel distributes accepted
// connections across them (the multi-core sharding seam — the first
// worker binds port 0 to pick the port, the rest bind that concrete
// port). The flag must be set on EVERY socket sharing the port, so
// even the first worker of a shard group binds through this entry.
int fp_listen_shared(void* ep, const char* ip, int port) {
    return fp_listen_impl((Engine*)ep, ip, port, 1);
}

// 1 when the OpenSSL runtime could be dlopen'd (TLS termination /
// origination available), else 0.
int fp_tls_runtime_available() { return l5dtls::available() ? 1 : 0; }

// Install the accept-leg TLS context (cert/key PEM + ALPN preference
// CSV, e.g. "http/1.1"). Call BEFORE fp_start. Returns 0, or -1 with
// the OpenSSL error text in err.
int fp_set_tls(void* ep, const char* cert, const char* key,
               const char* alpn, char* err, size_t errcap) {
    Engine* e = (Engine*)ep;
    std::string why;
    l5dtls::Ctx* c = l5dtls::server_ctx(cert, key, alpn, &why);
    if (c == nullptr) {
        if (err != nullptr && errcap > 0) {
            snprintf(err, errcap, "%s", why.c_str());
        }
        return -1;
    }
    l5dtls::free_ctx(e->tls_srv);
    e->tls_srv = c;
    return 0;
}

// Like fp_listen, but connections accepted on this listener terminate
// TLS (requires fp_set_tls first).
int fp_listen_tls(void* ep, const char* ip, int port) {
    Engine* e = (Engine*)ep;
    if (e->tls_srv == nullptr) return -1;
    int got = fp_listen(ep, ip, port);
    if (got >= 0) e->tls_listeners.insert(e->listeners.back());
    return got;
}

// TLS + SO_REUSEPORT (see fp_listen_shared).
int fp_listen_tls_shared(void* ep, const char* ip, int port) {
    Engine* e = (Engine*)ep;
    if (e->tls_srv == nullptr) return -1;
    int got = fp_listen_shared(ep, ip, port);
    if (got >= 0) e->tls_listeners.insert(e->listeners.back());
    return got;
}

// Originate TLS to every upstream endpoint (the router-wide client.tls
// block). verify=0 skips chain/hostname validation
// (tls.disableValidation parity); ca_path, when set, replaces the
// default trust roots. Call BEFORE fp_start.
int fp_set_client_tls(void* ep, const char* alpn, int verify,
                      const char* ca_path, char* err, size_t errcap) {
    Engine* e = (Engine*)ep;
    std::string why;
    l5dtls::Ctx* c = l5dtls::client_ctx(alpn, verify != 0, ca_path, &why);
    if (c == nullptr) {
        if (err != nullptr && errcap > 0) {
            snprintf(err, errcap, "%s", why.c_str());
        }
        return -1;
    }
    l5dtls::free_ctx(e->tls_cli);
    e->tls_cli = c;
    e->tls_cli_verify = verify != 0;
    return 0;
}

// endpoints: space-separated "ip:port" entries (trailing space ok).
int fp_set_route(void* ep, const char* host, const char* endpoints) {
    Engine* e = (Engine*)ep;
    std::vector<Endpoint> eps;
    const char* p = endpoints;
    while (p && *p) {
        while (*p == ' ') p++;
        if (!*p) break;
        const char* colon = strchr(p, ':');
        if (!colon) break;
        std::string ip(p, (size_t)(colon - p));
        int port = atoi(colon + 1);
        Endpoint epnt{};
        if (inet_pton(AF_INET, ip.c_str(), &epnt.ip_be) == 1 &&
            port > 0 && port < 65536) {
            epnt.port = (uint16_t)port;
            eps.push_back(epnt);
        }
        const char* sp = strchr(colon, ' ');
        if (!sp) break;
        p = sp + 1;
    }
    std::string key(host);
    lower(key);
    {
        std::lock_guard<std::mutex> g(e->mu);
        auto it = e->routes.find(key);
        if (it == e->routes.end()) {
            Route r;
            r.id = e->next_route_id++;
            r.eps = std::move(eps);
            e->routes.emplace(std::move(key), std::move(r));
        } else {
            Route& r = it->second;
            for (auto& ne : eps)
                for (auto& oe : r.eps)
                    if (oe.ip_be == ne.ip_be && oe.port == ne.port) {
                        ne.inflight = oe.inflight;
                        ne.idle = std::move(oe.idle);
                    }
            r.eps = std::move(eps);
        }
    }
    uint64_t v = 1;
    ssize_t r = ::write(e->wakefd, &v, sizeof(v));
    (void)r;
    return 0;
}

int fp_remove_route(void* ep, const char* host) {
    Engine* e = (Engine*)ep;
    std::string key(host);
    lower(key);
    std::lock_guard<std::mutex> g(e->mu);
    return e->routes.erase(key) ? 0 : -1;
}

long fp_drain_misses(void* ep, char* buf, size_t cap) {
    Engine* e = (Engine*)ep;
    std::lock_guard<std::mutex> g(e->mu);
    size_t used = 0;
    long count = 0;
    while (!e->misses.empty()) {
        const std::string& h = e->misses.front();
        if (used + h.size() + 2 > cap) break;
        memcpy(buf + used, h.data(), h.size());
        used += h.size();
        buf[used++] = '\n';
        e->misses.pop_front();
        count++;
    }
    buf[used] = 0;
    return count;
}

long fp_stats_json(void* ep, char* buf, size_t cap) {
    Engine* e = (Engine*)ep;
    std::string s = "{\"routes\":{";
    std::lock_guard<std::mutex> g(e->mu);
    bool first = true;
    for (auto& kv : e->routes) {
        RouteStats& st = kv.second.stats;
        char tmp[256];
        s += first ? "\"" : ",\"";
        l5dtls::json_escape(kv.first, &s);  // keys came off the wire
        snprintf(tmp, sizeof(tmp),
                 "\":{\"id\":%llu,\"requests\":%llu,\"success\":%llu,"
                 "\"f4xx\":%llu,\"f5xx\":%llu,\"conn_fail\":%llu,"
                 "\"hist\":[",
                 (unsigned long long)kv.second.id,
                 (unsigned long long)st.requests,
                 (unsigned long long)st.success,
                 (unsigned long long)st.f4xx,
                 (unsigned long long)st.f5xx,
                 (unsigned long long)st.conn_fail);
        s += tmp;
        for (int i = 0; i < LAT_BUCKETS; i++) {
            if (i) s += ",";
            snprintf(tmp, sizeof(tmp), "%llu",
                     (unsigned long long)st.lat_hist[i]);
            s += tmp;
        }
        s += "]}";
        first = false;
    }
    char tail[512];
    l5dtls::TlsStats& t = e->tls_stats;
    snprintf(tail, sizeof(tail),
             "},\"accepted\":%llu,\"features_dropped\":%llu,"
             "\"tls\":{\"handshakes\":%llu,\"failures\":%llu,"
             "\"resumed\":%llu,\"alpn_h2\":%llu,\"alpn_http1\":%llu,"
             "\"upstream_handshakes\":%llu,\"upstream_resumed\":%llu,"
             "\"upstream_failures\":%llu,\"enabled\":%s,"
             "\"client_enabled\":%s},",
             (unsigned long long)e->accepted.load(
                 std::memory_order_relaxed),
             (unsigned long long)e->features_dropped,
             (unsigned long long)t.handshakes,
             (unsigned long long)t.failures,
             (unsigned long long)t.resumed,
             (unsigned long long)t.alpn_h2,
             (unsigned long long)t.alpn_http1,
             (unsigned long long)t.up_handshakes,
             (unsigned long long)t.up_resumed,
             (unsigned long long)t.up_failures,
             e->tls_srv != nullptr ? "true" : "false",
             e->tls_cli != nullptr ? "true" : "false");
    s += tail;
    l5dtg::tenants_json(e->tenants, e->quotas, &s);
    s += ",";
    l5dtg::guard_json(e->guard, &s);
    s += ",";
    l5dscore::stats_json(*e->slab, e->score_stats, &s);
    s += "}";
    if (s.size() + 1 > cap) return -2;
    memcpy(buf, s.data(), s.size());
    buf[s.size()] = 0;
    return (long)s.size();
}

// Each row: [route_id, latency_ms, status, req_bytes, rsp_bytes, ts_s,
// score, scored, tenant, kind, stream, frame_seq]
long fp_drain_features(void* ep, float* buf, long cap_rows) {
    Engine* e = (Engine*)ep;
    std::lock_guard<std::mutex> g(e->mu);
    long n = (long)e->features.size();
    if (n > cap_rows) n = cap_rows;
    constexpr long W = sizeof(FeatureRow) / sizeof(float);
    for (long i = 0; i < n; i++)
        memcpy(buf + i * W, &e->features[(size_t)i], sizeof(FeatureRow));
    e->features.erase(e->features.begin(), e->features.begin() + n);
    return n;
}

// Install the tenant-extraction mode (call BEFORE fp_start). kind:
// 0 = off, 1 = header (name, matched case-insensitively), 2 = path
// segment (`segment`th element of the request path), 3 = SNI (TLS
// listeners; requires a runtime with SSL_get_servername).
int fp_set_tenant(void* ep, int kind, const char* header, int segment) {
    Engine* e = (Engine*)ep;
    if (kind < 0 || kind > 3) return -1;
    e->tenant_ex.kind = kind;
    e->tenant_ex.header = header != nullptr ? header : "";
    lower(e->tenant_ex.header);
    e->tenant_ex.segment = segment;
    return 0;
}

// Push / clear (limit < 0) a per-tenant concurrency quota, keyed by
// the tenant's 32-bit hash. Safe at any time: the data plane reads
// quotas under the engine mu per request head.
int fp_set_tenant_quota(void* ep, unsigned int hash, int limit) {
    Engine* e = (Engine*)ep;
    std::lock_guard<std::mutex> g(e->mu);
    return e->quotas.set(hash, limit);
}

// Connection-plane guard knobs (call BEFORE fp_start); 0 disables the
// individual defense. tenant_cap bounds the tenant-stats LRU.
int fp_set_guard(void* ep, long header_budget_ms, long body_stall_ms,
                 long accept_burst, long accept_window_ms,
                 long max_hs_inflight, long tenant_cap) {
    Engine* e = (Engine*)ep;
    if (header_budget_ms < 0 || body_stall_ms < 0 || accept_burst < 0 ||
        accept_window_ms < 1 || max_hs_inflight < 0 || tenant_cap < 1)
        return -1;
    e->guard_cfg.header_budget_us = (uint64_t)header_budget_ms * 1000;
    e->guard_cfg.body_stall_budget_us = (uint64_t)body_stall_ms * 1000;
    e->guard_cfg.accept_burst = (uint32_t)accept_burst;
    e->guard_cfg.accept_window_us = (uint64_t)accept_window_ms * 1000;
    e->guard_cfg.max_hs_inflight = (uint32_t)max_hs_inflight;
    std::lock_guard<std::mutex> g(e->mu);
    e->tenants.cap = (size_t)tenant_cap;
    return 0;
}

// Install the dst-path feature-hash column/sign for a route (the
// Python controller computes path_hash_cols over the bound dst path —
// the engine only knows the Host key). Scoring stays off until this
// lands: the model was trained with the hash column set.
int fp_set_route_feature(void* ep, const char* host, int col,
                         float sign) {
    Engine* e = (Engine*)ep;
    std::string key(host);
    lower(key);
    std::lock_guard<std::mutex> g(e->mu);
    auto it = e->routes.find(key);
    if (it == e->routes.end()) return -1;
    it->second.feat.col = col;
    it->second.feat.sign = sign;
    return 0;
}

// Install a route's specialist-bank key (the FNV-1a hash of its bound
// dst path, pushed from Python like the feature column). Until this
// lands the route's rows score on the bank's base model (hash 0 never
// selects a head). Call after fp_set_route.
int fp_set_route_hash(void* ep, const char* host, unsigned int rhash) {
    Engine* e = (Engine*)ep;
    std::string key(host);
    lower(key);
    std::lock_guard<std::mutex> g(e->mu);
    auto it = e->routes.find(key);
    if (it == e->routes.end()) return -1;
    it->second.feat.rhash = rhash;
    return 0;
}

// Publish a weight blob (v1 model or v2 specialist bank) into the
// double-buffered slab (hot-swap; the data plane never pauses).
// Rejects blobs whose in_dim disagrees with the engine featurizer's
// FEATURE_DIM.
int fp_publish_weights(void* ep, const uint8_t* blob, size_t len,
                       char* err, size_t errcap) {
    Engine* e = (Engine*)ep;
    l5dscore::Bank b;
    if (!l5dscore::parse_bank_blob(blob, len, &b, err, errcap))
        return -1;
    if (b.base.in_dim != l5dscore::FEATURE_DIM) {
        l5dscore::fail(err, errcap,
                       "weight blob in_dim does not match engine "
                       "FEATURE_DIM");
        return -1;
    }
    l5dscore::slab_install(e->slab, std::move(b));
    return 0;
}

// Apply a per-route delta patch to the ACTIVE bank (generation-fenced;
// same reader-recheck flip as a full publish — with a shared slab one
// apply covers every worker). Rejected publishes leave the serving
// bank untouched.
int fp_publish_delta(void* ep, const uint8_t* blob, size_t len,
                     char* err, size_t errcap) {
    Engine* e = (Engine*)ep;
    l5dscore::Delta d;
    if (!l5dscore::parse_delta_blob(blob, len, &d, err, errcap))
        return -1;
    if (!l5dscore::slab_apply_delta(e->slab, d, err, errcap)) return -1;
    return 0;
}

// Score/publish through an EXTERNAL weight slab (l5d_slab_create)
// instead of the engine's embedded one — the multi-worker sharding
// seam: every worker of one router attaches the same slab, so a single
// publish (l5d_slab_publish, or fp_publish_weights on any one worker)
// fans out to all cores atomically. slab == NULL restores the embedded
// slab. Call BEFORE fp_start; the loop thread reads the pointer
// unlocked (same contract as the TLS contexts). The caller owns the
// external slab and must free it only after every attached engine has
// shut down.
int fp_attach_slab(void* ep, void* slab) {
    Engine* e = (Engine*)ep;
    if (e->thread_started) return -1;
    e->slab = slab != nullptr ? (l5dscore::Slab*)slab : &e->scorer_slab;
    return 0;
}

// Stream-sentinel knobs (call BEFORE fp_start). Thresholds mirror
// control.state.HysteresisGovernor: 0 < exit < enter <= 1, quorum
// consecutive samples, dwell after each transition. action: 0 =
// observe only, 1 = shed the sick tunnel.
int fp_set_stream_cfg(void* ep, long enabled, long sample_every,
                      long min_gap_ms, long table_cap, double enter,
                      double exitv, long quorum, long dwell_ms,
                      long action) {
    Engine* e = (Engine*)ep;
    if (e->thread_started) return -1;
    if (sample_every < 1 || min_gap_ms < 0 || table_cap < 1 ||
        quorum < 1 || dwell_ms < 0 || action < 0 || action > 1)
        return -1;
    if (enabled != 0 &&
        !(0.0 < exitv && exitv < enter && enter <= 1.0))
        return -1;
    e->stream_cfg.enabled = enabled != 0;
    e->stream_cfg.sample_every = (uint32_t)sample_every;
    e->stream_cfg.sample_min_gap_us = (uint64_t)min_gap_ms * 1000;
    e->stream_cfg.enter = enter;
    e->stream_cfg.exit_ = exitv;
    e->stream_cfg.quorum = (int)quorum;
    e->stream_cfg.dwell_us = (uint64_t)dwell_ms * 1000;
    e->stream_cfg.action = (int)action;
    std::lock_guard<std::mutex> g(e->mu);
    e->stream_tab.cap = (size_t)table_cap;
    return 0;
}

// Tunnel guard budgets (call BEFORE fp_start); 0 disables the
// individual cap. Enforced even when stream scoring is off — they are
// connection-plane defenses like the slowloris budgets.
int fp_set_tunnel_guard(void* ep, long idle_ms, long max_bytes) {
    Engine* e = (Engine*)ep;
    if (e->thread_started) return -1;
    if (idle_ms < 0 || max_bytes < 0) return -1;
    e->stream_cfg.tunnel_idle_us = (uint64_t)idle_ms * 1000;
    e->stream_cfg.tunnel_max_bytes = (uint64_t)max_bytes;
    return 0;
}

long fp_streams_json(void* ep, char* buf, size_t cap) {
    Engine* e = (Engine*)ep;
    std::string s;
    {
        std::lock_guard<std::mutex> g(e->mu);
        l5dstream::streams_json(e->stream_tab,
                                e->stream_cfg.enabled != 0, &s);
    }
    if (s.size() + 1 > cap) return -2;
    memcpy(buf, s.data(), s.size());
    buf[s.size()] = 0;
    return (long)s.size();
}

// Queue a tunnel close by stream key (Python-side actuation); the loop
// thread resolves it against by_skey on its next pass.
int fp_rst_stream(void* ep, unsigned int skey) {
    Engine* e = (Engine*)ep;
    if (skey == 0) return -1;
    {
        std::lock_guard<std::mutex> g(e->mu);
        e->pending_rst.push_back(skey);
    }
    uint64_t v = 1;
    ssize_t r = ::write(e->wakefd, &v, sizeof(v));
    (void)r;
    return 0;
}

void fp_shutdown(void* ep) {
    Engine* e = (Engine*)ep;
    e->running.store(false);
    uint64_t v = 1;
    ssize_t r = ::write(e->wakefd, &v, sizeof(v));
    (void)r;
    if (e->thread_started) pthread_join(e->thread, nullptr);
    for (auto& kv : e->conns) {
        ::close(kv.first);
        delete kv.second;
    }
    for (int lfd : e->listeners) ::close(lfd);
    for (auto& kv : e->tls_sessions) l5dtls::free_ssl_session(kv.second);
    l5dtls::free_ctx(e->tls_srv);
    l5dtls::free_ctx(e->tls_cli);
    ::close(e->wakefd);
    ::close(e->epfd);
    delete e;
}

}  // extern "C"
