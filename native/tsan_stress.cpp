// ThreadSanitizer stress driver for the fastpath engine.
//
// Exercises the cross-thread seams the Python control plane hits in
// production (SURVEY.md §5 race-detection note): concurrent route
// install/remove, live HTTP traffic through the proxy, stats snapshots,
// miss draining, and feature draining — all while the engine's epoll
// thread runs. Build + run via `python native/build.py --sanitize`;
// a clean exit with no TSan report is the pass criterion.

#include <arpa/inet.h>
#include <errno.h>
#include <netinet/in.h>
#include <pthread.h>
#include <signal.h>
#include <time.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <string>
#include <thread>
#include <vector>

#include "scorer.h"        // build_test_blob: the scoring leg's weight source
#include "tenant_guard.h"  // tenant_hash: the quota-push leg's key

extern "C" {
void* fp_create();
int fp_start(void* ep);
int fp_listen(void* ep, const char* ip, int port);
int fp_listen_shared(void* ep, const char* ip, int port);
int fp_listen_tls_shared(void* ep, const char* ip, int port);
int fp_attach_slab(void* ep, void* slab);
int fp_set_route(void* ep, const char* host, const char* endpoints);
int fp_remove_route(void* ep, const char* host);
long fp_drain_misses(void* ep, char* buf, size_t cap);
long fp_stats_json(void* ep, char* buf, size_t cap);
long fp_drain_features(void* ep, float* buf, long cap_rows);
void fp_shutdown(void* ep);
int fp_tls_runtime_available();
int fp_set_tls(void* ep, const char* cert, const char* key,
               const char* alpn, char* err, size_t errcap);
int fp_listen_tls(void* ep, const char* ip, int port);
int fp_set_client_tls(void* ep, const char* alpn, int verify,
                      const char* ca_path, char* err, size_t errcap);
int fp_publish_weights(void* ep, const unsigned char* blob, size_t len,
                       char* err, size_t errcap);
int fp_publish_delta(void* ep, const unsigned char* blob, size_t len,
                     char* err, size_t errcap);
int fp_set_route_feature(void* ep, const char* host, int col, float sign);
int fp_set_route_hash(void* ep, const char* host, unsigned int rhash);
int fp_set_tenant(void* ep, int kind, const char* header, int segment);
int fp_set_tenant_quota(void* ep, unsigned int hash, int limit);
int fp_set_guard(void* ep, long header_budget_ms, long body_stall_ms,
                 long accept_burst, long accept_window_ms,
                 long max_hs_inflight, long tenant_cap);
int fp_set_stream_cfg(void* ep, long enabled, long sample_every,
                      long min_gap_ms, long table_cap, double enter,
                      double exitv, long quorum, long dwell_ms,
                      long action);
int fp_set_tunnel_guard(void* ep, long idle_ms, long max_bytes);
long fp_streams_json(void* ep, char* buf, size_t cap);
int fp_rst_stream(void* ep, unsigned int skey);
}

namespace {

std::atomic<bool> stop{false};
std::atomic<long> responses{0};
std::atomic<long> tls_responses{0};  // via the front-engine TLS chain
std::atomic<long> errors{0};
std::atomic<long> scored_rows{0};    // drained rows the engine pre-scored
std::atomic<long> weight_swaps{0};   // weight publishes that landed
std::atomic<long> tunnel_trips{0};   // CONNECT-tunnel round trips
std::atomic<long> storm_sent{0};     // SIGUSR1s delivered by the storm leg

// The signal-storm leg (below) delivers SIGUSR1 without SA_RESTART, so
// ANY thread's blocking syscall can return EINTR mid-run. The harness
// legs must ride through that themselves — a storm-interrupted read is
// not a dead connection — or the traffic floors fail for the wrong
// reason.
ssize_t xread(int fd, void* buf, size_t n) {
    for (;;) {
        ssize_t r = read(fd, buf, n);
        if (r < 0 && errno == EINTR) continue;
        return r;
    }
}

ssize_t xwrite(int fd, const void* buf, size_t n) {
    for (;;) {
        ssize_t r = write(fd, buf, n);
        if (r < 0 && errno == EINTR) continue;
        return r;
    }
}

long now_ms() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return (long)ts.tv_sec * 1000 + ts.tv_nsec / 1000000;
}

// Minimal blocking HTTP/1.1 backend: fixed 200 response per request.
void backend_loop(int lfd) {
    while (!stop.load()) {
        int fd = accept(lfd, nullptr, nullptr);
        if (fd < 0) {
            if (errno == EINTR) continue;  // storm hit, not shutdown
            return;
        }
        std::thread([fd] {
            char buf[4096];
            std::string acc;
            const char rsp[] =
                "HTTP/1.1 200 OK\r\nContent-Length: 2\r\n\r\nok";
            while (!stop.load()) {
                ssize_t n = xread(fd, buf, sizeof(buf));
                if (n <= 0) break;
                acc.append(buf, n);
                // one response per request head seen
                size_t pos;
                while ((pos = acc.find("\r\n\r\n")) != std::string::npos) {
                    acc.erase(0, pos + 4);
                    if (xwrite(fd, rsp, sizeof(rsp) - 1) < 0) {
                        break;
                    }
                }
            }
            close(fd);
        }).detach();
    }
}

int listen_on(int* port_out) {
    int fd = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = 0;
    if (bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
        close(fd);
        return -1;
    }
    socklen_t len = sizeof(addr);
    getsockname(fd, (sockaddr*)&addr, &len);
    *port_out = ntohs(addr.sin_port);
    listen(fd, 64);
    return fd;
}

// Client: keep-alive requests against the proxy with a Host header.
void client_loop(int proxy_port, int idx, std::atomic<long>* counter) {
    while (!stop.load()) {
        int fd = socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in addr{};
        addr.sin_family = AF_INET;
        addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
        addr.sin_port = htons(proxy_port);
        if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
            close(fd);
            errors.fetch_add(1);
            usleep(1000);
            continue;
        }
        static std::atomic<long> conn_seq{0};
        long seq = conn_seq.fetch_add(1);
        char req[160];
        // rotating tenant ids churn the engine's bounded tenant LRU
        // while stats/features drain concurrently
        int rn = snprintf(req, sizeof(req),
                          "GET / HTTP/1.1\r\nHost: svc-%d\r\n"
                          "l5d-tenant: t-%ld\r\n\r\n",
                          idx % 4, seq % 37);
        char buf[2048];
        for (int i = 0; i < 50 && !stop.load(); i++) {
            if (xwrite(fd, req, rn) < 0) { errors.fetch_add(1); break; }
            ssize_t n = xread(fd, buf, sizeof(buf));
            if (n <= 0) { errors.fetch_add(1); break; }
            counter->fetch_add(1);
        }
        close(fd);
    }
}

// Slowloris attacker: partial request heads, then stall until the
// engine's header budget closes us (the sweep leg under fire).
void slowloris_loop(int proxy_port) {
    while (!stop.load()) {
        int fd = socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in addr{};
        addr.sin_family = AF_INET;
        addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
        addr.sin_port = htons(proxy_port);
        if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
            close(fd);
            usleep(2000);
            continue;
        }
        const char partial[] = "GET / HTTP/1.1\r\nHost: sv";
        (void)write(fd, partial, sizeof(partial) - 1);
        // wait for the engine to close us (or give up after 2s)
        char buf[256];
        struct timeval tv{2, 0};
        setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
        while (xread(fd, buf, sizeof(buf)) > 0) {}
        close(fd);
    }
}

// CONNECT-tunnel client: opens a byte tunnel through the engine to the
// backend (which answers the CONNECT head like any request head:
// 200 + body) and trades bytes through it — the TUNNEL relay,
// per-read featurization, stream-table churn, and mid-tunnel close
// paths under fire. Inside the tunnel the backend still speaks its
// one-response-per-blank-line protocol, so every ping gets opaque
// bytes relayed back.
void tunnel_loop(int proxy_port) {
    while (!stop.load()) {
        int fd = socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in addr{};
        addr.sin_family = AF_INET;
        addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
        addr.sin_port = htons(proxy_port);
        if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
            close(fd);
            usleep(1000);
            continue;
        }
        char buf[2048];
        struct timeval tv{1, 0};
        setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
        const char conreq[] =
            "CONNECT svc-0:80 HTTP/1.1\r\nHost: svc-0\r\n\r\n";
        if (xwrite(fd, conreq, sizeof(conreq) - 1) < 0) {
            close(fd);
            continue;
        }
        if (xread(fd, buf, sizeof(buf)) <= 0) {  // the backend's 200
            close(fd);
            continue;
        }
        for (int i = 0; i < 20 && !stop.load(); i++) {
            const char ping[] = "ping\r\n\r\n";
            if (xwrite(fd, ping, sizeof(ping) - 1) < 0) break;
            // a short read just means the engine shed the tunnel
            // mid-stream (rst leg / sentinel): reconnect and go again
            if (xread(fd, buf, sizeof(buf)) <= 0) break;
            tunnel_trips.fetch_add(1);
        }
        close(fd);
    }
}

// Connection-churn attacker: connect + immediately close, at rate —
// the accept-throttle and fresh-conn bookkeeping under fire.
void churn_flood_loop(int proxy_port) {
    while (!stop.load()) {
        int fd = socket(AF_INET, SOCK_STREAM, 0);
        sockaddr_in addr{};
        addr.sin_family = AF_INET;
        addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
        addr.sin_port = htons(proxy_port);
        if (connect(fd, (sockaddr*)&addr, sizeof(addr)) == 0)
            close(fd);
        else
            close(fd);
        usleep(200);
    }
}

}  // namespace

int main() {
    int backend_port = 0;
    int lfd = listen_on(&backend_port);
    if (lfd < 0) { perror("backend listen"); return 2; }
    std::thread backend(backend_loop, lfd);

    // TLS leg (when the runner provides a cert + the OpenSSL runtime
    // loads): cleartext clients -> front engine (TLS ORIGINATION) ->
    // main engine's TLS listener (TERMINATION) -> backend. Both sides
    // of the memory-BIO pump run under the sanitizer; no TLS client
    // code needed. TLS contexts/listeners are installed BEFORE start()
    // (the wrapper's contract: the loop thread reads them unlocked).
    //
    // Multi-worker leg: the engine under test is a TWO-worker shard
    // group — both workers accept from the SAME ports (SO_REUSEPORT)
    // and score through ONE shared weight slab, so every other leg
    // (traffic, slowloris, churn, rotating-tenant LRU, quota pushes,
    // weight hot-swaps, stats/feature drains) now runs against the
    // sharded topology with two epoll threads reading the slab
    // concurrently while the swapper publishes.
    constexpr int NWORKERS = 2;
    void* workers[NWORKERS];
    l5dscore::Slab shared_slab;
    for (int w = 0; w < NWORKERS; w++) {
        workers[w] = fp_create();
        fp_attach_slab(workers[w], &shared_slab);
    }
    void* ep = workers[0];  // publish/config entry point
    void* front = nullptr;
    const char* cert = getenv("L5D_STRESS_CERT");
    const char* key = getenv("L5D_STRESS_KEY");
    bool tls_leg = cert && key && fp_tls_runtime_available();
    int proxy_port = fp_listen_shared(ep, "127.0.0.1", 0);
    if (proxy_port <= 0) { fprintf(stderr, "fp_listen failed\n"); return 2; }
    for (int w = 1; w < NWORKERS; w++)
        if (fp_listen_shared(workers[w], "127.0.0.1", proxy_port) <= 0) {
            fprintf(stderr, "shared listen failed\n");
            return 2;
        }
    int tls_port = 0, front_port = 0;
    if (tls_leg) {
        char err[256];
        for (int w = 0; w < NWORKERS; w++)
            if (fp_set_tls(workers[w], cert, key, "http/1.1", err,
                           sizeof(err)) != 0) {
                fprintf(stderr, "fp_set_tls: %s\n", err);
                return 2;
            }
        tls_port = fp_listen_tls_shared(ep, "127.0.0.1", 0);
        if (tls_port <= 0) { fprintf(stderr, "tls listen failed\n"); return 2; }
        for (int w = 1; w < NWORKERS; w++)
            if (fp_listen_tls_shared(workers[w], "127.0.0.1",
                                     tls_port) <= 0) {
                fprintf(stderr, "shared tls listen failed\n");
                return 2;
            }
        front = fp_create();
        if (fp_set_client_tls(front, "http/1.1", 0, nullptr, err,
                              sizeof(err)) != 0) {
            fprintf(stderr, "fp_set_client_tls: %s\n", err);
            return 2;
        }
        front_port = fp_listen(front, "127.0.0.1", 0);
        if (front_port <= 0) {
            fprintf(stderr, "front listen failed\n");
            return 2;
        }
    } else {
        fprintf(stderr, "tsan_stress: TLS leg skipped (%s)\n",
                cert && key ? "no OpenSSL runtime" : "no cert in env");
    }
    // tenant + guard legs: header extraction on, tight slowloris
    // budgets (the sweep must reap the attacker threads below), a
    // generous accept throttle (the legit clients must keep flowing),
    // and a small tenant LRU so the rotating-tenant clients force
    // evictions under concurrent stats/feature drains
    for (int w = 0; w < NWORKERS; w++) {
        fp_set_tenant(workers[w], 1, "l5d-tenant", 0);
        fp_set_guard(workers[w], /*header_ms=*/400, /*body_ms=*/400,
                     /*accept_burst=*/100000, /*accept_window_ms=*/1000,
                     /*max_hs_inflight=*/64, /*tenant_cap=*/16);
        // stream sentinel ON with a tiny table (LRU eviction under
        // tunnel churn); enter is high so the tunnel clients rarely
        // trip organically — deterministic mid-stream closes come from
        // the drain thread's fp_rst_stream leg and the idle budget
        fp_set_stream_cfg(workers[w], /*enabled=*/1, /*sample_every=*/2,
                          /*min_gap_ms=*/0, /*table_cap=*/64,
                          /*enter=*/0.95, /*exit=*/0.5, /*quorum=*/4,
                          /*dwell_ms=*/0, /*action=*/1);
        fp_set_tunnel_guard(workers[w], /*idle_ms=*/2000,
                            /*max_bytes=*/0);
        if (fp_start(workers[w]) != 0) {
            fprintf(stderr, "fp_start failed\n");
            return 2;
        }
    }

    char endpoints[64];
    snprintf(endpoints, sizeof(endpoints), "127.0.0.1:%d", backend_port);
    for (int i = 0; i < 4; i++) {
        char host[32];
        snprintf(host, sizeof(host), "svc-%d", i);
        for (int w = 0; w < NWORKERS; w++) {
            fp_set_route(workers[w], host, endpoints);
            // scoring leg: push each route's dst-hash feature column so
            // the in-engine scorer featurizes its rows, and its
            // specialist-bank key (the test banks below carry heads
            // keyed 1000..1003) so head SELECTION runs under fire too
            fp_set_route_feature(workers[w], host, 14 + i,
                                 i % 2 ? -1.0f : 1.0f);
            fp_set_route_hash(workers[w], host, 1000u + (unsigned)i);
        }
    }
    if (front != nullptr) {
        if (fp_start(front) != 0) {
            fprintf(stderr, "front start failed\n");
            return 2;
        }
        char tls_ep[64];
        snprintf(tls_ep, sizeof(tls_ep), "127.0.0.1:%d", tls_port);
        for (int i = 0; i < 4; i++) {
            char host[32];
            snprintf(host, sizeof(host), "svc-%d", i);
            fp_set_route(front, host, tls_ep);
        }
    }

    // control-plane churn thread: install/remove ONE route while
    // traffic runs (svc-0..2 stay stable so their rows keep scoring
    // in-engine; svc-3 exercises the remove/re-add + feature-re-push
    // path the Python controller's _push performs on every update) —
    // broadcast to every worker, exactly as the sharded wrapper does
    std::thread churn([&] {
        int gen = 0;
        while (!stop.load()) {
            for (int w = 0; w < NWORKERS; w++)
                fp_remove_route(workers[w], "svc-3");
            usleep(500);
            for (int w = 0; w < NWORKERS; w++) {
                fp_set_route(workers[w], "svc-3", endpoints);
                fp_set_route_feature(workers[w], "svc-3", 17,
                                     gen % 2 ? -1.0f : 1.0f);
                fp_set_route_hash(workers[w], "svc-3", 1003u);
            }
            // per-tenant quota push/clear races the data plane's
            // quota reads (the TenantAdmission actuation path)
            unsigned int th = l5dtg::tenant_hash("t-3", 3);
            for (int w = 0; w < NWORKERS; w++)
                fp_set_tenant_quota(workers[w], th, gen % 2 ? 1 : -1);
            gen++;
            usleep(1500);
        }
    });

    // weight-swap thread: alternating f32/int8/int4 BANK blobs (base +
    // specialist heads) hot-swap into the SHARED slab, each followed
    // by a generation-fenced per-route DELTA patch (the distiller's
    // publish path), while both workers' epoll threads score — and
    // head-select — concurrently: the double-buffer + reader-recheck
    // protocol with multi-core readers under sanitizer fire. One
    // publish (through any worker) must fan out to every worker
    // atomically.
    std::thread swapper([&] {
        std::vector<uint8_t> blob;
        char err[256];
        uint32_t gen = 1;
        while (!stop.load()) {
            const int quant = (int)(gen % 3);
            l5dscore::build_test_bank_blob(&blob, gen, quant, gen, 2);
            if (fp_publish_weights(workers[gen % NWORKERS], blob.data(),
                                   blob.size(), err, sizeof(err)) == 0)
                weight_swaps.fetch_add(1);
            // fenced delta: upsert a head for one of the live routes
            // (1000..1003), then a remove of it on the next patch
            l5dscore::build_test_delta_blob(
                &blob, gen, gen + 1, 1000u + gen % 4, quant, gen + 7,
                /*remove=*/false);
            if (fp_publish_delta(workers[(gen + 1) % NWORKERS],
                                 blob.data(), blob.size(), err,
                                 sizeof(err)) == 0)
                weight_swaps.fetch_add(1);
            l5dscore::build_test_delta_blob(
                &blob, gen + 1, gen + 2, 1000u + gen % 4, quant,
                gen + 9, /*remove=*/true);
            if (fp_publish_delta(workers[gen % NWORKERS], blob.data(),
                                 blob.size(), err, sizeof(err)) == 0)
                weight_swaps.fetch_add(1);
            gen += 3;
            usleep(1000);
        }
    });

    // drain thread: misses + stats + features from EVERY worker, like
    // the sharded Python controller's fan-in
    std::thread drain([&] {
        std::vector<char> buf(1 << 16);
        std::vector<float> feats(64 * 1024);
        long iter = 0;
        while (!stop.load()) {
            // stream-sentinel leg: skeys are sequential, so low keys
            // DO resolve to live tunnels — mid-stream close under fire
            if (iter % 8 == 0)
                for (int w = 0; w < NWORKERS; w++)
                    fp_rst_stream(workers[w],
                                  (unsigned)(iter / 8 % 512) + 1);
            for (int w = 0; w < NWORKERS; w++) {
                fp_drain_misses(workers[w], buf.data(), buf.size());
                fp_stats_json(workers[w], buf.data(), buf.size());
                fp_streams_json(workers[w], buf.data(), buf.size());
                long n = fp_drain_features(workers[w], feats.data(),
                                           1024);
                for (long r = 0; r < n; r++)
                    if (feats[r * 12 + 7] > 0.5f)
                        scored_rows.fetch_add(1);
            }
            if (front != nullptr) {
                fp_drain_misses(front, buf.data(), buf.size());
                fp_stats_json(front, buf.data(), buf.size());
                fp_drain_features(front, feats.data(), 1024);
            }
            usleep(2000);
            iter++;
        }
    });

    // signal-storm leg: a no-op SIGUSR1 handler installed WITHOUT
    // SA_RESTART, then a thread peppering the whole process with it.
    // Every blocking syscall in every thread — including the engines'
    // epoll/recv/send/accept4 loops — now sees spurious EINTR, which
    // is exactly the regression pin for the engines' EINTR-retry
    // paths: drop one of those `errno == EINTR` branches and this leg
    // turns interrupts into dropped conns and the traffic floors fail.
    struct sigaction storm_sa {};
    storm_sa.sa_handler = [](int) {};
    sigemptyset(&storm_sa.sa_mask);
    storm_sa.sa_flags = 0;  // deliberately NOT SA_RESTART
    sigaction(SIGUSR1, &storm_sa, nullptr);
    std::thread storm([] {
        while (!stop.load()) {
            kill(getpid(), SIGUSR1);
            storm_sent.fetch_add(1);
            usleep(3000);
        }
    });

    std::vector<std::thread> clients;
    for (int i = 0; i < 4; i++)
        clients.emplace_back(client_loop, proxy_port, i, &responses);
    clients.emplace_back(slowloris_loop, proxy_port);
    clients.emplace_back(churn_flood_loop, proxy_port);
    clients.emplace_back(tunnel_loop, proxy_port);
    clients.emplace_back(tunnel_loop, proxy_port);
    if (tls_leg)  // the TLS chain: front (originate) -> ep (terminate)
        for (int i = 0; i < 2; i++)
            clients.emplace_back(client_loop, front_port, i,
                                 &tls_responses);

    // sleep(5) would return in milliseconds under the storm; pace on
    // the monotonic clock instead (usleep early-returns are fine, the
    // loop re-checks elapsed time)
    const long t0 = now_ms();
    while (now_ms() - t0 < 5000) usleep(20000);
    stop.store(true);
    storm.join();
    for (auto& t : clients) t.join();
    churn.join();
    swapper.join();
    drain.join();
    if (front != nullptr) fp_shutdown(front);
    // every worker joins its loop thread here, BEFORE the shared slab
    // (a stack local) goes out of scope — mirrors the wrapper's close()
    for (int w = 0; w < NWORKERS; w++) fp_shutdown(workers[w]);
    shutdown(lfd, SHUT_RDWR);
    close(lfd);
    backend.detach();

    fprintf(stderr, "tsan_stress: %ld responses (%ld via TLS), "
            "%ld errors, %ld rows scored in-engine across %ld weight "
            "swaps, %ld tunnel round-trips, %ld storm signals\n",
            responses.load(), tls_responses.load(), errors.load(),
            scored_rows.load(), weight_swaps.load(),
            tunnel_trips.load(), storm_sent.load());
    if (responses.load() < 100) {
        fprintf(stderr, "tsan_stress: too little traffic flowed\n");
        return 1;
    }
    if (tunnel_trips.load() < 20) {
        fprintf(stderr, "tsan_stress: tunnel leg starved (%ld)\n",
                tunnel_trips.load());
        return 1;
    }
    if (tls_leg && tls_responses.load() < 50) {
        fprintf(stderr, "tsan_stress: too little TLS traffic flowed\n");
        return 1;
    }
    if (storm_sent.load() < 200) {
        fprintf(stderr, "tsan_stress: signal storm starved (%ld)\n",
                storm_sent.load());
        return 1;
    }
    if (scored_rows.load() < 50 || weight_swaps.load() < 100) {
        fprintf(stderr, "tsan_stress: scoring leg starved "
                "(scored=%ld swaps=%ld)\n", scored_rows.load(),
                weight_swaps.load());
        return 1;
    }
    return 0;
}
