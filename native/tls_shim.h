// TLS for the native data plane, without build-time OpenSSL headers.
//
// The reference terminates TLS inside its fast path via netty-tcnative
// boringssl (project/Deps.scala:24). The analogous move here must work
// in containers that ship only the OpenSSL *runtime* (libssl.so.1.1 —
// no /usr/include/openssl), so this shim declares the small stable
// slice of the OpenSSL 1.1 ABI it needs and resolves it with
// dlopen/dlsym at first use. Everything is opaque-pointer based, which
// is exactly how the 1.1 API is designed to be consumed; when the
// runtime is missing the engines report TLS unavailable and Python
// keeps serving TLS on its own data plane (graceful gate, not a build
// failure).
//
// The I/O model is non-blocking memory BIOs: the epoll loop owns the
// sockets and moves ciphertext in/out of the BIO pair; OpenSSL never
// sees a file descriptor and can never block the loop. Handshake,
// ALPN selection and session resumption (tickets) all ride the same
// pump:
//
//   socket readable --> feed(ciphertext) --> pump() --> plaintext in
//   plaintext out   --> write_plain()    --> cipher_out --> socket
//
// Used by fastpath.cpp / h2_fastpath.cpp (both proxy legs), the
// h2bench load generator's TLS mode, and the TSan/ASan stress drivers.
#pragma once

#include <dlfcn.h>
#include <pthread.h>
#include <stddef.h>
#include <stdio.h>
#include <string.h>

#include <string>

namespace l5dtls {

// ---- the OpenSSL 1.1 ABI slice (opaque types + constants) ----

typedef struct ssl_ctx_st SSL_CTX;
typedef struct ssl_st SSL;
typedef struct bio_st BIO;
typedef struct bio_method_st BIO_METHOD;
typedef struct ssl_method_st SSL_METHOD;
typedef struct ssl_session_st SSL_SESSION;
typedef struct x509_vp_st X509_VERIFY_PARAM;

constexpr int SSL_FILETYPE_PEM = 1;
constexpr int SSL_ERROR_NONE = 0;
constexpr int SSL_ERROR_WANT_READ = 2;
constexpr int SSL_ERROR_WANT_WRITE = 3;
constexpr int SSL_ERROR_ZERO_RETURN = 6;
constexpr long SSL_CTRL_MODE = 33;
constexpr long SSL_CTRL_SET_SESS_CACHE_MODE = 44;
constexpr long SSL_MODE_ENABLE_PARTIAL_WRITE = 0x1;
constexpr long SSL_MODE_ACCEPT_MOVING_WRITE_BUFFER = 0x2;
constexpr long SSL_MODE_RELEASE_BUFFERS = 0x10;
constexpr long SSL_SESS_CACHE_CLIENT = 0x1;
constexpr long SSL_SESS_CACHE_SERVER = 0x2;
constexpr int SSL_VERIFY_NONE = 0;
constexpr int SSL_VERIFY_PEER = 1;
constexpr int SSL_SENT_SHUTDOWN = 1;
constexpr int SSL_RECEIVED_SHUTDOWN = 2;
constexpr int SSL_TLSEXT_ERR_OK = 0;
constexpr int SSL_TLSEXT_ERR_NOACK = 3;
constexpr long BIO_CTRL_PENDING = 10;

struct Api {
    void* h_ssl = nullptr;
    void* h_crypto = nullptr;
    bool ok = false;
    std::string err;

    const SSL_METHOD* (*TLS_method)();
    SSL_CTX* (*SSL_CTX_new)(const SSL_METHOD*);
    void (*SSL_CTX_free)(SSL_CTX*);
    int (*SSL_CTX_use_certificate_chain_file)(SSL_CTX*, const char*);
    int (*SSL_CTX_use_PrivateKey_file)(SSL_CTX*, const char*, int);
    int (*SSL_CTX_check_private_key)(const SSL_CTX*);
    long (*SSL_CTX_ctrl)(SSL_CTX*, int, long, void*);
    void (*SSL_CTX_set_verify)(SSL_CTX*, int,
                               int (*)(int, void*));
    int (*SSL_CTX_load_verify_locations)(SSL_CTX*, const char*,
                                         const char*);
    void (*SSL_CTX_set_alpn_select_cb)(
        SSL_CTX*,
        int (*)(SSL*, const unsigned char**, unsigned char*,
                const unsigned char*, unsigned, void*),
        void*);
    int (*SSL_set_alpn_protos)(SSL*, const unsigned char*, unsigned);
    void (*SSL_get0_alpn_selected)(const SSL*, const unsigned char**,
                                   unsigned*);
    SSL* (*SSL_new)(SSL_CTX*);
    void (*SSL_free)(SSL*);
    void (*SSL_set_accept_state)(SSL*);
    void (*SSL_set_connect_state)(SSL*);
    void (*SSL_set_bio)(SSL*, BIO*, BIO*);
    int (*SSL_do_handshake)(SSL*);
    int (*SSL_read)(SSL*, void*, int);
    int (*SSL_write)(SSL*, const void*, int);
    int (*SSL_get_error)(const SSL*, int);
    long (*SSL_ctrl)(SSL*, int, long, void*);
    int (*SSL_session_reused)(SSL*);
    SSL_SESSION* (*SSL_get1_session)(SSL*);
    int (*SSL_set_session)(SSL*, SSL_SESSION*);
    void (*SSL_SESSION_free)(SSL_SESSION*);
    X509_VERIFY_PARAM* (*SSL_get0_param)(SSL*);
    int (*X509_VERIFY_PARAM_set1_host)(X509_VERIFY_PARAM*, const char*,
                                       size_t);
    int (*SSL_shutdown)(SSL*);
    void (*SSL_set_shutdown)(SSL*, int);
    // optional (present in 1.1 and 3.x): server-side SNI retrieval for
    // tenant extraction; nullptr when the runtime lacks it
    const char* (*SSL_get_servername)(const SSL*, int);
    BIO* (*BIO_new)(const BIO_METHOD*);
    const BIO_METHOD* (*BIO_s_mem)();
    int (*BIO_write)(BIO*, const void*, int);
    int (*BIO_read)(BIO*, void*, int);
    long (*BIO_ctrl)(BIO*, int, long, void*);
    unsigned long (*ERR_get_error)();
    void (*ERR_error_string_n)(unsigned long, char*, size_t);
    void (*ERR_clear_error)();
};

inline Api& api() {
    static Api a;
    static pthread_once_t once = PTHREAD_ONCE_INIT;
    static auto init = [] {
        // try the sonames this container family actually ships; the
        // 1.1 names first (what this image has), then 3.x (the set1_host
        // / options signatures are register-compatible on LP64)
        const char* ssl_names[] = {"libssl.so.1.1", "libssl.so.3",
                                   "libssl.so"};
        const char* crypto_names[] = {"libcrypto.so.1.1", "libcrypto.so.3",
                                      "libcrypto.so"};
        for (const char* n : crypto_names) {
            a.h_crypto = dlopen(n, RTLD_NOW | RTLD_GLOBAL);
            if (a.h_crypto) break;
        }
        for (const char* n : ssl_names) {
            a.h_ssl = dlopen(n, RTLD_NOW | RTLD_GLOBAL);
            if (a.h_ssl) break;
        }
        if (!a.h_ssl || !a.h_crypto) {
            a.err = "libssl/libcrypto runtime not found";
            return;
        }
        bool all = true;
        auto want = [&](const char* name) -> void* {
            void* p = dlsym(a.h_ssl, name);
            if (!p) p = dlsym(a.h_crypto, name);
            if (!p) {
                all = false;
                if (a.err.empty())
                    // l5d: ignore[hot-alloc] — one-shot dlopen symbol loader; api() runs its resolver exactly once per process, never on the event path
                    a.err = std::string("missing symbol ") + name;
            }
            return p;
        };
#define L5D_SYM(n) a.n = (decltype(a.n))want(#n)
        L5D_SYM(TLS_method);
        L5D_SYM(SSL_CTX_new);
        L5D_SYM(SSL_CTX_free);
        L5D_SYM(SSL_CTX_use_certificate_chain_file);
        L5D_SYM(SSL_CTX_use_PrivateKey_file);
        L5D_SYM(SSL_CTX_check_private_key);
        L5D_SYM(SSL_CTX_ctrl);
        L5D_SYM(SSL_CTX_set_verify);
        L5D_SYM(SSL_CTX_load_verify_locations);
        L5D_SYM(SSL_CTX_set_alpn_select_cb);
        L5D_SYM(SSL_set_alpn_protos);
        L5D_SYM(SSL_get0_alpn_selected);
        L5D_SYM(SSL_new);
        L5D_SYM(SSL_free);
        L5D_SYM(SSL_set_accept_state);
        L5D_SYM(SSL_set_connect_state);
        L5D_SYM(SSL_set_bio);
        L5D_SYM(SSL_do_handshake);
        L5D_SYM(SSL_read);
        L5D_SYM(SSL_write);
        L5D_SYM(SSL_get_error);
        L5D_SYM(SSL_ctrl);
        L5D_SYM(SSL_session_reused);
        L5D_SYM(SSL_get1_session);
        L5D_SYM(SSL_set_session);
        L5D_SYM(SSL_SESSION_free);
        L5D_SYM(SSL_get0_param);
        L5D_SYM(X509_VERIFY_PARAM_set1_host);
        L5D_SYM(SSL_shutdown);
        L5D_SYM(SSL_set_shutdown);
        // optional: load without failing the slice when absent
        a.SSL_get_servername = (decltype(a.SSL_get_servername))
            dlsym(a.h_ssl, "SSL_get_servername");
        L5D_SYM(BIO_new);
        L5D_SYM(BIO_s_mem);
        L5D_SYM(BIO_write);
        L5D_SYM(BIO_read);
        L5D_SYM(BIO_ctrl);
        L5D_SYM(ERR_get_error);
        L5D_SYM(ERR_error_string_n);
        L5D_SYM(ERR_clear_error);
#undef L5D_SYM
        a.ok = all;
    };
    pthread_once(&once, [] { init(); });
    return a;
}

inline bool available() { return api().ok; }
inline const char* load_error() { return api().err.c_str(); }

inline std::string ossl_errors() {
    Api& a = api();
    std::string out;
    char buf[256];
    for (int i = 0; i < 4; i++) {
        unsigned long e = a.ERR_get_error();
        if (!e) break;
        a.ERR_error_string_n(e, buf, sizeof(buf));
        if (!out.empty()) out += "; ";
        out += buf;
    }
    return out.empty() ? "unknown TLS error" : out;
}

// ---- contexts ----

// ALPN preference list in wire format: len-prefixed protocol names.
inline std::string alpn_wire(const char* csv) {
    std::string out;
    if (csv == nullptr) return out;
    const char* p = csv;
    while (*p) {
        const char* c = strchr(p, ',');
        size_t n = c ? (size_t)(c - p) : strlen(p);
        if (n > 0 && n < 256) {
            out.push_back((char)n);
            out.append(p, n);
        }
        p = c ? c + 1 : p + n;
    }
    return out;
}

struct Ctx {
    SSL_CTX* ctx = nullptr;
    std::string alpn;  // wire-format preference list (ours)
    bool is_server = false;
};

// Server-preference ALPN select: first of OUR protocols the client
// offered; no overlap -> NOACK (proceed without ALPN, prior-knowledge
// clients still work).
inline int alpn_select_cb(SSL*, const unsigned char** out,
                          unsigned char* outlen, const unsigned char* in,
                          unsigned inlen, void* arg) {
    Ctx* c = (Ctx*)arg;
    const unsigned char* pref = (const unsigned char*)c->alpn.data();
    size_t pn = c->alpn.size();
    for (size_t i = 0; i < pn;) {
        unsigned char plen = pref[i];
        for (unsigned j = 0; j < inlen;) {
            unsigned char clen = in[j];
            if (clen == plen && j + 1 + clen <= inlen &&
                memcmp(pref + i + 1, in + j + 1, clen) == 0) {
                *out = in + j + 1;
                *outlen = clen;
                return SSL_TLSEXT_ERR_OK;
            }
            j += 1 + clen;
        }
        i += 1 + plen;
    }
    return SSL_TLSEXT_ERR_NOACK;
}

// Server context: cert/key PEM + ALPN preference list ("h2,http/1.1").
// nullptr + *err on failure.
inline Ctx* server_ctx(const char* cert_path, const char* key_path,
                       const char* alpn_csv, std::string* err) {
    Api& a = api();
    if (!a.ok) {
        if (err) *err = a.err;
        return nullptr;
    }
    a.ERR_clear_error();
    SSL_CTX* sc = a.SSL_CTX_new(a.TLS_method());
    if (!sc) {
        if (err) *err = ossl_errors();
        return nullptr;
    }
    a.SSL_CTX_ctrl(sc, SSL_CTRL_MODE,
                   SSL_MODE_ENABLE_PARTIAL_WRITE |
                       SSL_MODE_ACCEPT_MOVING_WRITE_BUFFER |
                       SSL_MODE_RELEASE_BUFFERS,
                   nullptr);
    // session tickets are on by default; keep a server-side cache too so
    // ticketless clients can still resume
    a.SSL_CTX_ctrl(sc, SSL_CTRL_SET_SESS_CACHE_MODE, SSL_SESS_CACHE_SERVER,
                   nullptr);
    if (a.SSL_CTX_use_certificate_chain_file(sc, cert_path) != 1 ||
        a.SSL_CTX_use_PrivateKey_file(sc, key_path, SSL_FILETYPE_PEM) != 1 ||
        a.SSL_CTX_check_private_key(sc) != 1) {
        if (err) *err = ossl_errors();
        a.SSL_CTX_free(sc);
        return nullptr;
    }
    Ctx* c = new Ctx();
    c->ctx = sc;
    c->is_server = true;
    c->alpn = alpn_wire(alpn_csv);
    if (!c->alpn.empty())
        a.SSL_CTX_set_alpn_select_cb(sc, alpn_select_cb, c);
    return c;
}

// Client context. verify=false skips chain+hostname validation
// (tls.disableValidation parity); ca_path, when set, replaces the
// default trust roots.
inline Ctx* client_ctx(const char* alpn_csv, bool verify,
                       const char* ca_path, std::string* err) {
    Api& a = api();
    if (!a.ok) {
        if (err) *err = a.err;
        return nullptr;
    }
    a.ERR_clear_error();
    SSL_CTX* sc = a.SSL_CTX_new(a.TLS_method());
    if (!sc) {
        if (err) *err = ossl_errors();
        return nullptr;
    }
    a.SSL_CTX_ctrl(sc, SSL_CTRL_MODE,
                   SSL_MODE_ENABLE_PARTIAL_WRITE |
                       SSL_MODE_ACCEPT_MOVING_WRITE_BUFFER |
                       SSL_MODE_RELEASE_BUFFERS,
                   nullptr);
    a.SSL_CTX_ctrl(sc, SSL_CTRL_SET_SESS_CACHE_MODE, SSL_SESS_CACHE_CLIENT,
                   nullptr);
    if (verify) {
        if (ca_path != nullptr && *ca_path) {
            if (a.SSL_CTX_load_verify_locations(sc, ca_path, nullptr) != 1) {
                if (err) *err = ossl_errors();
                a.SSL_CTX_free(sc);
                return nullptr;
            }
        }
        a.SSL_CTX_set_verify(sc, SSL_VERIFY_PEER, nullptr);
    } else {
        a.SSL_CTX_set_verify(sc, SSL_VERIFY_NONE, nullptr);
    }
    Ctx* c = new Ctx();
    c->ctx = sc;
    c->is_server = false;
    c->alpn = alpn_wire(alpn_csv);
    return c;
}

inline void free_ctx(Ctx* c) {
    if (!c) return;
    if (c->ctx) api().SSL_CTX_free(c->ctx);
    delete c;
}

// ---- per-connection session (the memory-BIO pump) ----

struct Sess {
    SSL* ssl = nullptr;
    BIO* rbio = nullptr;  // ciphertext from the peer (we BIO_write)
    BIO* wbio = nullptr;  // ciphertext to the peer (we BIO_read)
    bool is_server = false;
    bool hs_done = false;
    bool fatal = false;
    std::string alpn;       // negotiated protocol ("" = none)
    std::string last_err;
};

// verify_name: hostname pinned against the peer cert (client side with
// verification); also sent as SNI. resume: cached SSL_SESSION* to offer
// (client side), or nullptr.
inline Sess* new_session(Ctx* c, const char* verify_name, bool verify,
                         SSL_SESSION* resume) {
    Api& a = api();
    if (!a.ok || c == nullptr || c->ctx == nullptr) return nullptr;
    a.ERR_clear_error();
    SSL* ssl = a.SSL_new(c->ctx);
    if (!ssl) return nullptr;
    BIO* rbio = a.BIO_new(a.BIO_s_mem());
    BIO* wbio = a.BIO_new(a.BIO_s_mem());
    if (!rbio || !wbio) {
        a.SSL_free(ssl);
        return nullptr;
    }
    a.SSL_set_bio(ssl, rbio, wbio);  // SSL owns the BIOs now
    Sess* s = new Sess();
    s->ssl = ssl;
    s->rbio = rbio;
    s->wbio = wbio;
    s->is_server = c->is_server;
    if (c->is_server) {
        a.SSL_set_accept_state(ssl);
    } else {
        if (!c->alpn.empty())
            a.SSL_set_alpn_protos(ssl,
                                  (const unsigned char*)c->alpn.data(),
                                  (unsigned)c->alpn.size());
        if (verify_name != nullptr && *verify_name) {
            // SNI (SSL_ctrl SSL_CTRL_SET_TLSEXT_HOSTNAME=55, type=0)
            a.SSL_ctrl(ssl, 55, 0, (void*)verify_name);
            if (verify)
                a.X509_VERIFY_PARAM_set1_host(a.SSL_get0_param(ssl),
                                              verify_name, 0);
        }
        if (resume != nullptr) a.SSL_set_session(ssl, resume);
        a.SSL_set_connect_state(ssl);
    }
    return s;
}

inline void free_session(Sess* s) {
    if (!s) return;
    if (s->ssl) {
        // Mark the connection cleanly shut down even when the close was
        // abortive: SSL_free on an un-shutdown SSL invalidates its
        // session (ssl_clear_bad_session marks it not_resumable), which
        // would silently defeat resumption for any stashed session ref.
        api().SSL_set_shutdown(s->ssl,
                               SSL_SENT_SHUTDOWN | SSL_RECEIVED_SHUTDOWN);
        api().SSL_free(s->ssl);  // frees the BIO pair too
    }
    delete s;
}

// Ciphertext read from the socket. Memory BIOs grow as needed; callers
// feed at most one socket read (<=64KB) per call, so growth is bounded
// by the read loop.
inline bool feed(Sess* s, const char* data, size_t n) {
    Api& a = api();
    size_t off = 0;
    while (off < n) {
        int w = a.BIO_write(s->rbio, data + off, (int)(n - off));
        if (w <= 0) {
            s->fatal = true;
            s->last_err = "BIO_write failed";
            return false;
        }
        off += (size_t)w;
    }
    return true;
}

inline void drain_wbio(Sess* s, std::string* cipher_out) {
    Api& a = api();
    char buf[16 * 1024];
    while (a.BIO_ctrl(s->wbio, BIO_CTRL_PENDING, 0, nullptr) > 0) {
        int r = a.BIO_read(s->wbio, buf, sizeof(buf));
        if (r <= 0) break;
        cipher_out->append(buf, (size_t)r);
    }
}

// Advance the state machine: handshake if pending, then decrypt all
// available plaintext into *plain_in; outgoing ciphertext (handshake
// records, session tickets, close-notify responses) is appended to
// *cipher_out. Returns 0 = ok, -1 = fatal (flush cipher_out, close),
// 1 = clean TLS shutdown from the peer.
inline int pump(Sess* s, std::string* plain_in, std::string* cipher_out) {
    Api& a = api();
    if (s->fatal) return -1;
    a.ERR_clear_error();
    if (!s->hs_done) {
        int r = a.SSL_do_handshake(s->ssl);
        drain_wbio(s, cipher_out);
        if (r == 1) {
            s->hs_done = true;
            const unsigned char* proto = nullptr;
            unsigned plen = 0;
            a.SSL_get0_alpn_selected(s->ssl, &proto, &plen);
            if (proto != nullptr && plen > 0)
                s->alpn.assign((const char*)proto, plen);
        } else {
            int e = a.SSL_get_error(s->ssl, r);
            if (e != SSL_ERROR_WANT_READ && e != SSL_ERROR_WANT_WRITE) {
                s->fatal = true;
                s->last_err = ossl_errors();
                return -1;
            }
            return 0;  // need more ciphertext from the peer
        }
    }
    char buf[16 * 1024];
    for (;;) {
        int r = a.SSL_read(s->ssl, buf, sizeof(buf));
        if (r > 0) {
            plain_in->append(buf, (size_t)r);
            continue;
        }
        int e = a.SSL_get_error(s->ssl, r);
        drain_wbio(s, cipher_out);
        if (e == SSL_ERROR_WANT_READ || e == SSL_ERROR_WANT_WRITE)
            return 0;
        if (e == SSL_ERROR_ZERO_RETURN) return 1;  // close-notify
        s->fatal = true;
        s->last_err = ossl_errors();
        return -1;
    }
}

// Encrypt plaintext; ciphertext lands in *cipher_out. Returns bytes of
// plaintext consumed (0 while the handshake is still in flight, which
// is not an error), or -1 on fatal error.
inline long write_plain(Sess* s, const char* data, size_t n,
                        std::string* cipher_out) {
    Api& a = api();
    if (s->fatal) return -1;
    if (!s->hs_done) {
        // drive the handshake opportunistically so connect-side sessions
        // emit their ClientHello without waiting for socket readability
        std::string scratch;
        if (pump(s, &scratch, cipher_out) < 0) return -1;
        // (scratch stays empty pre-handshake)
        if (!s->hs_done) return 0;
    }
    a.ERR_clear_error();
    size_t off = 0;
    while (off < n) {
        int w = a.SSL_write(s->ssl, data + off, (int)(n - off));
        if (w > 0) {
            off += (size_t)w;
            continue;
        }
        int e = a.SSL_get_error(s->ssl, w);
        if (e == SSL_ERROR_WANT_READ || e == SSL_ERROR_WANT_WRITE) break;
        s->fatal = true;
        s->last_err = ossl_errors();
        drain_wbio(s, cipher_out);
        return -1;
    }
    drain_wbio(s, cipher_out);
    return (long)off;
}

inline bool resumed(Sess* s) {
    return s->ssl != nullptr && api().SSL_session_reused(s->ssl) == 1;
}

// Server-side SNI the client sent (TLSEXT_NAMETYPE_host_name = 0), or
// "" when none / the runtime lacks SSL_get_servername. Valid once the
// ClientHello has been processed (post-handshake is always safe).
inline std::string server_sni(Sess* s) {
    Api& a = api();
    if (s == nullptr || s->ssl == nullptr ||
        a.SSL_get_servername == nullptr)
        return "";
    const char* name = a.SSL_get_servername(s->ssl, 0);
    return name != nullptr ? std::string(name) : "";
}

// Client-side resumption: take a ref on the current session (caller
// frees with free_ssl_session; TLS1.3 tickets arrive post-handshake so
// call this after traffic has flowed).
inline SSL_SESSION* get1_session(Sess* s) {
    return api().SSL_get1_session(s->ssl);
}

inline void free_ssl_session(SSL_SESSION* sess) {
    if (sess != nullptr) api().SSL_SESSION_free(sess);
}

// Append a close-notify record to cipher_out (best-effort graceful
// shutdown; safe to skip on abortive closes).
inline void shutdown(Sess* s, std::string* cipher_out) {
    if (s->fatal || !s->hs_done) return;
    api().SSL_shutdown(s->ssl);
    drain_wbio(s, cipher_out);
}

}  // namespace l5dtls
