"""Announcers: register server addresses into service discovery.

Ref: linkerd/core/.../Announcer.scala:41 (SPI; ``servers[].announce``
paths matched by announcer prefix, driven from Main.announce,
linkerd/main/.../Main.scala:97-130) and linkerd/announcer/serversets
ZkAnnouncer.scala:19. The fs announcer is the file-based counterpart of
the fs namer — a linkerd announcing into a directory that other linkerds
discover from (the single-node-stack analogue of serversets).
"""

from __future__ import annotations

import abc
import os
from dataclasses import dataclass
from typing import List, Tuple

from linkerd_tpu.config import ConfigError, register
from linkerd_tpu.core import Path
from linkerd_tpu.core.var import Closable


class Announcer(abc.ABC):
    prefix: Path

    @abc.abstractmethod
    def announce(self, host: str, port: int, name: Path) -> Closable:
        """Register host:port under ``name`` (the path AFTER the
        announcer prefix); the Closable withdraws it."""


class FsAnnouncer(Announcer):
    """One file per announced name; one ``host port`` line per announcer
    (kind ``io.l5d.fs``)."""

    def __init__(self, root_dir: str, prefix: Path):
        self.root = root_dir
        self.prefix = prefix
        os.makedirs(root_dir, exist_ok=True)

    def _file(self, name: Path) -> str:
        if len(name) == 0:
            raise ValueError("empty announce name")
        return os.path.join(self.root, "-".join(name))

    def _rewrite(self, path: str, drop: str, add: str = "") -> None:
        # Multiple linkerds announce into one shared directory, so the
        # read-modify-write must be serialized across PROCESSES: flock on
        # a sidecar lock file (the serversets analogue of ZK's atomicity).
        import fcntl
        with open(path + ".lock", "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            try:
                lines: List[str] = []
                if os.path.exists(path):
                    with open(path) as f:
                        lines = [ln for ln in f.read().splitlines()
                                 if ln.strip() and ln.strip() != drop]
                if add:
                    lines.append(add)
                if lines:
                    tmp = path + ".tmp"
                    with open(tmp, "w") as f:
                        f.write("\n".join(lines) + "\n")
                    os.replace(tmp, path)
                elif os.path.exists(path):
                    os.unlink(path)
            finally:
                fcntl.flock(lock, fcntl.LOCK_UN)

    def announce(self, host: str, port: int, name: Path) -> Closable:
        path = self._file(name)
        entry = f"{host} {port}"
        self._rewrite(path, drop=entry, add=entry)
        return Closable(lambda: self._rewrite(path, drop=entry))


@register("announcer", "io.l5d.fs")
@dataclass
class FsAnnouncerConfig:
    rootDir: str = ""
    prefix: str = "/io.l5d.fs"

    def mk(self) -> Announcer:
        if not self.rootDir:
            raise ConfigError("io.l5d.fs announcer needs rootDir")
        return FsAnnouncer(self.rootDir, Path.read(self.prefix))


class ZkAnnouncer(Announcer):
    """Announce into a ZK serverset: an ephemeral-sequential ``member_``
    node carrying serviceEndpoint JSON under ``{pathPrefix}{name}``
    (kind ``io.l5d.serversets``; ref: linkerd/announcer/serversets/...
    /ZkAnnouncer.scala:19 — ephemerality is the withdrawal mechanism, so
    a crashed linkerd's announcement dies with its session)."""

    def __init__(self, hosts: str, path_prefix: Path, prefix: Path,
                 session_timeout_ms: int = 10000):
        from linkerd_tpu.namer.zk import shared_zk

        self.zk = shared_zk(hosts, session_timeout_ms)
        self.path_prefix = path_prefix
        self.prefix = prefix

    def announce(self, host: str, port: int, name: Path) -> Closable:
        import asyncio
        import json
        import logging

        from linkerd_tpu.zk.client import ZkError, ZK_NONODE, zk_backoff

        log = logging.getLogger(__name__)
        zk_path = "/" + "/".join(self.path_prefix + name)
        data = json.dumps({
            "serviceEndpoint": {"host": host, "port": port},
            "additionalEndpoints": {},
            "status": "ALIVE",
        }).encode("utf-8")
        state = {"node": None}

        async def maintain() -> None:
            # Supervising loop: (re)create the ephemeral member and
            # re-announce whenever it disappears (session expiry deletes
            # ephemerals server-side; the watch — or the synthetic
            # Disconnected event on session loss — wakes us to rejoin).
            attempt = 0
            try:
                while True:
                    try:
                        if state["node"] is None:
                            await self.zk.ensure_path(zk_path)
                            state["node"] = await self.zk.create(
                                f"{zk_path}/member_", data,
                                ephemeral=True, sequential=True)
                            log.info("announced %s at %s:%d",
                                     state["node"], host, port)
                        gone = asyncio.Event()
                        stat = await self.zk.exists(
                            state["node"], watch=lambda ev: gone.set())
                        if stat is None:
                            state["node"] = None
                            continue
                        attempt = 0
                        await gone.wait()
                        # re-check on the next iteration (exists) —
                        # a data-change event is not a disappearance
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:  # noqa: BLE001 — keep trying
                        log.debug("zk announce %s: %r", zk_path, e)
                        attempt = await zk_backoff(attempt)
            finally:
                # withdraw: delete whatever we know we created. If a
                # create was in flight when cancelled, the node is
                # ephemeral and dies with the session.
                node = state["node"]
                if node is not None:
                    try:
                        await self.zk.delete(node)
                    except ZkError as e:
                        if e.code != ZK_NONODE:
                            log.debug("zk withdraw %s: %r", node, e)
                    except Exception:  # noqa: BLE001
                        pass

        task = asyncio.get_event_loop().create_task(maintain())

        def withdraw() -> None:
            task.cancel()

        return Closable(withdraw)


@register("announcer", "io.l5d.serversets")
@dataclass
class ZkAnnouncerConfig:
    """Announce server ports as ZooKeeper serversets under
    ``pathPrefix`` (finagle-compatible member JSON), so serverset-aware
    namers (io.l5d.serversets) resolve this router's listeners."""

    zkAddrs: list = None  # type: ignore[assignment]
    hosts: str = ""
    pathPrefix: str = "/discovery"
    prefix: str = "/io.l5d.serversets"
    sessionTimeoutMs: int = 10000

    def mk(self) -> Announcer:
        from linkerd_tpu.namer.zk import parse_zk_addrs

        connect = parse_zk_addrs(self.zkAddrs or [], self.hosts)
        return ZkAnnouncer(connect, Path.read(self.pathPrefix),
                           Path.read(self.prefix), self.sessionTimeoutMs)


def match_announcer(announcers: List[Tuple[Path, Announcer]],
                    announce_path: Path) -> Tuple[Announcer, Path]:
    """``/#/io.l5d.fs/web`` -> (announcer, /web)
    (ref: Main.announce prefix matching)."""
    if len(announce_path) == 0 or announce_path[0] != "#":
        raise ConfigError(
            f"announce path must start with /#/, got {announce_path.show}")
    rest = announce_path.drop(1)
    for prefix, ann in announcers:
        if rest.starts_with(prefix):
            return ann, rest.drop(len(prefix))
    raise ConfigError(f"no announcer for {announce_path.show}")
