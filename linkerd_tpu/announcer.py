"""Announcers: register server addresses into service discovery.

Ref: linkerd/core/.../Announcer.scala:41 (SPI; ``servers[].announce``
paths matched by announcer prefix, driven from Main.announce,
linkerd/main/.../Main.scala:97-130) and linkerd/announcer/serversets
ZkAnnouncer.scala:19. The fs announcer is the file-based counterpart of
the fs namer — a linkerd announcing into a directory that other linkerds
discover from (the single-node-stack analogue of serversets).
"""

from __future__ import annotations

import abc
import os
from dataclasses import dataclass
from typing import List, Tuple

from linkerd_tpu.config import ConfigError, register
from linkerd_tpu.core import Path
from linkerd_tpu.core.var import Closable


class Announcer(abc.ABC):
    prefix: Path

    @abc.abstractmethod
    def announce(self, host: str, port: int, name: Path) -> Closable:
        """Register host:port under ``name`` (the path AFTER the
        announcer prefix); the Closable withdraws it."""


class FsAnnouncer(Announcer):
    """One file per announced name; one ``host port`` line per announcer
    (kind ``io.l5d.fs``)."""

    def __init__(self, root_dir: str, prefix: Path):
        self.root = root_dir
        self.prefix = prefix
        os.makedirs(root_dir, exist_ok=True)

    def _file(self, name: Path) -> str:
        if len(name) == 0:
            raise ValueError("empty announce name")
        return os.path.join(self.root, "-".join(name))

    def _rewrite(self, path: str, drop: str, add: str = "") -> None:
        # Multiple linkerds announce into one shared directory, so the
        # read-modify-write must be serialized across PROCESSES: flock on
        # a sidecar lock file (the serversets analogue of ZK's atomicity).
        import fcntl
        with open(path + ".lock", "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            try:
                lines: List[str] = []
                if os.path.exists(path):
                    with open(path) as f:
                        lines = [ln for ln in f.read().splitlines()
                                 if ln.strip() and ln.strip() != drop]
                if add:
                    lines.append(add)
                if lines:
                    tmp = path + ".tmp"
                    with open(tmp, "w") as f:
                        f.write("\n".join(lines) + "\n")
                    os.replace(tmp, path)
                elif os.path.exists(path):
                    os.unlink(path)
            finally:
                fcntl.flock(lock, fcntl.LOCK_UN)

    def announce(self, host: str, port: int, name: Path) -> Closable:
        path = self._file(name)
        entry = f"{host} {port}"
        self._rewrite(path, drop=entry, add=entry)
        return Closable(lambda: self._rewrite(path, drop=entry))


@register("announcer", "io.l5d.fs")
@dataclass
class FsAnnouncerConfig:
    rootDir: str = ""
    prefix: str = "/io.l5d.fs"

    def mk(self) -> Announcer:
        if not self.rootDir:
            raise ConfigError("io.l5d.fs announcer needs rootDir")
        return FsAnnouncer(self.rootDir, Path.read(self.prefix))


def match_announcer(announcers: List[Tuple[Path, Announcer]],
                    announce_path: Path) -> Tuple[Announcer, Path]:
    """``/#/io.l5d.fs/web`` -> (announcer, /web)
    (ref: Main.announce prefix matching)."""
    if len(announce_path) == 0 or announce_path[0] != "#":
        raise ConfigError(
            f"announce path must start with /#/, got {announce_path.show}")
    rest = announce_path.drop(1)
    for prefix, ann in announcers:
        if rest.starts_with(prefix):
            return ann, rest.drop(len(prefix))
    raise ConfigError(f"no announcer for {announce_path.show}")
