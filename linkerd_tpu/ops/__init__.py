"""Pallas TPU kernels for the scoring hot path."""

from linkerd_tpu.ops.scoring import fused_anomaly_scores, fused_available

__all__ = ["fused_anomaly_scores", "fused_available"]
