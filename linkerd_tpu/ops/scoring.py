"""Fused anomaly-scoring Pallas kernel.

One kernel computes the whole autoencoder+classifier forward and the blended
score for a tile of the micro-batch: weights stay resident in VMEM across the
batch grid, activations never round-trip to HBM between layers, and the only
HBM traffic is the feature tile in and the score vector out. At micro-batch
scale (hundreds to a few thousand rows of 32 features) the model is far too
small to be MXU-bound — HBM traffic and kernel-launch overhead dominate — so
the fusion is the win (see /opt/skills/guides/pallas_guide.md).

Falls back transparently to the plain XLA path (`models.anomaly.anomaly_scores`)
when Mosaic can't compile (e.g. CPU tests run with ``interpret=True``).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from linkerd_tpu.models.anomaly import (
    AnomalyModelConfig, Params, anomaly_scores, normalize_features,
)


def _flatten_layers(params: Params):
    """Flatten the param pytree into an ordered list of (w, b) pairs:
    encoder, decoder, then classifier."""
    out = []
    for group in ("enc", "dec", "cls"):
        for layer in params[group]:
            out.append((layer["w"], layer["b"]))
    return out


def _score_kernel(x_ref, *refs, n_enc: int, n_dec: int, n_cls: int,
                  recon_weight: float, compute_dtype: Any):
    """Pallas kernel body: refs = [w0, b0, w1, b1, ..., out_ref]."""
    out_ref = refs[-1]
    wb = refs[:-1]
    x32 = x_ref[...].astype(jnp.float32)
    x = x32.astype(compute_dtype)

    def run(h, lo, n, final_act):
        for i in range(n):
            w = wb[2 * (lo + i)][...].astype(compute_dtype)
            b = wb[2 * (lo + i) + 1][...].astype(compute_dtype)
            h = jnp.dot(h, w, preferred_element_type=jnp.float32).astype(
                compute_dtype) + b
            if final_act or i < n - 1:
                h = jnp.maximum(h, 0.0)
        return h

    z = run(x, 0, n_enc, final_act=True)
    recon = run(z, n_enc, n_dec, final_act=False)
    logits = run(z, n_enc + n_dec, n_cls, final_act=False)

    # reconstruction error against the ORIGINAL f32 input, matching
    # models.anomaly.anomaly_scores (not the bf16-rounded copy)
    err = jnp.mean(jnp.square(recon.astype(jnp.float32) - x32),
                   axis=-1, keepdims=True)
    recon_score = jnp.tanh(err)
    cls_score = jax.nn.sigmoid(logits.astype(jnp.float32))
    # out is [block_rows, 1]: keep 2-D so Mosaic uses the standard layout
    out_ref[...] = recon_weight * recon_score + (1.0 - recon_weight) * cls_score


def fused_anomaly_scores(
    params: Params,
    x: jax.Array,
    cfg: AnomalyModelConfig = AnomalyModelConfig(),
    block_rows: int = 256,
    interpret: bool = False,
) -> jax.Array:
    """Score ``x`` [B, D] -> [B] with the fused kernel.

    Ragged batches are zero-padded up to a multiple of ``block_rows`` and
    the padding rows sliced off the result. Weights are broadcast to every
    grid step (index_map -> block 0) so they load into VMEM once and stay
    resident.
    """
    orig_b, d = x.shape
    b = ((orig_b + block_rows - 1) // block_rows) * block_rows
    if b != orig_b:
        x = jnp.pad(x, ((0, b - orig_b), (0, 0)))
    layers = _flatten_layers(params)
    n_enc = len(params["enc"])
    n_dec = len(params["dec"])
    n_cls = len(params["cls"])

    flat_args = []
    in_specs = [
        pl.BlockSpec((block_rows, d), lambda i: (i, 0)),  # x tile
    ]
    for w, bia in layers:
        flat_args.append(w)
        in_specs.append(pl.BlockSpec(w.shape, lambda i: (0, 0)))
        flat_args.append(bia)
        in_specs.append(pl.BlockSpec(bia.shape, lambda i: (0,)))

    kernel = functools.partial(
        _score_kernel,
        n_enc=n_enc, n_dec=n_dec, n_cls=n_cls,
        recon_weight=cfg.recon_weight, compute_dtype=cfg.compute_dtype,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b // block_rows,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_rows, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((b, 1), jnp.float32),
        interpret=interpret,
    )(x, *flat_args)
    return out[:orig_b, 0]


@functools.lru_cache(maxsize=16)
def fused_available(cfg: AnomalyModelConfig = AnomalyModelConfig()) -> bool:
    """Probe whether the fused kernel compiles+runs for THIS config on the
    current backend (cached per config)."""
    try:
        from linkerd_tpu.models.anomaly import init_params
        params = init_params(jax.random.key(0), cfg)
        x = jnp.zeros((256, cfg.in_dim), jnp.float32)
        got = jax.jit(lambda p, v: fused_anomaly_scores(p, v, cfg))(params, x)
        ref = anomaly_scores(params, x, cfg)
        return bool(jnp.allclose(got, ref, atol=2e-2))
    except Exception:  # noqa: BLE001 — any Mosaic/lowering error means "no"
        return False


def best_scorer(cfg: AnomalyModelConfig = AnomalyModelConfig(),
                donate: bool = False):
    """Return a jitted scorer: the fused kernel when available, else XLA.

    The returned fn is ``(params, x, mu=None, var=None) -> scores``:
    with mu/var, ``normalize_features`` runs on device ahead of the
    kernel (XLA fuses the z-score into the input tile load), so the
    host ships raw f32 features and never touches the batch.

    With ``donate``, the input batch (argument 1) is donated: the
    line-rate dispatch path hands the step a device-resident staging
    buffer it will never re-read, and XLA reuses that buffer for the
    step's temporaries/outputs instead of allocating fresh device
    memory per micro-batch. Donated buffers raise on re-read.
    """

    def _norm(v, mu, var):
        return v if mu is None else normalize_features(v, mu, var)

    if fused_available(cfg):
        fn = lambda p, v, mu=None, var=None: \
            fused_anomaly_scores(p, _norm(v, mu, var), cfg)  # noqa: E731
    else:
        fn = lambda p, v, mu=None, var=None: \
            anomaly_scores(p, _norm(v, mu, var), cfg)  # noqa: E731
    if donate:
        return jax.jit(fn, donate_argnums=(1,))
    return jax.jit(fn)
