"""Per-route drift detection + per-route replay for the distiller.

The global ``lifecycle.drift.DriftMonitor`` answers "has the MESH
drifted from the serving checkpoint"; the distiller needs the per-ROUTE
question: which specific route's score distribution has walked away
from where it was when its serving head (base or specialist) was
anchored. ``RouteDriftMonitor`` keeps one EWMA score mean/std pair per
route, anchors a reference once the route has warmed, and reports
routes whose live mean sits more than the configured number of
reference-sigmas away — the retrain-on-shift trigger.

``RouteReplayWindow`` is the matching training/holdout source: recent
rows PER ROUTE (features, labels, mask), bounded per route and in route
count, so a retrain always fine-tunes on the traffic that actually
shifted. Both are host-side numpy on already-drained batches — nothing
here may touch the device (the batch publish path runs next to the
serving loop).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import numpy as np

_STD_FLOOR = 0.05  # sigma denominator floor: scores live in [0, 1]


class _RouteStats:
    __slots__ = ("ref_mean", "ref_std", "live_mean", "live_std", "rows",
                 "last_anchor")

    def __init__(self) -> None:
        self.ref_mean: Optional[float] = None
        self.ref_std: Optional[float] = None
        self.live_mean: Optional[float] = None
        self.live_std: Optional[float] = None
        self.rows = 0
        self.last_anchor = 0.0  # monotonic


class RouteDriftMonitor:
    """Per-route score-shift gauges and retrain triggers.

    ``observe`` folds one drained batch's (dst, score) rows in;
    ``score_shift(dst)`` is |live - ref| in reference-sigma units;
    ``triggered`` lists routes past ``threshold``. ``re_anchor`` resets
    a route's reference to its live stats (called when its head — base
    or specialist — changes, exactly like the global DriftMonitor
    re-anchors on promotion: scores right after a publish are
    "normal"). Route cardinality is bounded: past ``max_routes`` new
    routes are ignored rather than growing without bound.
    """

    def __init__(self, threshold: float = 1.0, min_rows: int = 64,
                 momentum: float = 0.1, max_routes: int = 1024):
        if threshold <= 0:
            raise ValueError("threshold must be > 0")
        if min_rows < 1:
            raise ValueError("min_rows must be >= 1")
        if not 0.0 < momentum <= 1.0:
            raise ValueError("momentum must be in (0, 1]")
        self.threshold = threshold
        self.min_rows = min_rows
        self.momentum = momentum
        self.max_routes = max_routes
        self._routes: Dict[str, _RouteStats] = {}

    def observe(self, dsts: List[str], scores: np.ndarray) -> None:
        """Fold one batch of per-row (dst, score) pairs into the live
        EWMA stats. O(batch) host arithmetic."""
        if len(dsts) == 0:
            return
        groups: Dict[str, List[float]] = {}
        for dst, s in zip(dsts, scores):
            groups.setdefault(dst, []).append(float(s))
        m = self.momentum
        for dst, vals in groups.items():
            st = self._routes.get(dst)
            if st is None:
                if len(self._routes) >= self.max_routes:
                    continue  # bounded cardinality
                st = self._routes[dst] = _RouteStats()
            mean = sum(vals) / len(vals)
            var = sum((v - mean) ** 2 for v in vals) / len(vals)
            std = var ** 0.5
            if st.live_mean is None:
                st.live_mean, st.live_std = mean, std
            else:
                st.live_mean = (1 - m) * st.live_mean + m * mean
                st.live_std = (1 - m) * st.live_std + m * std
            st.rows += len(vals)
            if st.ref_mean is None and st.rows >= self.min_rows:
                # first warm anchor: the route's opening distribution
                # is its own "normal"
                self._anchor(st)

    def _anchor(self, st: _RouteStats) -> None:
        st.ref_mean = st.live_mean
        st.ref_std = st.live_std
        st.last_anchor = time.monotonic()

    def re_anchor(self, dst: str) -> None:
        st = self._routes.get(dst)
        if st is not None and st.live_mean is not None:
            self._anchor(st)

    def re_anchor_all(self) -> None:
        """Base-model publish: every route's serving model changed, so
        every reference is stale."""
        for st in self._routes.values():
            if st.live_mean is not None:
                self._anchor(st)

    def score_shift(self, dst: str) -> float:
        st = self._routes.get(dst)
        if st is None or st.ref_mean is None or st.live_mean is None:
            return 0.0
        denom = max(st.ref_std or 0.0, _STD_FLOOR)
        return abs(st.live_mean - st.ref_mean) / denom

    def rows_of(self, dst: str) -> int:
        st = self._routes.get(dst)
        return 0 if st is None else st.rows

    def triggered(self) -> List[str]:
        """Routes whose live score distribution shifted past the
        threshold — the distiller's work queue, worst shift first."""
        out = [(self.score_shift(dst), dst) for dst in self._routes]
        return [dst for shift, dst in sorted(out, reverse=True)
                if shift > self.threshold]

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for dst, st in self._routes.items():
            out[dst] = {
                "shift": round(self.score_shift(dst), 4),
                "live_mean": st.live_mean,
                "ref_mean": st.ref_mean,
                "rows": st.rows,
            }
        return out


class RouteReplayWindow:
    """Recent rows per route: the retrain + holdout source.

    Rows arrive as whole drained batches (``add``); per route the
    newest ``per_route_rows`` rows are kept. Route cardinality is
    bounded by evicting the route with the OLDEST most-recent arrival
    (a route that stopped receiving traffic cannot retrain anyway).
    """

    def __init__(self, per_route_rows: int = 512, max_routes: int = 64):
        if per_route_rows < 8:
            raise ValueError("per_route_rows must be >= 8")
        if max_routes < 1:
            raise ValueError("max_routes must be >= 1")
        self.per_route_rows = per_route_rows
        self.max_routes = max_routes
        # dst -> (x rows, labels, mask) as growing-then-trimmed arrays
        self._rows: Dict[str, Tuple[np.ndarray, np.ndarray, np.ndarray]] = {}
        self._touched: Dict[str, int] = {}
        self._tick = 0

    def add(self, dsts: List[str], x: np.ndarray, labels: np.ndarray,
            mask: np.ndarray) -> None:
        if len(dsts) == 0:
            return
        self._tick += 1
        idx: Dict[str, List[int]] = {}
        for i, dst in enumerate(dsts):
            idx.setdefault(dst, []).append(i)
        for dst, rows in idx.items():
            if dst not in self._rows:
                if len(self._rows) >= self.max_routes:
                    victim = min(self._touched, key=self._touched.get)
                    del self._rows[victim]
                    del self._touched[victim]
                self._rows[dst] = (
                    np.zeros((0, x.shape[1]), np.float32),
                    np.zeros(0, np.float32), np.zeros(0, np.float32))
            xr, lr, mr = self._rows[dst]
            sel = np.array(rows, np.int64)
            xr = np.concatenate([xr, x[sel]])[-self.per_route_rows:]
            lr = np.concatenate([lr, labels[sel]])[-self.per_route_rows:]
            mr = np.concatenate([mr, mask[sel]])[-self.per_route_rows:]
            self._rows[dst] = (xr, lr, mr)
            self._touched[dst] = self._tick

    def rows(self, dst: str) -> int:
        got = self._rows.get(dst)
        return 0 if got is None else len(got[0])

    def sample(self, dst: str
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        got = self._rows.get(dst)
        if got is None or len(got[0]) == 0:
            raise ValueError(f"no replay rows for route {dst!r}")
        return got
