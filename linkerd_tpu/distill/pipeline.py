"""The drift-triggered distillation pipeline.

One ``run_once`` pass services the worst-shifted route:

    trigger (RouteDriftMonitor past driftThreshold, cooldown elapsed,
             enough replay rows, bank not full)
      -> snapshot the online-trained global model (the teacher)
      -> ``distill_head``: fine-tune a copy on the route's replay rows
         with per-route normalization stats (host->device->host inside
         one worker thread; the event loop never blocks on the device)
      -> shadow-gate: candidate vs the route's SERVING model (its
         existing specialist head, or the base) on held-out route rows,
         through the PromotionGate — a poisoned candidate regresses on
         rows it never trained on and is rejected
      -> on accept: one ``L5DWTD01`` delta patch (generation-fenced)
         publishes the head to every engine, with the full ``L5DWTS02``
         bank as the per-sink fallback; the bank registry, drift
         reference, and CheckpointStore specialist lineage advance only
         after the publish landed.

``rollback_route`` is the inverse: one REMOVE delta drops a single
route's head (the route falls back to the base model) while every
other head keeps serving.

Concurrency: one retrain runs at a time (``_busy`` reentrancy guard,
the same pattern as the telemeter's native-refresh task); bank
mutations + publishes sit under ``lock``, which the telemeter also
holds across its own full-bank exports (base promote/refresh), so a
promote landing mid-retrain cannot interleave generations.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from linkerd_tpu.distill.bank import SpecialistBank
from linkerd_tpu.distill.monitor import RouteDriftMonitor, RouteReplayWindow
from linkerd_tpu.lifecycle.export import (
    blob_meta, export_delta_blob, route_hash,
)
from linkerd_tpu.lifecycle.promote import (
    GatePolicy, PromotionGate, evaluate_snapshot,
)

log = logging.getLogger(__name__)

# jitted fine-tune steps, one per (model config, learning rate): the
# pipeline retrains many routes against the same geometry, so compile
# once and reuse
_STEP_CACHE: Dict[Tuple[Any, float], Any] = {}


def _fine_tune_step(cfg, lr: float):
    key = (cfg, float(lr))
    got = _STEP_CACHE.get(key)
    if got is not None:
        return got
    import jax
    import optax

    from linkerd_tpu.models.anomaly import loss_fn, normalize_features

    opt = optax.adam(lr)

    @jax.jit
    def step(params, opt_state, x, labels, mask, mu, var):
        xn = normalize_features(x, mu, var)
        loss, grads = jax.value_and_grad(loss_fn)(
            params, xn, labels, mask, cfg)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    _STEP_CACHE[key] = (opt, step)
    return opt, step


def distill_head(base_snap, x: np.ndarray, labels: np.ndarray,
                 mask: np.ndarray, steps: int, lr: float):
    """Fine-tune a specialist head for one route from the global model.

    The teacher is the starting point: the candidate begins at the
    online-trained global parameters and specializes on the route's own
    rows. Normalization specializes too — the head's mu/var blend the
    base stats with the route's observed distribution, which is where
    most of the per-route win lives (the base model normalizes every
    route with mesh-wide statistics).

    Blocking (device round-trips); call off the event loop. Returns a
    ``ModelSnapshot`` with empty optimizer leaves (heads are serving
    artifacts, not training lineage — the GLOBAL model keeps training).
    """
    import jax

    from linkerd_tpu.lifecycle.store import ModelSnapshot

    x = np.ascontiguousarray(x, np.float32)
    mu_r = x.mean(axis=0)
    var_r = x.var(axis=0) + 1e-6
    mu = (0.5 * np.asarray(base_snap.mu, np.float32)
          + 0.5 * mu_r).astype(np.float32)
    var = (0.5 * np.asarray(base_snap.var, np.float32)
           + 0.5 * var_r).astype(np.float32)
    opt, step = _fine_tune_step(base_snap.cfg, lr)
    params = base_snap.params
    opt_state = opt.init(params)
    labels = np.ascontiguousarray(labels, np.float32)
    mask = np.ascontiguousarray(mask, np.float32)
    for _ in range(max(1, int(steps))):
        params, opt_state, _loss = step(params, opt_state, x, labels,
                                        mask, mu, var)
    return ModelSnapshot(
        params=jax.device_get(params), opt_leaves=[],
        mu=mu.copy(), var=var.copy(), norm_initialized=True,
        step=int(base_snap.step), cfg=base_snap.cfg)


class DistillationPipeline:
    """See module docstring. ``node`` is the telemeter's
    ``anomaly/distill`` MetricsTree scope (None for registry-less unit
    tests); ``store`` the CheckpointStore carrying specialist lineage
    (None without a lifecycle block)."""

    def __init__(self, cfg, node=None, gate: Optional[PromotionGate] = None,
                 store=None, default_quant: str = "f32"):
        if cfg.maxHeads < 1:
            raise ValueError("distill.maxHeads must be >= 1")
        if cfg.driftThreshold <= 0:
            raise ValueError("distill.driftThreshold must be > 0")
        if cfg.minRouteRows < 8:
            raise ValueError("distill.minRouteRows must be >= 8")
        if cfg.retrainSteps < 1:
            raise ValueError("distill.retrainSteps must be >= 1")
        if cfg.learningRate <= 0:
            raise ValueError("distill.learningRate must be > 0")
        if cfg.cooldownS < 0:
            raise ValueError("distill.cooldownS must be >= 0")
        self.cfg = cfg
        self.quant = cfg.quant or default_quant
        self.bank = SpecialistBank(cfg.maxHeads)
        self.monitor = RouteDriftMonitor(
            threshold=cfg.driftThreshold, min_rows=cfg.minRouteRows)
        self.replay = RouteReplayWindow(
            per_route_rows=cfg.perRouteReplayRows)
        self.gate = gate or PromotionGate(GatePolicy(
            aucTolerance=cfg.aucTolerance,
            lossTolerance=cfg.lossTolerance,
            minLabeled=cfg.minLabeled))
        self.store = store
        # publisher: fn(full_blob, delta_blob|None) -> bool, installed
        # by the telemeter (publish_bank_update); None = local-only
        # bank (no engines registered — /model.json still shows heads)
        self._publisher: Optional[Callable[[bytes, Optional[bytes]],
                                           bool]] = None
        self.lock = asyncio.Lock()
        self._busy = False
        self._cooldown: Dict[str, float] = {}  # dst -> monotonic
        self.last_outcome: Optional[Dict[str, Any]] = None
        self.last_rollback: Optional[Dict[str, Any]] = None
        if node is not None:
            self._retrains = node.counter("retrains")
            self._promotions = node.counter("promotions")
            self._rejections = node.counter("rejections")
            self._rollbacks = node.counter("rollbacks")
            self._delta_pub = node.counter("delta_publishes")
            self._full_pub = node.counter("full_publishes")
            node.gauge("heads", fn=lambda: float(len(self.bank)))
            node.gauge("generation",
                       fn=lambda: float(self.bank.generation))
            node.gauge("pending",
                       fn=lambda: float(len(self.monitor.triggered())))
        else:
            self._retrains = self._promotions = self._rejections = None
            self._rollbacks = self._delta_pub = self._full_pub = None

    def _incr(self, counter) -> None:
        if counter is not None:
            counter.incr()

    # -- wiring ------------------------------------------------------------
    def set_publisher(self, fn: Callable[[bytes, Optional[bytes]],
                                         bool]) -> None:
        self._publisher = fn

    # -- batch feed (host numpy only; runs on the drain path) -------------
    def observe_batch(self, dsts: List[str], x: np.ndarray,
                      scores: np.ndarray, labels: np.ndarray,
                      mask: np.ndarray) -> None:
        self.monitor.observe(dsts, scores)
        self.replay.add(dsts, x, labels, mask)

    # -- trigger scan ------------------------------------------------------
    def pending_route(self) -> Optional[str]:
        """Worst-shifted route that is actually retrainable now."""
        now = time.monotonic()
        for dst in self.monitor.triggered():
            if now - self._cooldown.get(dst, -1e9) < self.cfg.cooldownS:
                continue
            if self.replay.rows(dst) < self.cfg.minRouteRows:
                continue
            if self.bank.full and self.bank.head_for(dst) is None:
                continue  # no slot for a NEW head; existing may retrain
            return dst
        return None

    @property
    def busy(self) -> bool:
        return self._busy

    # -- the retrain cycle -------------------------------------------------
    async def run_once(self, scorer,
                       base_version: Optional[int] = None
                       ) -> Optional[Dict[str, Any]]:
        """Retrain + gate + publish for ONE pending route (the worst
        shift). Returns the outcome dict, or None when nothing was
        pending or a retrain is already in flight."""
        if self._busy:
            return None
        dst = self.pending_route()
        if dst is None:
            return None
        self._busy = True
        try:
            return await self._retrain_route(dst, scorer, base_version)
        finally:
            self._busy = False

    async def _retrain_route(self, dst: str, scorer,
                             base_version: Optional[int]
                             ) -> Dict[str, Any]:
        self._incr(self._retrains)
        self._cooldown[dst] = time.monotonic()
        x, labels, mask = self.replay.sample(dst)
        # deterministic holdout: every 4th row is shadow-eval only —
        # the candidate never trains on the rows that judge it
        hold = np.arange(len(x)) % 4 == 0
        x_tr, l_tr, m_tr = x[~hold], labels[~hold], mask[~hold]
        x_ho, l_ho, m_ho = x[hold], labels[hold], mask[hold]
        base_snap = await asyncio.to_thread(scorer.snapshot)
        if base_version is None:
            base_version = int(getattr(base_snap, "step", 0) or 0)
        candidate = await asyncio.to_thread(
            distill_head, base_snap, x_tr, l_tr, m_tr,
            self.cfg.retrainSteps, self.cfg.learningRate)
        cand_report = await asyncio.to_thread(
            evaluate_snapshot, candidate, x_ho, l_ho, m_ho)
        serving_head = self.bank.head_for(dst)
        serving_snap = (serving_head.snapshot if serving_head is not None
                        else base_snap)
        serv_report = await asyncio.to_thread(
            evaluate_snapshot, serving_snap, x_ho, l_ho, m_ho)
        decision = self.gate.decide(cand_report, serv_report)
        if not decision.accepted:
            self._incr(self._rejections)
            outcome = {"action": "rejected", "route": dst,
                       "decision": decision.as_dict()}
            self.last_outcome = outcome
            log.info("distill: candidate head for %s rejected: %s",
                     dst, decision.reason)
            return outcome
        async with self.lock:
            gen = self.bank.generation
            head_version = self.bank.next_head_version()
            rh = route_hash(dst)
            delta = None
            if self.cfg.deltaPublish:
                delta = await asyncio.to_thread(
                    export_delta_blob, gen, gen + 1,
                    {rh: (head_version, candidate)}, quant=self.quant)
            info = self.bank.upsert(dst, candidate, head_version,
                                    int(base_version), gen + 1)
            self.bank.generation = gen + 1
            # the full-bank fallback ships the freshly snapshotted base
            # (it IS the online-trained model the engines should serve),
            # so the stamped base version moves with it — a sink that
            # falls back to the full blob must report the lineage of
            # the bits it actually serves, not the pre-retrain stamp
            self._base_snap = base_snap
            self.bank.base_version = int(base_version)
            # exports are host-numpy over base + every head: off-loop
            # (the lock is held across the await, so generations stay
            # serialized against concurrent publishes)
            full = await asyncio.to_thread(
                self.bank.export_full, base_snap,
                self.bank.base_version, gen + 1, self.quant)
            used_delta = self._publish(full, delta)
            self._incr(self._promotions)
            self._incr(self._delta_pub if used_delta else self._full_pub)
            self.monitor.re_anchor(dst)
            self._record_lineage(rh, info, delta)
            outcome = {
                "action": "promoted", "route": dst,
                "route_hash": rh, "head_version": head_version,
                "generation": self.bank.generation,
                "delta_bytes": len(delta) if delta is not None else None,
                "full_bytes": len(full),
                "delta_published": used_delta,
                "decision": decision.as_dict(),
            }
            self.last_outcome = outcome
        log.info("distill: promoted specialist head for %s "
                 "(generation %d, %s publish)", dst,
                 self.bank.generation,
                 "delta" if used_delta else "full")
        return outcome

    def _publish(self, full: Optional[bytes],
                 delta: Optional[bytes]) -> bool:
        """Ship the update through the telemeter; returns True when the
        delta path carried it (False = full-blob path or no engines)."""
        if self._publisher is None:
            return False
        return bool(self._publisher(full, delta))

    def _record_lineage(self, rh: int, info, delta: Optional[bytes]
                        ) -> None:
        if self.store is None:
            return
        meta = info.meta()
        if delta is not None:
            dm = blob_meta(delta)
            meta["delta_crc"] = dm["crc"] if dm else None
            meta["delta_bytes"] = len(delta)
        try:
            self.store.record_specialist(rh, meta)
        except Exception:  # noqa: BLE001 — lineage annotation must not
            # undo a publish that already landed
            log.exception("specialist lineage record failed for %r",
                          info.dst)

    # -- single-route rollback --------------------------------------------
    async def rollback_route(self, dst: str) -> bool:
        """Drop ONE route's specialist head (admin- or gate-triggered):
        one REMOVE delta, generation-fenced, every other head keeps
        serving; the route falls back to the base model."""
        async with self.lock:
            info = self.bank.head_for(dst)
            if info is None:
                return False
            gen = self.bank.generation
            delta = export_delta_blob(gen, gen + 1,
                                      removes=[info.route_hash],
                                      quant=self.quant)
            self.bank.remove(dst)
            self.bank.generation = gen + 1
            full = None
            if self._base_snap is not None:
                full = await asyncio.to_thread(
                    self.bank.export_full, self._base_snap,
                    self.bank.base_version or 0, gen + 1, self.quant)
            self._publish(full, delta)
            self._incr(self._rollbacks)
            self.monitor.re_anchor(dst)
            if self.store is not None:
                try:
                    self.store.record_specialist(info.route_hash, None)
                except Exception:  # noqa: BLE001 — see _record_lineage
                    log.exception(
                        "specialist lineage removal failed for %r", dst)
            self.last_rollback = {"route": dst,
                                  "route_hash": info.route_hash,
                                  "generation": self.bank.generation,
                                  "at": time.time()}
        log.info("distill: rolled back specialist head for %s "
                 "(generation %d)", dst, self.bank.generation)
        return True

    # -- base-model publishes ----------------------------------------------
    _base_snap = None  # last exported base ModelSnapshot (host numpy)

    def export_full(self, base_snap, base_version: int,
                    quant: Optional[str] = None) -> bytes:
        """Full-bank export for the telemeter's refresh path (startup,
        lifecycle promote/rollback, nativeRefreshS): the base model
        changed, so the generation bumps, every head rides along, and
        every route's drift reference re-anchors. The caller holds
        ``self.lock`` (sync body: no await point between the generation
        bump and the blob that carries it)."""
        self.bank.generation += 1
        self.bank.base_version = int(base_version)
        self._base_snap = base_snap
        blob = self.bank.export_full(base_snap, int(base_version),
                                     self.bank.generation,
                                     quant or self.quant)
        self.monitor.re_anchor_all()
        return blob

    # -- observability -----------------------------------------------------
    def state(self) -> Dict[str, Any]:
        return {
            "quant": self.quant,
            "delta_publish": bool(self.cfg.deltaPublish),
            "drift_threshold": self.cfg.driftThreshold,
            "bank": self.bank.state(),
            "routes": self.monitor.snapshot(),
            "pending": self.monitor.triggered()[:8],
            "last_outcome": self.last_outcome,
            "last_rollback": self.last_rollback,
        }
