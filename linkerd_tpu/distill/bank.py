"""The specialist bank: per-route heads, generations, and exports.

``SpecialistBank`` is the Python-side source of truth for what the
engines' weight slab serves: the base model identity, a monotonically
increasing generation (the delta fence), and one ``HeadInfo`` per
specialist route. The pipeline mutates it only after a publish landed,
so the bank state and the slab state move together; ``/model.json``
renders ``state()`` so an operator can see exactly which routes run a
specialist, distilled from which base checkpoint, at which generation.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from linkerd_tpu.lifecycle.export import export_bank_blob, route_hash


@dataclass
class HeadInfo:
    """One promoted specialist head."""

    dst: str                  # the route's dst path (the hash preimage)
    route_hash: int
    version: int              # head version (stamps the model section)
    snapshot: Any             # ModelSnapshot (host numpy)
    base_version: int         # base checkpoint the head distilled from
    generation: int           # bank generation that first served it
    promoted_at: float = field(default_factory=time.time)
    retrains: int = 1         # times this route's head was (re)promoted

    def meta(self) -> Dict[str, Any]:
        return {
            "dst": self.dst,
            "route_hash": self.route_hash,
            "version": self.version,
            "base_version": self.base_version,
            "generation": self.generation,
            "promoted_at": self.promoted_at,
            "retrains": self.retrains,
        }


class SpecialistBank:
    """Head registry + generation counter (see module docstring)."""

    def __init__(self, max_heads: int = 32):
        if max_heads < 1:
            raise ValueError("max_heads must be >= 1")
        self.max_heads = max_heads
        self.generation = 0
        self.base_version: Optional[int] = None
        self.heads: Dict[int, HeadInfo] = {}  # route_hash -> HeadInfo
        self._next_head_version = 1

    def __len__(self) -> int:
        return len(self.heads)

    @property
    def full(self) -> bool:
        return len(self.heads) >= self.max_heads

    def head_for(self, dst: str) -> Optional[HeadInfo]:
        return self.heads.get(route_hash(dst))

    def next_head_version(self) -> int:
        v = self._next_head_version
        self._next_head_version += 1
        return v

    def upsert(self, dst: str, snapshot: Any, version: int,
               base_version: int, generation: int) -> HeadInfo:
        rh = route_hash(dst)
        prev = self.heads.get(rh)
        if prev is None and self.full:
            raise ValueError(
                f"bank is full ({self.max_heads} heads); cannot add "
                f"{dst!r}")
        info = HeadInfo(dst=dst, route_hash=rh, version=version,
                        snapshot=snapshot, base_version=base_version,
                        generation=generation,
                        retrains=(prev.retrains + 1) if prev else 1)
        self.heads[rh] = info
        return info

    def remove(self, dst: str) -> Optional[HeadInfo]:
        return self.heads.pop(route_hash(dst), None)

    def export_full(self, base_snap: Any, base_version: int,
                    generation: int, quant: str) -> bytes:
        """The full ``L5DWTS02`` blob for the CURRENT head set under
        ``generation`` (the caller owns when generations bump)."""
        return export_bank_blob(
            base_snap, base_version, generation,
            {rh: (h.version, h.snapshot) for rh, h in self.heads.items()},
            quant=quant)

    def state(self) -> Dict[str, Any]:
        """The /model.json per-route bank view."""
        return {
            "generation": self.generation,
            "base_version": self.base_version,
            "max_heads": self.max_heads,
            "heads": {str(h.route_hash): h.meta()
                      for h in self.heads.values()},
        }
