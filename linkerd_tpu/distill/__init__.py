"""Specialist model bank + continuous in-plane learning.

One global autoencoder/MLP scores every route today, averaging over
workloads instead of specializing per flow — the gap Taurus (per-packet
ML in the data plane) and INSIGHT (per-flow in-network intelligence)
identify as the end-state for in-network scoring. This package turns
the one-shot ``nativeRefreshS`` re-export loop into a drift-triggered
distillation pipeline producing a bank of small per-route specialist
heads:

    per-route score-shift (RouteDriftMonitor)
        -> retrain-on-shift from the route's replay rows
           (DistillationPipeline.distill_head: the online-trained
           global model is the teacher/starting point; the candidate
           fine-tunes on the route's own traffic with per-route
           normalization stats)
        -> shadow-gate through the existing PromotionGate on held-out
           route rows (a poisoned candidate evaluates worse than the
           serving model and is rejected, never published)
        -> publish a per-route DELTA patch (lifecycle/export
           ``L5DWTD01``) into the engines' double-buffered weight slab
           — generation-fenced, reader-recheck flip, multi-worker
           shared slab included — with a full ``L5DWTS02`` bank blob
           as the fallback for engines that cannot take the patch.

The native evaluator (``native/scorer.h``) selects a route's head by
the FNV-1a route hash pushed alongside the feature column, falling
back to the base model; rollback of a single route is one REMOVE delta
that leaves every other head serving. Head lineage (which base
checkpoint each head was distilled from, which delta CRC shipped it)
rides the CheckpointStore manifest (``record_specialist``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from linkerd_tpu.distill.bank import HeadInfo, SpecialistBank
from linkerd_tpu.distill.monitor import RouteDriftMonitor, RouteReplayWindow


@dataclass
class DistillConfig:
    """YAML ``distill:`` block of the io.l5d.jaxAnomaly telemeter.

    The drift trigger and the promotion gate interlock: a route
    retrains when its live score distribution shifts more than
    ``driftThreshold`` reference-sigmas from where it was anchored, and
    the candidate head is promoted only when it does not regress
    (loss/AUC tolerances, the same ``PromotionGate`` semantics the
    global lifecycle uses) on the route's held-out rows.
    """

    maxHeads: int = 32           # specialist heads the bank may carry
    driftThreshold: float = 1.0  # per-route score-shift trigger (sigmas)
    minRouteRows: int = 64       # replay rows before a route may retrain
    perRouteReplayRows: int = 512   # replay window per route, rows
    retrainSteps: int = 8        # fine-tune steps per candidate
    learningRate: float = 0.001
    cooldownS: float = 30.0      # per-route floor between retrains
    # candidate gate (PromotionGate semantics, scoped to one route)
    aucTolerance: float = 0.02
    lossTolerance: float = 0.10
    minLabeled: int = 8
    # bank blob encoding: f32 | int8 | int4; None inherits the
    # telemeter's nativeQuant
    quant: Optional[str] = None
    # publish per-route delta patches (full-bank publish is always the
    # fallback for a sink that rejects the patch); False always ships
    # the full bank
    deltaPublish: bool = True

    def mk(self, node, gate=None, store=None,
           quant: str = "f32") -> "DistillationPipeline":
        from linkerd_tpu.distill.pipeline import DistillationPipeline
        return DistillationPipeline(self, node, gate=gate, store=store,
                                    default_quant=quant)


__all__ = [
    "DistillConfig", "HeadInfo", "RouteDriftMonitor",
    "RouteReplayWindow", "SpecialistBank",
]
