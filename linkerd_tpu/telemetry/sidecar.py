"""gRPC scorer sidecar: the TPU process serving anomaly scoring.

Deployment shape per BASELINE.json: the mesh router micro-batches feature
vectors over gRPC to a separate JAX/TPU process (this sidecar), so router
restarts don't lose the model and one TPU serves many routers (the same
topology as namerd serving many linkerds, SURVEY.md §2.4).

Uses grpc generic handlers with a simple length-prefixed ndarray codec
(no protoc codegen needed; the wire format is versioned by the method
names). Methods (service ``io.l5d.anomaly.Scorer``):

- ``Score``: request  = u32 n | u32 d | f32[n*d] features
             response = f32[n] scores
- ``Fit``:   request  = u32 n | u32 d | f32[n*d] x | f32[n] labels | f32[n] mask
             response = f32[1] loss
- ``Snapshot``: request = (empty)
             response = serialized ModelSnapshot (lifecycle/store format)
- ``Restore``:  request = serialized ModelSnapshot
             response = u64 restored step counter

Snapshot/Restore are the fleet hot-swap path: the lifecycle manager on
one router promotes a model, and every router pulls it into its sidecar
(or the shared sidecar restores once) without a restart.
"""

from __future__ import annotations

import asyncio
import logging
import struct
from typing import Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)

SERVICE = "io.l5d.anomaly.Scorer"


def bucket_rows(n: int) -> int:
    """Power-of-two batch bucket. The single source of truth shared by the
    scorer's padding (InProcessScorer._pad_rows) and the client's
    warm-deadline keying — they must agree on what constitutes one XLA
    compilation."""
    return 1 << max(0, n - 1).bit_length()


def encode_matrix(x: np.ndarray) -> bytes:
    # ascontiguousarray normalizes sliced/strided views (a telemeter may
    # hand us arr[::2]) and zero-row windows alike; tobytes() on a 0xD
    # array is a valid empty payload.
    x = np.ascontiguousarray(x, dtype=np.float32)
    if x.ndim != 2:
        raise ValueError(f"encode_matrix wants [n, d], got shape {x.shape}")
    n, d = x.shape
    return struct.pack("<II", n, d) + x.tobytes()


def decode_matrix(data: bytes) -> np.ndarray:
    if len(data) < 8:
        raise ValueError(
            f"truncated matrix payload: {len(data)} bytes, need >= 8")
    n, d = struct.unpack_from("<II", data)
    need = 8 + 4 * n * d
    if len(data) != need:
        # a Score payload is exactly one matrix; short payloads would
        # make np.frombuffer raise a generic message, and trailing bytes
        # would silently mask a producer-side framing bug
        raise ValueError(
            f"bad matrix payload: {len(data)} bytes, "
            f"need exactly {need} for {n}x{d} f32")
    arr = np.frombuffer(data, dtype=np.float32, offset=8, count=n * d)
    return arr.reshape(n, d)


def encode_fit(x: np.ndarray, labels: np.ndarray, mask: np.ndarray) -> bytes:
    labels = np.ascontiguousarray(labels, np.float32)
    mask = np.ascontiguousarray(mask, np.float32)
    n = x.shape[0]
    if labels.shape != (n,) or mask.shape != (n,):
        raise ValueError(
            f"encode_fit row mismatch: x has {n} rows, labels "
            f"{labels.shape}, mask {mask.shape}")
    return encode_matrix(x) + labels.tobytes() + mask.tobytes()


def decode_fit(data: bytes) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    if len(data) < 8:
        raise ValueError(
            f"truncated fit payload: {len(data)} bytes, need >= 8")
    n, d = struct.unpack_from("<II", data)
    need = 8 + 4 * (n * d + 2 * n)
    if len(data) != need:
        # a silent np.frombuffer misread here would train on shifted
        # labels/mask — reject short AND long payloads outright
        raise ValueError(
            f"bad fit payload: {len(data)} bytes, need exactly {need} "
            f"for {n}x{d} f32 + 2x{n} f32")
    off = 8
    x = np.frombuffer(data, np.float32, n * d, off).reshape(n, d)
    off += 4 * n * d
    labels = np.frombuffer(data, np.float32, n, off)
    off += 4 * n
    mask = np.frombuffer(data, np.float32, n, off)
    return x, labels, mask


class ScorerSidecar:
    """grpc.aio server wrapping an in-process Scorer."""

    def __init__(self, scorer=None, host: str = "127.0.0.1", port: int = 0,
                 warmup_rows: int = 0):
        if scorer is None:
            from linkerd_tpu.telemetry.anomaly import InProcessScorer
            scorer = InProcessScorer()
        self.scorer = scorer
        self.host = host
        self.port = port
        self.warmup_rows = warmup_rows
        self._server = None

    async def start(self) -> "ScorerSidecar":
        import grpc

        scorer = self.scorer

        async def score(request: bytes, context) -> bytes:
            x = decode_matrix(request)
            s = await scorer.score(x)
            return np.ascontiguousarray(s, np.float32).tobytes()

        async def fit(request: bytes, context) -> bytes:
            x, labels, mask = decode_fit(request)
            loss = await scorer.fit(x, labels, mask)
            return np.float32([loss]).tobytes()

        async def snapshot(request: bytes, context) -> bytes:
            # request payload is empty; response is the full serialized
            # checkpoint (lifecycle/store wire format, CRC-tailed)
            from linkerd_tpu.lifecycle.store import encode_snapshot
            snap = await asyncio.to_thread(scorer.snapshot)
            return encode_snapshot(snap)

        async def restore(request: bytes, context) -> bytes:
            from linkerd_tpu.lifecycle.store import decode_snapshot
            snap = decode_snapshot(request)
            await asyncio.to_thread(scorer.restore, snap)
            # echo the restored step so callers can confirm the swap
            return struct.pack("<Q", int(snap.step))

        handler = grpc.method_handlers_generic_handler(SERVICE, {
            "Score": grpc.unary_unary_rpc_method_handler(
                score,
                request_deserializer=None, response_serializer=None),
            "Fit": grpc.unary_unary_rpc_method_handler(
                fit,
                request_deserializer=None, response_serializer=None),
            "Snapshot": grpc.unary_unary_rpc_method_handler(
                snapshot,
                request_deserializer=None, response_serializer=None),
            "Restore": grpc.unary_unary_rpc_method_handler(
                restore,
                request_deserializer=None, response_serializer=None),
        })
        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers((handler,))
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        # Warm up BEFORE serving so no real Fit/Score can race the warmup
        # window (warmup restores pre-warmup scorer state when it finishes).
        if self.warmup_rows:
            warmup = getattr(scorer, "warmup", None)
            if warmup is not None:
                await warmup(self.warmup_rows)
        await self._server.start()
        return self

    async def close(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=0.5)
        closer = getattr(self.scorer, "close", None)
        if closer is not None:
            closer()  # release the scorer's dispatch ring + drainer


class GrpcScorerClient:
    """Scorer implementation that ships micro-batches to a sidecar."""

    def __init__(self, address: str, timeout_s: float = 5.0,
                 first_timeout_s: float = 60.0):
        # The first call on each RPC gets a long deadline to absorb the
        # sidecar's XLA compile (~20-40s on TPU); afterwards the short
        # steady-state deadline keeps failure detection responsive.
        self.address = address
        self.timeout_s = timeout_s
        self.first_timeout_s = first_timeout_s
        self._warm: set = set()
        # most recent Score call decomposition ({rpc_ms, bytes}): the
        # sidecar analogue of InProcessScorer.last_timing — scorer spans
        # annotate the gRPC hop cost instead of device phases
        self.last_timing = None
        self._channel = None
        self._score = None
        self._fit = None
        self._snapshot = None
        self._restore = None

    @staticmethod
    def _bucket(rpc: str, rows: int) -> tuple:
        # Each power-of-two bucket is a distinct XLA compilation (~20-40s
        # on TPU). Warm state is keyed by (rpc, bucket) so the first call
        # into any bucket gets the long deadline while compiled buckets
        # keep the short one.
        return (rpc, bucket_rows(rows))

    def _deadline(self, key: tuple) -> float:
        return self.timeout_s if key in self._warm else self.first_timeout_s

    def _ensure(self) -> None:
        if self._channel is None:
            import grpc

            self._channel = grpc.aio.insecure_channel(self.address)
            self._score = self._channel.unary_unary(
                f"/{SERVICE}/Score",
                request_serializer=None, response_deserializer=None)
            self._fit = self._channel.unary_unary(
                f"/{SERVICE}/Fit",
                request_serializer=None, response_deserializer=None)
            self._snapshot = self._channel.unary_unary(
                f"/{SERVICE}/Snapshot",
                request_serializer=None, response_deserializer=None)
            self._restore = self._channel.unary_unary(
                f"/{SERVICE}/Restore",
                request_serializer=None, response_deserializer=None)

    async def snapshot(self):
        """Pull the sidecar's full model state as a ModelSnapshot — the
        fleet-wide distribution path: one router checkpoints/promotes,
        every other router pulls and restores without restarting."""
        from linkerd_tpu.lifecycle.store import decode_snapshot
        self._ensure()
        rsp = await self._snapshot(b"", timeout=self.first_timeout_s)
        return decode_snapshot(rsp)

    async def restore(self, snap) -> int:
        """Hot-swap ``snap`` into the sidecar; returns the restored step."""
        from linkerd_tpu.lifecycle.store import encode_snapshot
        self._ensure()
        rsp = await self._restore(encode_snapshot(snap),
                                  timeout=self.first_timeout_s)
        return struct.unpack("<Q", rsp)[0]

    async def score(self, x: np.ndarray) -> np.ndarray:
        import time
        self._ensure()
        key = self._bucket("score", len(x))
        payload = encode_matrix(x)
        t0 = time.monotonic()
        rsp = await self._score(payload, timeout=self._deadline(key))
        self.last_timing = {
            "rpc_ms": (time.monotonic() - t0) * 1e3,
            "bytes": len(payload) + len(rsp),
        }
        self._warm.add(key)
        return np.frombuffer(rsp, np.float32)

    async def fit(self, x: np.ndarray, labels: np.ndarray,
                  mask: np.ndarray) -> float:
        self._ensure()
        key = self._bucket("fit", len(x))
        rsp = await self._fit(encode_fit(x, labels, mask),
                              timeout=self._deadline(key))
        self._warm.add(key)
        return float(np.frombuffer(rsp, np.float32)[0])

    async def aclose(self) -> None:
        """Close the channel, awaiting completion (use before the event
        loop shuts down)."""
        if self._channel is not None:
            ch, self._channel = self._channel, None
            await ch.close()

    def close(self) -> None:
        if self._channel is not None:
            ch, self._channel = self._channel, None
            try:
                asyncio.get_running_loop()
            except RuntimeError:
                # no running loop (interpreter teardown): nothing to
                # drain the close on; the socket dies with the process.
                # Checked BEFORE ch.close() is called so no never-awaited
                # coroutine is orphaned.
                return
            from linkerd_tpu.core.tasks import spawn
            spawn(ch.close(), what="sidecar-channel-close")


def main() -> None:
    """``python -m linkerd_tpu.telemetry.sidecar`` — run a scorer
    replica as a standalone process, optionally ANNOUNCED through the
    fs announcer so linkerds resolve it like any other service
    (``sidecarAddress: /#/io.l5d.fs/<name>``): the scorer tier becomes
    a first-class, load-balanced fleet service instead of a pinned
    host:port."""
    import argparse
    import signal

    parser = argparse.ArgumentParser(
        description="linkerd-tpu anomaly scorer replica")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--warmup-rows", type=int, default=0)
    parser.add_argument(
        "--announce-dir", default=None,
        help="fs-announcer root dir (the fs namer's rootDir); when set "
             "the replica registers itself under --announce-name and "
             "withdraws on shutdown")
    parser.add_argument("--announce-name", default="l5d-scorer")
    args = parser.parse_args()

    async def amain() -> None:
        from linkerd_tpu.core import Path

        sidecar = await ScorerSidecar(
            host=args.host, port=args.port,
            warmup_rows=args.warmup_rows).start()
        announcement = None
        if args.announce_dir:
            from linkerd_tpu.announcer import FsAnnouncer
            announcer = FsAnnouncer(args.announce_dir,
                                    Path.read("/io.l5d.fs"))
            announcement = announcer.announce(
                args.host, sidecar.port, Path.read(f"/{args.announce_name}"))
            log.info("scorer replica announced as %s in %s",
                     args.announce_name, args.announce_dir)
        print(f"SCORER_SIDECAR {args.host}:{sidecar.port}", flush=True)
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        if announcement is not None:
            announcement.close()
        await sidecar.close()

    import logging as _logging
    _logging.basicConfig(level=_logging.INFO)
    asyncio.run(amain())


if __name__ == "__main__":
    main()
