"""MetricsTree — the concurrent metric registry.

Reference parity: telemetry/core/.../MetricsTree.scala:9-122 (tree of scopes
with Counter/Gauge/Stat leaves, CAS registration, prune()) and the
BucketedHistogram (com/twitter/finagle/stats/buoyant/BucketedHistogram.scala).

Scope convention is the reference's ``rt/<router>/{server,service/<path>,
client/<id>}/...`` — the Prometheus exporter's label rewriting depends on it
(PrometheusTelemeter.scala:62-80).

Python build notes: leaf mutation is GIL-atomic (+= on int is not atomic
across threads in theory, so counters use an internal lock only on the slow
path — in practice the asyncio data plane mutates from one thread and the
scorer thread reads snapshots).
"""

from __future__ import annotations

import bisect
import math
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple, Union


class Counter:
    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0

    def incr(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """A gauge: either a set value or a zero-arg callable sampled on read."""

    __slots__ = ("_fn", "_value")

    def __init__(self, fn: Optional[Callable[[], float]] = None) -> None:
        self._fn = fn
        self._value = 0.0

    def set(self, v: float) -> None:
        self._fn = None
        self._value = float(v)

    @property
    def value(self) -> float:
        return float(self._fn()) if self._fn is not None else self._value


class Stat:
    """A histogram stat with power-of-two-ish bucketing.

    Bucket boundaries grow geometrically (~10% steps like the reference's
    BucketedHistogram error bound), giving bounded memory and cheap
    percentile snapshots.
    """

    __slots__ = ("_limits", "_counts", "count", "sum", "min", "max", "_lock")

    _SHARED_LIMITS: Optional[List[float]] = None

    @classmethod
    def _limits_shared(cls) -> List[float]:
        if cls._SHARED_LIMITS is None:
            # ~10% geometric buckets from 10us (in ms units) to 1e9 —
            # sub-ms resolution matters for a proxy with sub-1ms p99
            # targets (BASELINE.md).
            limits = [0.0]
            v = 0.01
            while v < 1e9:
                limits.append(v)
                v *= 1.1
            cls._SHARED_LIMITS = limits
        return cls._SHARED_LIMITS

    def __init__(self) -> None:
        self._limits = self._limits_shared()
        self._counts = [0] * len(self._limits)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._lock = threading.Lock()

    def add(self, v: float) -> None:
        with self._lock:
            idx = bisect.bisect_right(self._limits, v) - 1
            if idx < 0:
                idx = 0
            self._counts[idx] += 1
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def percentile(self, q: float) -> float:
        """Approximate q-quantile (q in [0,1]) from bucket midpoints."""
        with self._lock:
            if self.count == 0:
                return 0.0
            target = max(1, math.ceil(q * self.count))
            seen = 0
            for i, c in enumerate(self._counts):
                seen += c
                if seen >= target:
                    lo = self._limits[i]
                    hi = self._limits[i + 1] if i + 1 < len(self._limits) else lo
                    return (lo + hi) / 2.0
            return self.max

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            if self.count == 0:
                return {"count": 0}
        return {
            "count": self.count,
            "sum": self.sum,
            "avg": self.sum / self.count,
            "min": self.min,
            "max": self.max,
            "p50": self.percentile(0.50),
            "p90": self.percentile(0.90),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
            "p999": self.percentile(0.999),
        }


Metric = Union[Counter, Gauge, Stat]


@contextmanager
def observed(node: "MetricsTree"):
    """The standard op-instrumentation triple around a block:
    ``requests`` counter on entry, ``failures`` counter when the block
    raises, ``latency_ms`` stat always. One definition so every
    instrumented surface (namerd store ops, iface methods) exports the
    same family shape."""
    node.counter("requests").incr()
    t0 = time.monotonic()
    try:
        yield
    except BaseException:
        node.counter("failures").incr()
        raise
    finally:
        node.stat("latency_ms").add((time.monotonic() - t0) * 1e3)


class MetricsTree:
    """A tree of scopes; each node may hold one metric leaf + children."""

    __slots__ = ("_children", "_metric", "_lock")

    def __init__(self) -> None:
        self._children: Dict[str, "MetricsTree"] = {}
        self._metric: Optional[Metric] = None
        self._lock = threading.Lock()

    # -- navigation -------------------------------------------------------
    def scope(self, *names: str) -> "MetricsTree":
        node = self
        for name in names:
            nxt = node._children.get(name)
            if nxt is None:
                with node._lock:
                    nxt = node._children.setdefault(name, MetricsTree())
            node = nxt
        return node

    # -- leaf registration (idempotent; type conflicts raise) -------------
    def _mk(self, cls, *args) -> Metric:
        m = self._metric
        if m is None:
            with self._lock:
                if self._metric is None:
                    self._metric = cls(*args)
                m = self._metric
        if not isinstance(m, cls):
            raise ValueError(
                f"metric type conflict: wanted {cls.__name__}, "
                f"have {type(m).__name__}")
        return m

    def counter(self, *names: str) -> Counter:
        return self.scope(*names)._mk(Counter)

    def gauge(self, *names: str, fn: Optional[Callable[[], float]] = None) -> Gauge:
        g = self.scope(*names)._mk(Gauge)
        if fn is not None:
            g._fn = fn
        return g

    def stat(self, *names: str) -> Stat:
        return self.scope(*names)._mk(Stat)

    # -- maintenance ------------------------------------------------------
    def prune(self, *names: str) -> None:
        """Drop a subtree (ref: MetricsTree.prune, used by
        MetricsPruningModule when clients expire)."""
        if not names:
            return
        node = self
        for name in names[:-1]:
            node = node._children.get(name)  # type: ignore[assignment]
            if node is None:
                return
        with node._lock:
            node._children.pop(names[-1], None)

    # -- export -----------------------------------------------------------
    def walk(self, prefix: Tuple[str, ...] = ()) -> Iterator[Tuple[Tuple[str, ...], Metric]]:
        if self._metric is not None:
            yield prefix, self._metric
        for name, child in sorted(self._children.items()):
            yield from child.walk(prefix + (name,))

    def flatten(self, sep: str = "/") -> Dict[str, Any]:
        """Flat name -> value mapping (stats expand to their snapshots),
        the shape /admin/metrics.json serves."""
        out: Dict[str, Any] = {}
        for names, metric in self.walk():
            key = sep.join(names)
            if isinstance(metric, Counter):
                out[key] = metric.value
            elif isinstance(metric, Gauge):
                out[key] = metric.value
            else:
                for k, v in metric.snapshot().items():
                    out[f"{key}{sep}{k}"] = v
        return out

    def tree_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if isinstance(self._metric, Counter):
            out["counter"] = self._metric.value
        elif isinstance(self._metric, Gauge):
            out["gauge"] = self._metric.value
        elif isinstance(self._metric, Stat):
            out["stat"] = self._metric.snapshot()
        for name, child in sorted(self._children.items()):
            out[name] = child.tree_dict()
        return out
