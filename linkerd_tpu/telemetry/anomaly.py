"""``io.l5d.jaxAnomaly`` — the inline ML-inference telemeter (north star).

BASELINE.json: a telemeter that taps the router stack, extracts per-request
feature vectors, micro-batches them to a JAX/TPU anomaly scorer
(autoencoder + classifier), and feeds scores back into failure-accrual /
response-classification policy plus the admin metrics surface.

Data path (all off the request critical path — the recorder filter does
O(1) Python work per request; everything else is batched):

    request -> FeatureRecorder filter -> ring buffer (deque)
            -> micro-batcher task (drain + featurize -> float32[B, D])
            -> scorer (in-process jit OR gRPC sidecar)
            -> ScoreBoard (per-dst EWMA scores, Var + metrics gauges)
            -> AnomalyFailureAccrualPolicy / admin handlers

Reference parity: implements the Telemeter SPI (telemetry/core/.../
Telemeter.scala:11) the way exporter telemeters do, but taps the stack the
way the reference's stats filters do (PerDstPathStatsFilter.scala).
"""

from __future__ import annotations

import asyncio
import collections
import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from linkerd_tpu.config import register
from linkerd_tpu.control.loop import ControlConfig
from linkerd_tpu.core import Var
from linkerd_tpu.distill import DistillConfig
from linkerd_tpu.lifecycle import LifecycleConfig
from linkerd_tpu.models.features import FEATURE_DIM, FeatureVector, featurize_batch
from linkerd_tpu.protocol.http.message import Request, Response
from linkerd_tpu.router.service import Filter, Service
from linkerd_tpu.telemetry.metrics import MetricsTree
from linkerd_tpu.telemetry.telemeter import Telemeter

log = logging.getLogger(__name__)


class ScoreBoard:
    """Per-dst anomaly scores: EWMA-smoothed, observable, with a
    staleness TTL.

    The Var publishes {dst_path: score}; failure-accrual policies and the
    admin handler read it. Scores decay toward 0 when traffic stops, and
    — independently — go STALE when the scorer stops updating them (a
    degraded scorer path must not pin accrual policies to an old anomaly
    verdict): within ``ttl_s`` of the last update a score reads at full
    strength, then decays linearly to neutral (0) over one further
    ``ttl_s`` window. ``degraded`` is set by the telemeter while the
    scorer breaker is open; anomaly-aware policies treat it as
    "no signal" and fall back to their reference behavior.
    """

    def __init__(self, alpha: float = 0.3, ttl_s: Optional[float] = 30.0):
        self.alpha = alpha
        self.ttl_s = ttl_s
        self.scores: Var[dict] = Var({})
        self.degraded = False
        self._updated: Dict[str, float] = {}
        # per-REPLICA scores keyed by endpoint hostport (the balancer
        # stamps req.ctx["endpoint"] at pick time): the control loop's
        # score-weighted balancer reads these; same EWMA + staleness
        # machinery as the per-dst board
        self._ep_scores: Dict[str, float] = {}
        self._ep_updated: Dict[str, float] = {}

    def update_batch(self, dsts: List[str], scores: np.ndarray,
                     endpoints: Optional[List[Optional[str]]] = None,
                     ) -> None:
        now = time.monotonic()
        cur = dict(self.scores.sample())
        per_dst: Dict[str, List[float]] = {}
        per_ep: Dict[str, List[float]] = {}
        for i, (dst, s) in enumerate(zip(dsts, scores)):
            per_dst.setdefault(dst, []).append(float(s))
            if endpoints is not None and i < len(endpoints) \
                    and endpoints[i]:
                per_ep.setdefault(endpoints[i], []).append(float(s))
        for dst, vals in per_dst.items():
            mean = sum(vals) / len(vals)
            prev = cur.get(dst, mean)
            cur[dst] = prev + self.alpha * (mean - prev)
            self._updated[dst] = now
        for ep, vals in per_ep.items():
            mean = sum(vals) / len(vals)
            prev = self._ep_scores.get(ep, mean)
            self._ep_scores[ep] = prev + self.alpha * (mean - prev)
            self._ep_updated[ep] = now
        # endpoint keys churn with the replica set (hostports change on
        # every deploy); fully-stale entries are dead replicas — prune,
        # or the maps grow without bound on a long-running linker
        if self.ttl_s is not None and per_ep:
            dead = [ep for ep, upd in self._ep_updated.items()
                    if now - upd > 2 * self.ttl_s]
            for ep in dead:
                self._ep_scores.pop(ep, None)
                self._ep_updated.pop(ep, None)
        self.scores.update(cur)

    def _decay(self, updated: Optional[float], now: float) -> float:
        if self.ttl_s is None:
            return 1.0
        if updated is None:
            return 1.0  # pre-TTL boards (tests seed Var directly)
        age = now - updated
        if age <= self.ttl_s:
            return 1.0
        return max(0.0, 1.0 - (age - self.ttl_s) / self.ttl_s)

    def _staleness_factor(self, dst: str, now: float) -> float:
        return self._decay(self._updated.get(dst), now)

    def score_of(self, dst: str) -> float:
        raw = self.scores.sample().get(dst, 0.0)
        return raw * self._staleness_factor(dst, time.monotonic())

    def effective_scores(self) -> Dict[str, float]:
        """{dst: staleness-decayed score} — the policy-facing view."""
        now = time.monotonic()
        return {dst: s * self._staleness_factor(dst, now)
                for dst, s in self.scores.sample().items()}

    def endpoint_score_of(self, hostport: str) -> float:
        """Per-replica effective score: staleness-decayed, and neutral
        while the scorer path is degraded (a dead scorer must not pin
        a replica's down-weight)."""
        if self.degraded:
            return 0.0
        raw = self._ep_scores.get(hostport, 0.0)
        return raw * self._decay(self._ep_updated.get(hostport),
                                 time.monotonic())

    def effective_endpoint_scores(self) -> Dict[str, float]:
        if self.degraded:
            return {ep: 0.0 for ep in self._ep_scores}
        now = time.monotonic()
        return {ep: s * self._decay(self._ep_updated.get(ep), now)
                for ep, s in self._ep_scores.items()}

    def anomaly_level(self) -> float:
        """Mesh-wide anomaly level: max effective score, 0 while the
        scorer path is degraded (no signal beats a stale signal)."""
        if self.degraded:
            return 0.0
        return max(self.effective_scores().values(), default=0.0)


class FeatureRecorder(Filter[Request, Response]):
    """Tap the request path: record one FeatureVector per request into the
    ring. O(1) appends; the deque drops oldest under overload (scoring is
    best-effort, requests are never blocked). ``on_record`` (the
    telemeter's enqueue hook) counts the request toward the scored
    fraction and wakes the line-rate micro-batcher."""

    def __init__(self, ring: Deque,
                 on_record: Optional[Callable[[], None]] = None):
        from linkerd_tpu.models.features import DstTemporal
        self.ring = ring
        self._on_record = on_record
        self._inflight = 0
        self._rps_window: Deque[float] = collections.deque(maxlen=512)
        self._temporal = DstTemporal()

    async def apply(self, req: Request, service: Service) -> Response:
        t0 = time.monotonic()
        self._inflight += 1
        exc: Optional[BaseException] = None
        rsp: Optional[Response] = None
        try:
            rsp = await service(req)
            return rsp
        except BaseException as e:
            exc = e
            raise
        finally:
            self._inflight -= 1
            now = time.monotonic()
            self._rps_window.append(now)
            latency_ms = (now - t0) * 1e3
            dst = req.ctx.get("dst")
            dst_path = dst.path.show if dst is not None else "/unidentified"
            rc = req.ctx.get("response_class")
            status = rsp.status if rsp is not None else 0
            is_err = exc is not None or status >= 500
            drift, err_rate, rate_delta, mesh_err = self._temporal.observe(
                dst_path, latency_ms, is_err, now)
            fv = FeatureVector(
                latency_ms=latency_ms,
                status=status,
                retries=int(req.ctx.get("retries", 0)),
                # h2 messages carry streams, not bodies; size 0 there
                request_bytes=len(getattr(req, "body", b"") or b""),
                response_bytes=(len(getattr(rsp, "body", b"") or b"")
                                if rsp is not None else 0),
                concurrency=self._inflight + 1,
                queue_ms=0.0,
                exception=exc is not None,
                retryable=bool(getattr(rc, "is_retryable", False)),
                dst_path=dst_path,
                dst_rps=self._rps(now),
                lat_drift_ms=drift,
                dst_err_rate=err_rate,
                rate_delta=rate_delta,
                mesh_err_rate=mesh_err,
            )
            # label for fault-injection evaluation rides along when present:
            # from local ctx, or from the harness's response header
            label = req.ctx.get("fault_label")
            if label is None and rsp is not None:
                hdr = rsp.headers.get("l5d-fault-label")
                if hdr is not None:
                    try:
                        label = float(hdr)
                    except ValueError:
                        label = None  # untrusted header; never fail a request
            # the request's trace context + enqueue instant ride along so
            # the micro-batcher can emit scorer spans as children of the
            # originating request (ring wait = the span's queue
            # annotation); the balancer-picked endpoint rides too so the
            # board can score per replica (the control loop's weigher)
            self.ring.append((fv, label, req.ctx.get("trace"), now,
                              req.ctx.get("endpoint")))
            if self._on_record is not None:
                self._on_record()

    def _rps(self, now: float) -> float:
        w = self._rps_window
        if len(w) < 2:
            return 0.0
        span = now - w[0]
        return len(w) / span if span > 0 else 0.0


class Scorer:
    """Scoring + online-training backends. ``score`` takes float32[B, D]
    and returns float32[B] anomaly scores in [0, 1].

    Lifecycle hooks: ``snapshot``/``restore``/``swap`` capture and
    hot-swap the full training state (params, optimizer, normalization
    stats, step counter) without recreating the scorer. They may be sync
    (in-process: device transfers happen off the event loop via
    ``asyncio.to_thread``) or async (gRPC sidecar).

    ``last_timing``: per-call decomposition of the most recent score()
    ({queue_ms, transfer_ms, device_ms, bytes} in-process; {rpc_ms} for
    the sidecar) — the source for scorer-span annotations and the
    bench's transfer_GBps / device_step_ms seam metrics. None until the
    first scored batch; backends without instrumentation leave it None."""

    last_timing: Optional[dict] = None

    async def score(self, x: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    async def fit(self, x: np.ndarray, labels: np.ndarray,
                  mask: np.ndarray) -> float:
        raise NotImplementedError

    def snapshot(self):
        raise NotImplementedError

    def restore(self, snap) -> None:
        raise NotImplementedError

    def swap(self, snap):
        """Restore ``snap`` and return the previous state's snapshot."""
        raise NotImplementedError

    def close(self) -> None:
        return


class InProcessScorer(Scorer):
    """Runs the JAX model in-process, dispatched at line rate.

    The score path has NO per-call thread hop and NO fresh full-batch
    ``device_put``: batches land in persistent double-buffered staging
    buffers (one pair per padded batch bucket), the jitted score step
    takes the device copy with ``donate_argnums`` (XLA reuses the
    buffer instead of allocating per batch), and dispatch rides JAX
    async dispatch — a single background drainer thread does the
    blocking readback, so host→device transfer of batch N overlaps
    device compute of batch N-1 and the event loop never blocks on the
    device (see telemetry/linerate.RingDispatcher).

    With more than one device the SAME serving path runs sharded: a
    dp x tp mesh from parallel/mesh.py, params placed per the Megatron
    column/row specs, micro-batches fed per-device via
    ``parallel.mesh.shard_batch`` (each device receives exactly its
    shard; no single host-side device_put of the full batch) — XLA
    inserts the ICI collectives. Single-chip keeps the fused-Pallas
    kernel (ops/scoring.best_scorer)."""

    def __init__(self, seed: int = 0, learning_rate: float = 1e-3,
                 recon_weight: float = 0.7, fit_steps: int = 4,
                 devices=None):
        import jax
        import optax
        from linkerd_tpu.models.anomaly import AnomalyModelConfig, init_params
        from linkerd_tpu.ops.scoring import best_scorer

        self.cfg = AnomalyModelConfig(recon_weight=recon_weight)
        self._opt = optax.adam(learning_rate)
        devices = list(devices if devices is not None else jax.devices())
        self.mesh = None
        self._batch_multiple = 1
        if len(devices) > 1:
            from linkerd_tpu.parallel.mesh import (
                init_sharded, make_mesh, make_score_step, make_train_step,
            )
            # width-aware tp heuristic: at this model's scale the mesh
            # comes out pure-data (tp only engages for wide layers)
            self.mesh = make_mesh(devices,
                                  model_width=max(self.cfg.enc_dims))
            self.params, self._opt_state = init_sharded(
                self.mesh, jax.random.key(seed), self._opt, self.cfg)
            # the one jitted score step DONATES its input batch: every
            # caller hands it a buffer it never re-reads (the dispatch
            # ring's staging copy, or a fresh per-call device_put on
            # the instrumented path)
            self._scorer = make_score_step(self.mesh, self.cfg,
                                           donate=True)
            self._train_step = make_train_step(self.mesh, self._opt, self.cfg)
            self._batch_multiple = self.mesh.shape["data"]
        else:
            params = init_params(jax.random.key(seed), self.cfg)
            # honor an explicit device choice (e.g. pin to the second
            # chip); jit follows the committed placement of the params
            self.params = jax.device_put(params, devices[0])
            self._opt_state = self._opt.init(self.params)
            self._scorer = best_scorer(self.cfg, donate=True)
            self._train_step = self._mk_train_step()
        self.fit_steps = fit_steps
        self._devices = devices
        # cumulative train steps; checkpointed so a restored model resumes
        # its lineage, not a fresh step count
        self._step = 0
        # Running feature normalization (updated on non-anomalous training
        # rows): without it the autoencoder's reconstruction error is
        # dominated by raw feature scale and tanh() saturates for normal
        # AND anomalous traffic alike. The host keeps the authoritative
        # numpy stats (cheap EWMA over a few rows); device mirrors feed
        # the jitted steps, which apply models.anomaly.normalize_features
        # on device — the z-score with its 1e-2 soft variance floor (a
        # near-constant training dim must register novelty as a LARGE
        # z-score, not a 1e3-sigma blowup; hard clipping cost ~0.15 AUC
        # on the k8s-restart benchmark).
        self._mu = np.zeros(self.cfg.in_dim, np.float32)
        self._var = np.ones(self.cfg.in_dim, np.float32)
        self._norm_momentum = 0.2
        self._norm_initialized = False
        # score-path timing decomposition (worker-thread writes are
        # GIL-atomic dict swaps; readers snapshot last_timing whole).
        # OFF by default: the phase-split adds two device barriers per
        # batch, forfeiting transfer/compute overlap — only pay it when
        # a consumer exists (span sink installed, or bench seam metrics)
        self.timing_enabled = False
        # with timing on, only every Nth batch pays the instrumented
        # (two-barrier, thread-hop) path; the rest ride the line-rate
        # ring and span tags reuse the last sampled decomposition.
        # 1 = time every batch (the bench's seam phase sets this).
        self.timing_sample_every = 1
        self._timing_i = 0
        self.last_timing: Optional[dict] = None
        self.timing_totals = {"calls": 0, "queue_ms": 0.0,
                              "transfer_ms": 0.0, "device_ms": 0.0,
                              "bytes": 0}
        # persistent double-buffered staging ring (the line-rate
        # dispatch path; see class docstring)
        from linkerd_tpu.telemetry.linerate import RingDispatcher
        self._dispatcher = RingDispatcher(self.cfg.in_dim,
                                          self._bucket_target)
        self._place_norm()

    def _place_norm(self) -> None:
        """Refresh the device mirrors of the normalization stats: tiny
        replicated arrays the jitted score/train steps consume so the
        whole normalize->score pipeline runs on device (each data-axis
        shard z-scores its own rows; the host never touches the batch)."""
        import jax

        if self.mesh is not None:
            from linkerd_tpu.parallel.mesh import replicated
            placement = replicated(self.mesh)
        else:
            placement = self._devices[0]
        self._mu_d = jax.device_put(self._mu, placement)
        self._var_d = jax.device_put(self._var, placement)

    def _update_norm(self, x: np.ndarray, labels: np.ndarray,
                     mask: np.ndarray) -> None:
        # learn the "normal" distribution: exclude rows labeled anomalous
        normal = x[(mask == 0.0) | (labels == 0.0)]
        if len(normal) == 0:
            return
        mu = normal.mean(axis=0)
        var = normal.var(axis=0) + 1e-6
        if not self._norm_initialized:
            self._mu, self._var = mu, var
            self._norm_initialized = True
        else:
            m = self._norm_momentum
            self._mu = (1 - m) * self._mu + m * mu
            self._var = (1 - m) * self._var + m * var
        self._mu = np.asarray(self._mu, np.float32)
        self._var = np.asarray(self._var, np.float32)
        self._place_norm()

    def _mk_train_step(self):
        import jax
        import optax
        from linkerd_tpu.models.anomaly import loss_fn, normalize_features

        cfg = self.cfg
        opt = self._opt

        @jax.jit
        def step(params, opt_state, x, labels, mask, row_mask=None,
                 mu=None, var=None):
            if mu is not None:
                x = normalize_features(x, mu, var)
            loss, grads = jax.value_and_grad(loss_fn)(
                params, x, labels, mask, cfg, row_mask)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        return step

    def _bucket_target(self, n: int) -> int:
        """Padded batch size for ``n`` rows: next power of two, rounded
        up to a multiple of the data-axis size (sharded arrays must
        divide evenly over the mesh). Bucketing batch shapes bounds the
        number of distinct XLA compilations to ~log2(maxBatch) instead
        of one per batch size — and bounds the dispatch ring to one
        staging pair per bucket."""
        from linkerd_tpu.telemetry.sidecar import bucket_rows
        target = bucket_rows(n)
        m = self._batch_multiple
        if m > 1 and target % m:
            target += m - target % m
        return target

    def _pad_rows(self, arr: np.ndarray) -> np.ndarray:
        n = len(arr)
        target = self._bucket_target(n)
        if target == n:
            return arr
        widths = ((0, target - n),) + ((0, 0),) * (arr.ndim - 1)
        return np.pad(arr, widths)

    # -- lifecycle: snapshot / restore / swap -----------------------------
    def snapshot(self):
        """Capture the full training state to host memory: params,
        optimizer state, normalization stats, config, step counter. The
        returned ModelSnapshot restores to bit-identical scores on the
        same backend. Blocking (device->host transfer) — call off the
        event loop (the lifecycle manager uses asyncio.to_thread)."""
        import jax

        from linkerd_tpu.lifecycle.store import ModelSnapshot

        params = jax.device_get(self.params)
        opt_leaves = [np.asarray(leaf) for leaf in
                      jax.tree_util.tree_leaves(
                          jax.device_get(self._opt_state))]
        return ModelSnapshot(
            params=params, opt_leaves=opt_leaves,
            mu=self._mu.copy(), var=self._var.copy(),
            norm_initialized=self._norm_initialized,
            step=self._step, cfg=self.cfg)

    def restore(self, snap) -> None:
        """Hot-swap a snapshot in: params re-placed per the current
        topology (the dp x tp mesh specs when sharded, the pinned device
        otherwise), optimizer state rebuilt leaf-for-leaf. The already
        compiled score/train steps keep working — shapes, dtypes, and
        shardings are unchanged, so no recompilation."""
        import jax

        from linkerd_tpu.lifecycle.store import _cfg_to_dict

        if _cfg_to_dict(snap.cfg) != _cfg_to_dict(self.cfg):
            raise ValueError(
                f"snapshot config {snap.cfg_dict()} does not match "
                f"scorer config {_cfg_to_dict(self.cfg)}")
        if self.mesh is not None:
            from linkerd_tpu.parallel.mesh import place_snapshot
            self.params, self._opt_state = place_snapshot(
                self.mesh, self._opt, snap.params, snap.opt_leaves)
        else:
            params = jax.device_put(snap.params, self._devices[0])
            template = self._opt.init(params)
            t_leaves, treedef = jax.tree_util.tree_flatten(template)
            if len(snap.opt_leaves) != len(t_leaves):
                raise ValueError(
                    f"optimizer state mismatch: snapshot has "
                    f"{len(snap.opt_leaves)} leaves, optimizer expects "
                    f"{len(t_leaves)}")
            placed = []
            for leaf, t in zip(snap.opt_leaves, t_leaves):
                arr = np.asarray(leaf)
                if tuple(arr.shape) != tuple(t.shape):
                    raise ValueError(
                        f"optimizer leaf shape mismatch: snapshot "
                        f"{arr.shape} vs optimizer {tuple(t.shape)}")
                placed.append(jax.device_put(arr.astype(t.dtype),
                                             self._devices[0]))
            self.params = params
            self._opt_state = jax.tree_util.tree_unflatten(treedef, placed)
        self._mu = np.asarray(snap.mu, np.float32).copy()
        self._var = np.asarray(snap.var, np.float32).copy()
        self._norm_initialized = bool(snap.norm_initialized)
        self._place_norm()
        self._step = int(snap.step)

    def swap(self, snap):
        """Restore ``snap``; returns the displaced state so a failed
        promotion can be undone without a store round-trip."""
        old = self.snapshot()
        self.restore(snap)
        return old

    async def warmup(self, rows: int = 4) -> None:
        """Trigger compilation of the score and fit paths without letting
        the dummy rows contaminate normalization stats or parameters.
        Also exercises the snapshot->restore->score hot-swap path (host
        gather, re-placement, optimizer-state rebuild) so the first real
        swap doesn't stall the event loop."""
        rows = max(rows, self._batch_multiple, 1)
        x = np.zeros((rows, self.cfg.in_dim), np.float32)
        params, opt_state = self.params, self._opt_state
        mu, var, init = self._mu, self._var, self._norm_initialized
        step = self._step
        try:
            await self.score(x)
            await self.fit(x, np.zeros(rows, np.float32),
                           np.zeros(rows, np.float32))
            snap = await asyncio.to_thread(self.snapshot)
            await asyncio.to_thread(self.restore, snap)
            await self.score(x)
        finally:
            # startup-sequenced: warmup runs before the telemeter's drain
            # loop starts, so no concurrent fit/score exists to clobber
            self.params, self._opt_state = params, opt_state  # l5d: ignore[await-atomicity] — warmup is startup-sequenced; no concurrent mutator yet
            self._mu, self._var, self._norm_initialized = mu, var, init  # l5d: ignore[await-atomicity] — warmup is startup-sequenced; no concurrent mutator yet
            self._place_norm()
            self._step = step  # l5d: ignore[await-atomicity] — warmup is startup-sequenced; no concurrent mutator yet

    def _prep(self, x: np.ndarray) -> np.ndarray:
        """Pad + cast to the f32 transfer dtype. Raw features ship as-is:
        normalization happens ON DEVICE inside the jitted step (mu/var
        mirrors via _place_norm), fused into the first matmul's producer
        — so f32 precision is kept through the z-score (raw latencies in
        the thousands would lose mantissa bits if cast to bf16 before
        subtracting mu) and the sharded path normalizes each batch shard
        on its own device."""
        return self._pad_rows(np.asarray(x, np.float32))  # l5d: ignore[jax-hotpath] — host-side dtype cast of the input batch, not a device readback

    def _batch_placement(self):
        """Device placement for an input batch: the data-axis sharding
        when meshed, the pinned device otherwise."""
        if self.mesh is not None:
            from linkerd_tpu.parallel.mesh import batch_sharding
            return batch_sharding(self.mesh)
        return self._devices[0]

    def _note_timing(self, queue_ms: float, transfer_ms: float,
                     device_ms: float, nbytes: int) -> None:
        self.last_timing = {"queue_ms": queue_ms,
                            "transfer_ms": transfer_ms,
                            "device_ms": device_ms, "bytes": nbytes}
        t = self.timing_totals
        t["calls"] += 1
        t["queue_ms"] += queue_ms
        t["transfer_ms"] += transfer_ms
        t["device_ms"] += device_ms
        t["bytes"] += nbytes

    async def score(self, x: np.ndarray) -> np.ndarray:
        """Score [n, D] -> [n] through the donated staging ring. The
        event loop only pays one host memcpy into the staging slot plus
        JAX async dispatch; readback happens on the drainer thread.
        Hot-swap safety: ``params``/``mu``/``var`` are captured HERE —
        a concurrent ``restore``/``fit`` repoints the attributes but
        never mutates the captured (immutable) device arrays, so an
        in-flight donated batch always completes against a consistent
        model."""
        if self.timing_enabled:
            self._timing_i += 1
            if self.timing_sample_every <= 1 \
                    or self._timing_i % self.timing_sample_every == 1:
                return await self._score_timed(x)
        xf = np.asarray(x, np.float32)  # l5d: ignore[jax-hotpath] — host-side dtype cast of the input batch, not a device readback
        params = self.params
        mu_d, var_d = self._mu_d, self._var_d
        if self.mesh is not None:
            from linkerd_tpu.parallel.mesh import shard_batch
            mesh = self.mesh

            def step(staging: np.ndarray):
                # per-device shard feed; the assembled array is donated
                xd = shard_batch(mesh, staging)
                return self._scorer(params, xd, mu_d, var_d)
        else:
            dev = self._devices[0]

            def step(staging: np.ndarray):
                import jax
                xd = jax.device_put(staging, dev)  # l5d: ignore[jax-hotpath] — async placement of the persistent staging buffer; donated to the step, never re-read
                return self._scorer(params, xd, mu_d, var_d)

        return await self._dispatcher.dispatch(xf, step)

    async def _score_timed(self, x: np.ndarray) -> np.ndarray:
        """Instrumented scoring: explicit transfer/step/readback phases
        so the seam cost is measurable (transfer_GBps, device-step-ms)
        and scorer spans can split queue/device/transfer out. Pays two
        device barriers per batch — opt-in via ``timing_enabled`` only;
        the line-rate path is ``score`` above."""
        n = len(x)
        t_submit = time.monotonic()
        xn = self._prep(x)
        # capture the (mu, var) pair BEFORE dispatching to the worker
        # thread: a concurrent fit() repoints both mirrors, and reading
        # them from the thread could tear the pair (new mu, old var)
        mu_d, var_d = self._mu_d, self._var_d
        params = self.params

        def run() -> np.ndarray:
            import jax
            t0 = time.monotonic()
            xd = jax.block_until_ready(  # l5d: ignore[jax-hotpath] — instrumented path: the barriers ARE the measurement
                jax.device_put(xn, self._batch_placement()))  # l5d: ignore[jax-hotpath] — instrumented path: fresh per-call transfer, measured deliberately
            t1 = time.monotonic()
            import warnings

            from linkerd_tpu.telemetry.linerate import (
                _DONATION_DECLINED_MSG,
            )
            with warnings.catch_warnings():
                # first-compile of a bucket may happen here instead of
                # on the ring path; same expected donation-decline note
                warnings.filterwarnings(
                    "ignore", message=_DONATION_DECLINED_MSG)
                r = jax.block_until_ready(  # l5d: ignore[jax-hotpath] — instrumented path: device-step barrier, measured deliberately
                    self._scorer(params, xd, mu_d, var_d))
            t2 = time.monotonic()
            out = np.asarray(r, dtype=np.float32)[:n]  # l5d: ignore[jax-hotpath] — instrumented path: host readback timed deliberately
            t3 = time.monotonic()
            self._note_timing(
                queue_ms=(t0 - t_submit) * 1e3,
                transfer_ms=(t1 - t0 + t3 - t2) * 1e3,
                device_ms=(t2 - t1) * 1e3,
                nbytes=xn.nbytes + out.nbytes)
            return out

        return await asyncio.to_thread(run)  # l5d: ignore[jax-hotpath] — opt-in instrumented path only; the serving path is the donated ring dispatch

    async def fit(self, x: np.ndarray, labels: np.ndarray,
                  mask: np.ndarray) -> float:
        n = len(x)
        self._update_norm(x, labels, mask)
        xn = self._prep(x)
        labels = self._pad_rows(np.asarray(labels, np.float32))
        mask = self._pad_rows(np.asarray(mask, np.float32))
        # row_mask excludes the padding rows from BOTH loss terms so the
        # sharded and single-chip paths train on the same objective
        row_mask = (self._pad_rows(np.ones(n, np.float32))
                    if len(xn) != n else None)

        mu_d, var_d = self._mu_d, self._var_d  # consistent pair (see score)

        def run() -> float:
            loss = float("nan")
            for _ in range(self.fit_steps):
                self.params, self._opt_state, loss = self._train_step(
                    self.params, self._opt_state, xn, labels, mask,
                    row_mask, mu_d, var_d)
            self._step += self.fit_steps
            return float(loss)

        return await asyncio.to_thread(run)

    def close(self) -> None:
        self._dispatcher.close()


@register("telemeter", "io.l5d.jaxAnomaly")
@dataclass
class JaxAnomalyConfig:
    maxBatch: int = 1024
    intervalMs: int = 50
    ringCapacity: int = 65536
    maxBatchesPerWake: int = 8  # catch-up burst ceiling under backlog
    scoreThreshold: float = 0.5
    trainEveryBatches: int = 8      # online-fit cadence (0 = never train)
    reconWeight: float = 0.7
    learningRate: float = 0.001
    # line-rate micro-batcher (the default): drain is size- and
    # deadline-triggered — a batch dispatches when maxBatch rows are
    # pending OR the oldest pending row has lingered maxLingerMs,
    # whichever first — so 100% of requests are scored with bounded
    # added queue latency. lineRate: false falls back to the legacy
    # intervalMs polling loop (sampled-batch behavior).
    lineRate: bool = True
    maxLingerMs: float = 2.0
    scoreConcurrency: int = 2  # batches in flight (double-buffer depth)
    # gRPC sidecar address: "host:port" (one pinned replica),
    # "host:p1,host:p2" (static replica pool, load-balanced), or a
    # namer path "/#/io.l5d.fs/l5d-scorer" — announced scorer replicas
    # resolved through the linker's configured namers and load-balanced
    # like any other service (linkerd_tpu/fleet/scorer_pool.py)
    sidecarAddress: Optional[str] = None
    # sidecar tiering: "fallback" (default) serves every batch from the
    # in-process line-rate scorer and demotes the sidecar to a fallback
    # tier behind its breaker; "primary" keeps the sidecar as the one
    # scorer (the pre-line-rate wiring, used by the chaos harnesses)
    sidecarTier: str = "fallback"
    # in-data-plane scoring (the native tier): "primary" exports the
    # serving model as a versioned, CRC'd weight blob — published into
    # the fastpath engines' double-buffered weight slab at startup and
    # on every lifecycle promote/hot-swap — so engine rows arrive
    # PRE-SCORED (featurized and evaluated inside the epoll thread,
    # sub-ms added latency) and the JAX path only trains and serves
    # rows the engine could not score; "off" keeps every row on the
    # JAX tier. Python-path (non-fastPath) rows always score on JAX.
    nativeTier: str = "primary"
    # native blob weight encoding: f32 | int8 | int4 (int4 packs two
    # weights per byte — the smallest blobs/deltas; parity bound pinned
    # by test alongside the f32/int8 bounds)
    nativeQuant: str = "f32"
    # without a lifecycle: block there are no promote/rollback events
    # to chase, so the ONLINE-trained model is re-exported to the
    # engines on this cadence (seconds; 0 disables) — the native tier
    # must track training, not serve the startup init blob forever.
    # With a lifecycle, promotes republish and bound the staleness.
    nativeRefreshS: float = 30.0
    # scorer-path resilience (sidecar mode): per-call deadline, breaker
    # thresholds/probe backoffs, and the ScoreBoard staleness TTL (stale
    # scores decay to neutral so a dead scorer can't pin accrual policy)
    scoreTimeoutMs: int = 2000
    breakerFailures: int = 3
    breakerMinBackoffMs: int = 500
    breakerMaxBackoffMs: int = 30000
    scoreTtlSecs: float = 30.0
    # model lifecycle: checkpointing, shadow-eval promotion gating, drift
    # detection, restart restore (see linkerd_tpu/lifecycle/)
    lifecycle: Optional["LifecycleConfig"] = None
    # reactive control loop: score-weighted balancing, adaptive
    # admission, anomaly-triggered namerd dtab overrides (see
    # linkerd_tpu/control/)
    control: Optional["ControlConfig"] = None
    # continuous in-plane learning: drift-triggered distillation of
    # per-route specialist heads, shadow-gated and delta-published to
    # the engines' weight bank (see linkerd_tpu/distill/)
    distill: Optional["DistillConfig"] = None

    def mk(self, metrics: MetricsTree) -> "JaxAnomalyTelemeter":
        return JaxAnomalyTelemeter(self, metrics)


class JaxAnomalyTelemeter(Telemeter):
    def __init__(self, cfg: JaxAnomalyConfig, metrics: MetricsTree,
                 scorer: Optional[Scorer] = None):
        if cfg.maxBatchesPerWake < 1:
            # 0 would silently disable draining (NOT a sentinel like
            # trainEveryBatches' 0 = never)
            raise ValueError("maxBatchesPerWake must be >= 1")
        if cfg.sidecarTier not in ("primary", "fallback"):
            raise ValueError("sidecarTier must be 'primary' or 'fallback'")
        if cfg.nativeTier not in ("primary", "off"):
            raise ValueError("nativeTier must be 'primary' or 'off'")
        if cfg.nativeQuant not in ("f32", "int8", "int4"):
            raise ValueError(
                "nativeQuant must be 'f32', 'int8', or 'int4'")
        if cfg.distill is not None \
                and (cfg.distill.quant or "f32") not in ("f32", "int8",
                                                         "int4"):
            raise ValueError(
                "distill.quant must be 'f32', 'int8', or 'int4'")
        if cfg.nativeRefreshS < 0:
            raise ValueError("nativeRefreshS must be >= 0")
        if cfg.maxLingerMs < 0:
            raise ValueError("maxLingerMs must be >= 0")
        if cfg.scoreConcurrency < 1:
            raise ValueError("scoreConcurrency must be >= 1")
        from linkerd_tpu.telemetry.linerate import (
            NativeFeatureRing, NativeFeaturizer,
        )
        self.cfg = cfg
        self.metrics = metrics
        self.ring: Deque = collections.deque(maxlen=cfg.ringCapacity)
        # raw native-engine rows, drained C -> ring memory by the
        # FastPathController and consumed zero-copy by the batcher
        self.native_ring = NativeFeatureRing(cfg.ringCapacity)
        self._native_featurizer = NativeFeaturizer()
        self.board = ScoreBoard(ttl_s=cfg.scoreTtlSecs)
        self._scorer = scorer
        self._stop = asyncio.Event()
        self._wake = asyncio.Event()  # batcher wake: rows pending
        self._fit_lock = asyncio.Lock()
        self._node = metrics.scope("anomaly")
        self._scored = self._node.counter("scored_total")
        # rows scored IN the native engines (in-data-plane tier); the
        # scored_total counter includes them — native_scored_fraction
        # is the native-vs-JAX tier split
        self._native_scored = self._node.counter("native_scored_total")
        self._node.gauge("native_scored_fraction",
                         fn=self._native_fraction)
        # every request that ENTERS the scoring path (recorder append or
        # native-ring row): scored_total / requests_total is the scored
        # fraction — "100% scored" is measured, not asserted
        self._requests = self._node.counter("requests_total")
        self._node.gauge("scored_fraction", fn=self._scored_fraction)
        self._dropped = self._node.gauge("ring_depth", fn=lambda: len(self.ring))
        self._node.gauge("native_ring_depth",
                         fn=lambda: float(len(self.native_ring)))
        self._node.gauge("native_ring_dropped",
                         fn=lambda: float(self.native_ring.dropped))
        self._batches = self._node.counter("batches")
        self._train_loss = self._node.gauge("train_loss")
        # degraded mode: 1 while the scorer path is failing (breaker
        # open / calls erroring); the data plane keeps serving, scoring
        # pauses, anomaly-aware policies fall back to reference behavior
        self._degraded = self._node.gauge("degraded")
        self._degraded.set(0.0)
        self._score_failures = self._node.counter("score_failures")
        self._dropped_batches = self._node.counter("dropped_batches")
        # fleet model coordination: replicas restored per promote
        self._fleet_model_pushes = self._node.counter(
            "fleet_model_pushes")
        self._gauges: Dict[str, object] = {}
        self._batch_i = 0
        # native weight publication: the FastPath controllers register
        # their engines as sinks; the serving model is exported as a
        # CRC'd blob at startup and on every lifecycle promote/rollback
        # hot-swap, and the last blob is replayed to late registrations
        self._weight_sinks: List[Callable[[bytes], None]] = []
        # full sink -> delta-patch sink (engines that can apply
        # per-route L5DWTD01 patches register one alongside)
        self._delta_sinks: Dict[Callable, Callable[[bytes], None]] = {}
        self._last_blob: Optional[bytes] = None
        self._native_blob_meta: Optional[dict] = None
        self._native_publishes = 0
        self._last_native_pub = 0.0   # monotonic; periodic re-export
        self._native_refreshing = False
        # scorer replica pool (sidecarAddress as a list or namer path):
        # held separately from the wrapped self._scorer so run() can
        # start its membership watch and /model.json can report it
        self._scorer_pool = None
        self._sidecar_activity = None
        # span sink (the linker's BroadcastTracer): scorer-path spans —
        # per-request children of the originating trace plus one batch
        # span linking its constituents — flow to every tracer telemeter
        self._span_sink = None
        self._spans_recorded = self._node.counter("spans_recorded")
        # model lifecycle: checkpoint store + promotion gate + drift
        # monitor; None when the config block is absent (zero overhead)
        self._lifecycle = None
        if cfg.lifecycle is not None:
            if cfg.lifecycle.holdoutEveryBatches < 1:
                raise ValueError("lifecycle.holdoutEveryBatches must be >= 1")
            self._lifecycle = cfg.lifecycle.mk_manager(
                self._node.scope("drift"))
            model_node = self._node.scope("model")
            model_node.gauge("version", fn=lambda: float(
                self._lifecycle.serving_version or 0))
            model_node.gauge("step", fn=lambda: float(
                getattr(self._scorer, "_step", 0) or 0))
            model_node.gauge("promotions",
                             fn=lambda: float(self._lifecycle.promotions))
            model_node.gauge("rollbacks",
                             fn=lambda: float(self._lifecycle.rollbacks))
        # continuous in-plane learning: the drift-triggered distillation
        # pipeline producing per-route specialist heads; None when the
        # block is absent (zero overhead). Publishes ride the same
        # weight sinks as the global refresh, preferring delta patches.
        self.distill = None
        if cfg.distill is not None:
            self.distill = cfg.distill.mk(
                self._node.scope("distill"),
                store=(self._lifecycle.store
                       if self._lifecycle is not None else None),
                quant=cfg.nativeQuant)
            self.distill.set_publisher(self.publish_bank_update)
        # reactive control loop (score-weighted balancing / adaptive
        # admission / mesh reactor); None when the block is absent. The
        # Linker registers balancers + admission filters into it during
        # router assembly and its run() task rides alongside ours.
        self.control = None
        if cfg.control is not None:
            self.control = cfg.control.mk(
                self.board, metrics,
                drift=(self._lifecycle.drift
                       if self._lifecycle is not None else None),
                # cold-start guard: no actuation until the scorer has
                # seen (and trained on) warmupBatches batches
                ready_fn=lambda: (self._batches.value
                                  >= self.cfg.control.warmupBatches))

    @property
    def lifecycle(self):
        """The ModelLifecycleManager (None unless configured)."""
        return self._lifecycle

    def _scored_fraction(self) -> float:
        req = self._requests.value
        if req <= 0:
            return 1.0
        return min(1.0, self._scored.value / req)

    def _native_fraction(self) -> float:
        scored = self._scored.value
        if scored <= 0:
            return 0.0
        return min(1.0, self._native_scored.value / scored)

    # -- native tier: weight export + publication -------------------------
    def register_weight_sink(self, sink: Callable[[bytes], None],
                             delta_sink: Optional[Callable[[bytes], None]]
                             = None) -> None:
        """Install a native-engine publish callback (the FastPath
        controller registers ``engine.publish_weights`` here, plus
        ``engine.publish_delta`` when the engine can apply per-route
        patches). The last exported blob is replayed immediately, so
        registration order against the startup publish does not
        matter — a late engine starts from the full bank and is then
        eligible for deltas (its generation matches)."""
        self._weight_sinks.append(sink)
        if delta_sink is not None:
            self._delta_sinks[sink] = delta_sink
        if self._last_blob is not None:
            self._publish_blob_to(sink, self._last_blob)

    def unregister_weight_sink(self, sink: Callable[[bytes], None]) -> None:
        """Remove an engine's publish callback (the controller calls
        this from close(): a later promote must not call into a freed
        native engine)."""
        try:
            self._weight_sinks.remove(sink)
        except ValueError:
            pass
        self._delta_sinks.pop(sink, None)

    def publish_bank_update(self, full: Optional[bytes],
                            delta: Optional[bytes] = None) -> bool:
        """Ship a specialist-bank update to every registered engine:
        the delta patch where a sink can take it (generation-fenced in
        the engine; a rejection falls back to the full bank, which
        re-fences the engine for future deltas), the full blob
        otherwise. Returns True when at least one sink took the delta
        path. Called by the DistillationPipeline under its lock."""
        from linkerd_tpu.lifecycle.export import blob_meta
        used_delta = False
        if full is not None:
            self._last_blob = full
            self._native_blob_meta = blob_meta(full)
            self._native_publishes += 1
            self._last_native_pub = time.monotonic()
        for sink in list(self._weight_sinks):
            dsink = self._delta_sinks.get(sink)
            if delta is not None and dsink is not None:
                try:
                    dsink(delta)
                    used_delta = True
                    continue
                except Exception:  # noqa: BLE001 — a fence-rejected
                    # patch (engine restarted on an older generation)
                    # falls back to the full bank below
                    log.warning("native delta publish rejected; "
                                "falling back to full bank",
                                exc_info=True)
            if full is not None:
                self._publish_blob_to(sink, full)
        return used_delta

    def _publish_blob_to(self, sink, blob: bytes) -> None:
        try:
            sink(blob)
        except Exception:  # noqa: BLE001 — a rejecting engine must not
            # take down the telemeter; the JAX tier keeps scoring
            log.exception("native weight publish failed")

    async def refresh_native_weights(self, scorer: Optional[Scorer] = None,
                                     version: Optional[int] = None) -> bool:
        """Export the serving model as a native weight blob and publish
        it to every registered engine (double-buffered hot-swap in the
        slab — the data plane never pauses). Called at startup and after
        every lifecycle promote/rollback; also admin-invocable via the
        lifecycle cycle. Returns True when a blob went out."""
        if self.cfg.nativeTier != "primary":
            return False
        scorer = scorer or self._ensure_scorer()
        snap_fn = getattr(scorer, "snapshot", None)
        if snap_fn is None or asyncio.iscoroutinefunction(snap_fn):
            # no host-side snapshot surface (stub scorer, sidecar-primary
            # wiring): the native tier stays off, rows fall back to JAX
            return False
        from linkerd_tpu.lifecycle.export import export_weight_blob
        try:
            snap = await asyncio.to_thread(snap_fn)  # l5d: ignore[jax-hotpath] — weight export is a fire-and-forget task on the nativeRefreshS (>=30s) cadence, never a per-batch hop; the device readback must NOT run on the event loop
            if version is None:
                version = (self._lifecycle.serving_version
                           if self._lifecycle is not None else None)
            if version is None:
                version = int(getattr(scorer, "_step", 0) or 0)
            if self.distill is not None:
                # base model changed: export the FULL bank (new base +
                # every promoted head, generation bumped) so a promote
                # never wipes the specialists off the engines. Export
                # AND sink fan-out stay under the pipeline lock: a
                # retrain's delta landing between them would otherwise
                # be clobbered by this (older-generation) full blob.
                async with self.distill.lock:
                    # quant=None: the pipeline's own quant governs (its
                    # distill.quant override, else nativeQuant) — the
                    # recurring full-bank exports must match the delta
                    # publishes byte-encoding for byte-encoding
                    blob = await asyncio.to_thread(  # l5d: ignore[jax-hotpath] — same cadence-bounded export task as below, off-loop
                        self.distill.export_full, snap, int(version),
                        None)
                    self._finish_full_publish(blob, int(version))
                return True
            blob = await asyncio.to_thread(  # l5d: ignore[jax-hotpath] — same cadence-bounded export task: flattening a few-thousand-param snapshot off-loop, not a dispatch-path hop
                export_weight_blob, snap, int(version),
                self.cfg.nativeQuant)
        except Exception:  # noqa: BLE001 — export failures must never
            # stop scoring; the JAX tier serves everything meanwhile
            log.exception("native weight export failed")
            return False
        self._finish_full_publish(blob, int(version))
        return True

    def _finish_full_publish(self, blob: bytes, version: int) -> None:
        """Bookkeeping + sink fan-out for a full blob/bank export (sync
        so the distill path can hold its lock across it)."""
        from linkerd_tpu.lifecycle.export import blob_meta
        self._last_blob = blob
        self._native_blob_meta = blob_meta(blob)
        self._native_publishes += 1
        self._last_native_pub = time.monotonic()
        if (self._lifecycle is not None
                and version == self._lifecycle.serving_version):
            # the blob rides the checkpoint manifest: the serving
            # version's entry records exactly which CRC'd bits went to
            # the engines (lineage from training state to data plane)
            try:
                self._lifecycle.store.record_native_blob(
                    int(version), self._native_blob_meta)
            except Exception:  # noqa: BLE001 — lineage annotation must
                log.exception("native blob manifest record failed")
        for sink in list(self._weight_sinks):
            self._publish_blob_to(sink, blob)

    def _maybe_refresh_native_weights(self, scorer: Scorer) -> None:
        """Periodic re-export of the ONLINE-trained model to the
        engines when no lifecycle manages promotes — without this the
        native tier would serve the startup init blob forever while
        training improves only the JAX model. Fire-and-forget with a
        reentrancy guard; with a lifecycle configured, promote/rollback
        republishes bound the staleness instead (and keep the manifest
        lineage exact)."""
        if (self.cfg.nativeTier != "primary"
                or self._lifecycle is not None
                or not self.cfg.nativeRefreshS
                or not self._weight_sinks
                or self._native_refreshing
                or time.monotonic() - self._last_native_pub
                < self.cfg.nativeRefreshS):
            return
        self._native_refreshing = True

        async def go() -> None:
            try:
                await self.refresh_native_weights(scorer)
            finally:
                # rate-limit retries on export failure too
                self._last_native_pub = time.monotonic()
                self._native_refreshing = False

        from linkerd_tpu.core.tasks import monitor
        monitor(asyncio.create_task(go(), name="native-weight-refresh"),
                what="native-weight-refresh")

    def _maybe_distill(self, scorer: Scorer) -> None:
        """Kick one drift-triggered specialist retrain when a route is
        pending — fire-and-forget with the pipeline's own reentrancy
        guard (one retrain at a time; a second trigger waits for the
        next batch). Fine-tune + shadow-eval run off-loop inside the
        pipeline; the drain path only pays the trigger scan."""
        if self.distill is None or self.distill.busy:
            return
        snap_fn = getattr(scorer, "snapshot", None)
        if snap_fn is None or asyncio.iscoroutinefunction(snap_fn):
            return  # no host snapshot surface: nothing to distill from
        if self.distill.pending_route() is None:
            return
        base_version = (self._lifecycle.serving_version
                        if self._lifecycle is not None else None)

        async def go() -> None:
            try:
                await self.distill.run_once(scorer,
                                            base_version=base_version)
            except Exception:  # noqa: BLE001 — a failed retrain must
                # never stop scoring; the route keeps its serving head
                log.exception("distillation cycle failed")

        from linkerd_tpu.core.tasks import monitor
        monitor(asyncio.create_task(go(), name="distill-retrain"),
                what="distill-retrain")

    def native_tier_state(self) -> dict:
        """The /model.json + /control.json native-tier block: what blob
        the engines serve (version/CRC), how often it swapped, and the
        native-vs-JAX scored split."""
        scored = self._scored.value
        nat = self._native_scored.value
        return {
            "mode": self.cfg.nativeTier,
            "quant": self.cfg.nativeQuant,
            "blob": self._native_blob_meta,
            "publishes": self._native_publishes,
            "engines": len(self._weight_sinks),
            "native_scored_total": nat,
            "jax_scored_total": scored - nat,
            "native_scored_fraction": (round(nat / scored, 6)
                                       if scored else 0.0),
        }

    # -- stack tap --------------------------------------------------------
    def recorder(self) -> FeatureRecorder:
        return FeatureRecorder(self.ring, on_record=self._note_request)

    def _note_request(self) -> None:
        self._requests.incr()
        self._wake.set()

    # -- native fastpath feed ---------------------------------------------
    def set_native_route_resolver(self, fn: Callable[[int], str]) -> None:
        """Install the FastPathController's route_id -> dst-path mapping
        (consulted once per unique route, cached)."""
        self._native_featurizer.resolver = fn

    def native_committed(self, rows: int, dropped: int = 0) -> None:
        """The controller drained ``rows`` engine rows into
        ``native_ring`` and shed ``dropped`` more under backpressure:
        BOTH count toward requests_total (a shed row entered the
        scoring path and was not scored — the scored fraction must
        report < 1.0 under overload, not hide the shed), then wake the
        batcher."""
        if rows > 0 or dropped > 0:
            self._requests.incr(rows + dropped)
        if rows > 0:
            self._wake.set()

    # with a span sink installed, 1-in-N batches pay the instrumented
    # two-barrier timing path; the other N-1 keep the line-rate ring
    # and span tags reuse the last sampled decomposition
    TIMING_SAMPLE_EVERY = 16

    def set_tracer(self, tracer) -> None:
        """Install the linker's span sink (called after telemeter
        assembly — the broadcast tracer is built FROM telemeters, so it
        cannot exist when this one is constructed). With a sink in
        place the scorer's phase-split timing pays for itself, so it is
        switched on — SAMPLED, so the serving path stays on the
        donated ring."""
        self._span_sink = tracer
        if self.control is not None and tracer is not None:
            self.control.set_tracer(tracer)
        if self._scorer is not None and tracer is not None:
            self._enable_sampled_timing(self._scorer)

    def _enable_sampled_timing(self, scorer) -> None:
        scorer.timing_enabled = True
        if hasattr(scorer, "timing_sample_every"):
            scorer.timing_sample_every = self.TIMING_SAMPLE_EVERY

    # -- Telemeter --------------------------------------------------------
    def _mk_inprocess(self) -> "InProcessScorer":
        return InProcessScorer(
            learning_rate=self.cfg.learningRate,
            recon_weight=self.cfg.reconWeight)

    def set_sidecar_activity(self, activity) -> None:
        """Install the namer lookup Activity backing a path-form
        ``sidecarAddress`` (the Linker resolves the path against its
        configured namers at assembly); the replica pool tracks it."""
        self._sidecar_activity = activity
        if self._scorer_pool is not None:
            self._scorer_pool.attach_activity(activity)

    def _mk_sidecar_client(self):
        """One pinned GrpcScorerClient, or a ScorerReplicaPool for a
        static list / namer path address (fleet/scorer_pool.py)."""
        addr = self.cfg.sidecarAddress
        from linkerd_tpu.telemetry.sidecar import GrpcScorerClient
        if addr.startswith("/"):
            from linkerd_tpu.fleet.scorer_pool import ScorerReplicaPool
            self._scorer_pool = ScorerReplicaPool()
            if self._sidecar_activity is not None:
                self._scorer_pool.attach_activity(self._sidecar_activity)
            return self._scorer_pool
        if "," in addr:
            from linkerd_tpu.fleet.scorer_pool import ScorerReplicaPool
            self._scorer_pool = ScorerReplicaPool(addr.split(","))
            return self._scorer_pool
        return GrpcScorerClient(addr)

    def _ensure_scorer(self) -> Scorer:
        if self._scorer is None:
            if self.cfg.sidecarAddress:
                from linkerd_tpu.telemetry.linerate import TieredScorer
                from linkerd_tpu.telemetry.resilience import (
                    CircuitBreaker, ResilientScorer,
                )
                # the breaker + per-call deadline wrap OUTSIDE the
                # client's own (compile-aware) gRPC deadlines: a hung
                # sidecar costs one bounded call, then fails fast
                resilient = ResilientScorer(
                    self._mk_sidecar_client(),
                    call_timeout_s=self.cfg.scoreTimeoutMs / 1e3,
                    breaker=CircuitBreaker(
                        failures=self.cfg.breakerFailures,
                        min_backoff_s=self.cfg.breakerMinBackoffMs / 1e3,
                        max_backoff_s=self.cfg.breakerMaxBackoffMs / 1e3))
                if self.cfg.sidecarTier == "primary":
                    self._scorer = resilient
                else:
                    # line-rate default: in-process primary, sidecar
                    # DEMOTED to the fallback tier behind the breaker
                    try:
                        primary = self._mk_inprocess()
                    except Exception as e:  # noqa: BLE001 — no local
                        # device/toolchain: the sidecar carries the load
                        log.warning("in-process scorer unavailable (%r); "
                                    "sidecar serves as the only tier", e)
                        self._scorer = resilient
                    else:
                        self._scorer = TieredScorer(primary, resilient)
            else:
                self._scorer = self._mk_inprocess()
            if self._span_sink is not None:
                # spans consume the decomposition: turn on phase-split
                # timing (a no-op attribute on backends without it),
                # sampled so the line-rate path keeps the ring
                self._enable_sampled_timing(self._scorer)
        return self._scorer

    def _set_degraded(self, degraded: bool) -> None:
        self._degraded.set(1.0 if degraded else 0.0)
        self.board.degraded = degraded

    async def run(self) -> None:
        scorer = self._ensure_scorer()
        if self._scorer_pool is not None:
            # begin tracking announced scorer replicas (namer path mode;
            # a no-op for static replica lists)
            self._scorer_pool.start_watch()
        lc_cfg = self.cfg.lifecycle
        if self._lifecycle is not None and lc_cfg.restoreOnStart:
            # survive restarts: pull the last-good model before scoring
            try:
                restored = await self._lifecycle.bootstrap(scorer)
                if restored is not None:
                    log.info("anomaly model restored from checkpoint v%d",
                             restored)
            except Exception:  # noqa: BLE001 — a bad store must not
                log.exception("checkpoint bootstrap failed; "
                              "serving from fresh init")
        # initial native publish: the engines score in-data-plane from
        # the first request (fresh-init weights if nothing restored;
        # promotions republish as the model improves)
        await self.refresh_native_weights(scorer)
        control_task = None
        if self.control is not None:
            from linkerd_tpu.core.tasks import monitor
            control_task = asyncio.create_task(
                self.control.run(), name="control-loop")
            monitor(control_task, what="control-loop")
        try:
            if self.cfg.lineRate:
                await self._line_rate_loop(scorer)
            else:
                await self._interval_loop(scorer)
        except asyncio.CancelledError:
            pass
        finally:
            if control_task is not None:
                control_task.cancel()
                await asyncio.gather(control_task, return_exceptions=True)
            if self.control is not None and self.control.fleet is not None:
                # the exchange's gossip/store HTTP clients die with the
                # drain loop (nothing else awaits the control loop's
                # teardown; the reactor's client keeps its historical
                # process-lifetime scope)
                await self.control.fleet.aclose()

    async def _maybe_lifecycle(self, last_cycle: float) -> float:
        lc_cfg = self.cfg.lifecycle
        if (self._lifecycle is not None and lc_cfg.checkpointEveryS > 0
                and time.monotonic() - last_cycle
                >= lc_cfg.checkpointEveryS):
            last_cycle = time.monotonic()
            await self.lifecycle_cycle()
        return last_cycle

    async def _interval_loop(self, scorer: Scorer) -> None:
        """Legacy polling drain (lineRate: false): one burst per
        intervalMs tick; rows arriving between ticks wait a full
        interval."""
        interval = self.cfg.intervalMs / 1e3
        last_cycle = time.monotonic()
        while not self._stop.is_set():
            await asyncio.sleep(interval)
            try:
                await self._drain_burst(scorer)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — the drain loop must
                # outlive any scoring failure; drain_once already
                # downgraded scorer faults, so this is a last resort
                log.exception("anomaly drain failed; continuing")
            last_cycle = await self._maybe_lifecycle(last_cycle)

    async def _line_rate_loop(self, scorer: Scorer) -> None:
        """Adaptive micro-batcher (the default): dispatch when maxBatch
        rows are pending OR the oldest pending row has lingered
        ``maxLingerMs``. Up to ``scoreConcurrency`` batches stay in
        flight so the staging ring double-buffers — host→device of
        batch N overlaps device compute of batch N-1 — while the
        recorder path stays O(1) (it only sets the wake event)."""
        from linkerd_tpu.core.tasks import monitor
        linger = max(self.cfg.maxLingerMs, 0.0) / 1e3
        tick = max(linger / 4, 2e-4)
        sem = asyncio.Semaphore(self.cfg.scoreConcurrency)
        inflight: set = set()
        last_cycle = time.monotonic()
        try:
            while not self._stop.is_set():
                if not self._pending_rows():
                    self._wake.clear()
                    if not self._pending_rows():  # recheck: append raced
                        # asyncio.wait, NOT wait_for: 3.10's wait_for
                        # swallows a cancel() that lands on the same
                        # tick the wake future completes, which would
                        # leave this loop running forever after the
                        # owner cancelled it
                        waiter = asyncio.ensure_future(self._wake.wait())
                        try:
                            await asyncio.wait((waiter,), timeout=0.05)
                        finally:
                            waiter.cancel()
                        if not self._pending_rows():
                            last_cycle = await self._maybe_lifecycle(
                                last_cycle)
                            continue
                # linger: give the batch up to maxLingerMs to fill
                t0 = time.monotonic()
                while (self._pending_rows() < self.cfg.maxBatch
                       and time.monotonic() - t0 < linger
                       and not self._stop.is_set()):
                    await asyncio.sleep(tick)
                batch = self._take_batch()
                if batch is None:
                    continue
                await sem.acquire()
                task = asyncio.create_task(
                    self._score_and_publish(scorer, batch),
                    name="anomaly-score-batch")
                task.add_done_callback(lambda _t: sem.release())
                inflight.add(task)
                task.add_done_callback(inflight.discard)
                monitor(task, what="anomaly-score-batch")
                last_cycle = await self._maybe_lifecycle(last_cycle)
        finally:
            for t in list(inflight):
                t.cancel()
            if inflight:
                await asyncio.gather(*inflight, return_exceptions=True)

    def _pending_rows(self) -> int:
        return len(self.ring) + len(self.native_ring)

    async def lifecycle_cycle(self) -> Optional[dict]:
        """One checkpoint/shadow-eval/promote-or-rollback pass (the
        namerd-style periodic maintenance task; also admin-invocable)."""
        if self._lifecycle is None:
            return None
        try:
            outcome = await self._lifecycle.run_cycle(self._ensure_scorer())
            log.info("model lifecycle cycle: %s",
                     outcome.get("action", "?"))
            if outcome.get("action") in ("promoted", "rolled_back"):
                # the serving model changed (hot-swap): the native tier
                # must follow, or the engines keep scoring the old one
                await self.refresh_native_weights(
                    version=self._lifecycle.serving_version)
                # fleet model coordination: fan the promoted model out
                # to every announced scorer replica (Snapshot/Restore
                # RPCs) so fleet fallback scorers serve the same
                # generation as the in-plane bank
                self._maybe_push_fleet_model()
            return outcome
        except Exception:  # noqa: BLE001 — lifecycle failures must never
            log.exception("model lifecycle cycle failed")  # stop scoring
            return None

    def _maybe_push_fleet_model(self) -> None:
        """Fire-and-forget fleet model push: the serving checkpoint to
        every scorer replica in the pool via the Snapshot/Restore
        sidecar RPCs. Skipped when no pool (pinned/in-process-only
        wiring) or no promoted checkpoint exists. A slow replica costs
        one bounded background task, never the lifecycle cycle."""
        if self._scorer_pool is None or self._lifecycle is None:
            return
        version = self._lifecycle.serving_version
        if version is None:
            return

        async def go() -> None:
            try:
                _, snap = await asyncio.to_thread(
                    self._lifecycle.store.load, version)
                n = await asyncio.wait_for(
                    self._scorer_pool.broadcast_restore(snap), 30.0)
                if n:
                    self._fleet_model_pushes.incr(n)
                    log.info("fleet model push: v%s restored on %d "
                             "scorer replica(s)", version, n)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — the fleet push is
                # best-effort; replicas converge on a later promote
                log.exception("fleet model push failed")

        from linkerd_tpu.core.tasks import monitor
        monitor(asyncio.create_task(go(), name="fleet-model-push"),
                what="fleet-model-push")

    async def _drain_burst(self, scorer: Scorer,
                           max_batches: Optional[int] = None) -> int:
        """Catch-up drain: under backlog, score several micro-batches
        per wake instead of one per interval — one full batch per 50ms
        caps at ~20k rows/s, below the proxy's saturation, and the ring
        would otherwise shed its OLDEST rows under sustained load."""
        if max_batches is None:
            max_batches = self.cfg.maxBatchesPerWake
        total = 0
        for _ in range(max_batches):
            n = await self.drain_once(scorer)
            total += n
            if n < self.cfg.maxBatch:
                break  # ring drained below one full batch
        return total

    async def drain_once(self, scorer: Optional[Scorer] = None) -> int:
        """Drain one micro-batch through the scorer; returns rows scored."""
        scorer = scorer or self._ensure_scorer()
        batch = self._take_batch()
        if batch is None:
            return 0
        return await self._score_and_publish(scorer, batch)

    def _take_batch(self) -> Optional[dict]:
        """Assemble one micro-batch: Python-path ring items plus a
        zero-copy block of native engine rows. Featurization happens
        HERE, synchronously — the native block is a view into ring
        memory that is only valid until the caller's next await.

        Engine rows that arrived PRE-SCORED (the in-data-plane native
        tier; scored flag set) are split out of the JAX dispatch: their
        features still feed training/drift/holdout, but the device
        never re-scores them. ``x`` holds only the rows that NEED a
        JAX score (Python-path + unscored native rows)."""
        from linkerd_tpu.telemetry.linerate import (
            NATIVE_COL_SCORE, NATIVE_COL_SCORED,
        )
        n_py = min(len(self.ring), self.cfg.maxBatch)
        # ring items are (fv, label[, trace, enqueued_at, endpoint]) —
        # external producers (benchmarks, fault harnesses) still append
        # 2-tuples
        items = [(it + (None, None, None, None))[:5]
                 for it in (self.ring.popleft() for _ in range(n_py))]
        nat_block = self.native_ring.consume(self.cfg.maxBatch - n_py)
        k = len(nat_block)
        if not items and k == 0:
            return None
        fvs = [it[0] for it in items]
        x_py = featurize_batch(fvs)
        nat_inv: Optional[np.ndarray] = None
        nat_dsts: List[str] = []
        nat_scored: Optional[dict] = None
        x_nat: Optional[np.ndarray] = None
        if k:
            # encode the WHOLE block in one pass — the featurizer's
            # per-route drift EWMA must advance exactly once per drain,
            # in arrival order (two subset passes would double-step the
            # baseline and compute the later subset's drift against an
            # already-advanced EWMA) — then split the ENCODED rows by
            # tier. Boolean fancy indexing copies, safe across awaits.
            x_enc, inv_all, dsts = \
                self._native_featurizer.encode_block(nat_block)
            is_scored = nat_block[:, NATIVE_COL_SCORED] > 0.5
            if is_scored.any():
                all_sc = bool(is_scored.all())
                nat_scored = {
                    "x": x_enc if all_sc else x_enc[is_scored],
                    "scores": np.ascontiguousarray(
                        nat_block[is_scored, NATIVE_COL_SCORE],
                        np.float32),
                    "inv": inv_all if all_sc else inv_all[is_scored],
                    "dsts": dsts,
                }
            un = ~is_scored
            if un.any():
                all_un = bool(un.all())
                x_nat = x_enc if all_un else x_enc[un]
                nat_inv = inv_all if all_un else inv_all[un]
                nat_dsts = dsts
        k_un = 0 if x_nat is None else len(x_nat)
        labels = np.array(
            [0.0 if it[1] is None else float(it[1]) for it in items]
            + [0.0] * k_un, dtype=np.float32)
        mask = np.array(
            [0.0 if it[1] is None else 1.0 for it in items]
            + [0.0] * k_un, dtype=np.float32)
        if x_nat is not None:
            x = np.concatenate([x_py, x_nat]) if n_py else x_nat
        else:
            x = x_py
        return {"items": items, "fvs": fvs, "x": x, "labels": labels,
                "mask": mask, "n_py": n_py, "nat_inv": nat_inv,
                "nat_dsts": nat_dsts, "nat_scored": nat_scored}

    async def _score_and_publish(self, scorer: Scorer, b: dict) -> int:
        """Score one assembled batch and publish every downstream
        effect: degraded-mode accounting, scorer spans, lifecycle
        drift/holdout, per-dst board updates, training cadence.

        Rows the engines already scored in-data-plane (``nat_scored``)
        skip the JAX dispatch entirely: their scores publish straight
        to the board, their features still feed drift/holdout/training
        — the RingDispatcher stays the training and fallback tier."""
        x, items, n_py = b["x"], b["items"], b["n_py"]
        ns = b.get("nat_scored")
        k_ns = 0 if ns is None else len(ns["x"])
        n_jax = len(x)
        t_drain = time.monotonic()
        ts_us = int(time.time() * 1e6)
        scores: Optional[np.ndarray] = None
        jax_failed = False
        if n_jax:
            try:
                scores = await scorer.score(x)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — graceful degradation:
                # scoring is best-effort; a dead/hung scorer drops the
                # JAX half of the batch (requests were never blocked on
                # it) and flips degraded mode — engine-scored rows still
                # publish below: the native tier does not depend on the
                # device being healthy
                self._score_failures.incr()
                self._dropped_batches.incr()
                jax_failed = True
                if not self.board.degraded:
                    log.warning(
                        "anomaly scorer degraded (scoring paused, data "
                        "plane unaffected): %r", e)
                self._set_degraded(True)
                if k_ns == 0:
                    return 0
            else:
                scores = np.asarray(scores)  # l5d: ignore[jax-hotpath] — scorers return host arrays (the drainer already did readback); this is a no-op view
                if self.board.degraded:
                    log.info("anomaly scorer recovered; scoring resumed")
                self._set_degraded(False)
        n_scored = (n_jax if scores is not None else 0) + k_ns
        self._scored.incr(n_scored)
        if k_ns:
            self._native_scored.incr(k_ns)
        if not jax_failed:
            # a failed JAX dispatch was already counted dropped; the
            # native half still publishes below but the batch must not
            # ALSO count completed, nor export scorer spans for the
            # Python items whose scoring was just dropped
            self._batches.incr()
            if self._span_sink is not None:
                self._record_scorer_spans(
                    items, t_drain, ts_us,
                    int((time.monotonic() - t_drain) * 1e6), scorer)
        # every row with a score — JAX-scored and engine-scored alike —
        # feeds drift/holdout; labels/mask for the native rows are all
        # zeros (engine rows are never fault-labeled)
        x_all, labels_all, mask_all, scores_all = x, b["labels"], \
            b["mask"], scores
        if k_ns:
            if scores is not None and n_jax:
                x_all = np.concatenate([x, ns["x"]])
                scores_all = np.concatenate(
                    [scores, ns["scores"]])
                labels_all = np.concatenate(
                    [b["labels"], np.zeros(k_ns, np.float32)])
                mask_all = np.concatenate(
                    [b["mask"], np.zeros(k_ns, np.float32)])
            else:
                x_all, scores_all = ns["x"], ns["scores"]
                labels_all = np.zeros(k_ns, np.float32)
                mask_all = np.zeros(k_ns, np.float32)
        holdout = False
        if self._lifecycle is not None and scores_all is not None:
            # drift sees every batch (read-only); the replay window only
            # takes HOLDOUT batches, which are then excluded from
            # training below — a shadow-eval set the candidate trained on
            # (same rows AND same labels) could not catch a poisoned
            # training stream, because the poisoned candidate evaluates
            # best on its own poison
            self._lifecycle.drift.observe(x_all, scores_all)
            holdout = self._batch_i % self.cfg.lifecycle.holdoutEveryBatches == 0
            if holdout:
                self._lifecycle.replay.add_batch(x_all, labels_all,
                                                 mask_all)
        if scores is not None:
            self.board.update_batch([fv.dst_path for fv in b["fvs"]],
                                    scores[:n_py],
                                    endpoints=[it[4] for it in items])
            if b["nat_inv"] is not None and b["nat_dsts"]:
                # native rows: per-ROUTE means, vectorized (update_batch
                # averages per dst anyway, so feeding group means is
                # equivalent to feeding every row)
                self._publish_route_means(
                    b["nat_dsts"], b["nat_inv"], scores[n_py:])
        self._publish_native_batch(ns)
        if self.distill is not None and scores_all is not None \
                and len(scores_all):
            # per-route drift + replay feed: host-only bookkeeping,
            # mirroring exactly how x_all was assembled (python rows,
            # then JAX-scored native rows, then engine-scored rows)
            dsts_all: List[str] = []
            if scores is not None:
                dsts_all.extend(fv.dst_path for fv in b["fvs"])
                if b["nat_inv"] is not None and b["nat_dsts"]:
                    nd = b["nat_dsts"]
                    dsts_all.extend(nd[int(i)] for i in b["nat_inv"])
            if k_ns:
                nsd = ns["dsts"]
                dsts_all.extend(nsd[int(i)] for i in ns["inv"])
            if len(dsts_all) == len(scores_all):
                self.distill.observe_batch(dsts_all, x_all, scores_all,
                                           labels_all, mask_all)
        self._publish_gauges()
        self._batch_i += 1
        if (not holdout and self.cfg.trainEveryBatches
                and not jax_failed
                and self._batch_i % self.cfg.trainEveryBatches == 0):
            try:
                # serialized: concurrent line-rate batches must not
                # interleave their fit steps. Engine-scored rows train
                # too — the JAX model is the training tier for ALL
                # traffic, or it would drift away from the distribution
                # the native tier actually serves
                async with self._fit_lock:
                    loss = await scorer.fit(x_all, labels_all, mask_all)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — training is optional;
                # a fit failure (it still feeds the shared breaker) must
                # not take down scoring
                self._score_failures.incr()
                log.debug("online fit skipped (scorer failure): %r", e)
            else:
                self._train_loss.set(loss)
                self._maybe_refresh_native_weights(scorer)
        self._maybe_distill(scorer)
        return n_scored

    def _publish_native_batch(self, ns: Optional[dict]) -> None:
        """Publish engine-scored rows to the board: per-route score
        means, no device work — the scores were computed in-data-plane
        and this hop is pure host arithmetic (a jax-hotpath root: a
        device seam creeping in here would put the old per-batch
        latency right back on the native tier's publish path)."""
        if ns is None or not ns["dsts"]:
            return
        self._publish_route_means(ns["dsts"], ns["inv"], ns["scores"])

    def _publish_route_means(self, dsts: List[str], inv: np.ndarray,
                             scores: np.ndarray) -> None:
        """Per-route score means onto the board. ``dsts`` is the FULL
        block's route list while ``inv`` may index only one tier's
        subset of its rows — routes with no rows here are skipped, not
        published as a spurious 0.0."""
        m = len(dsts)
        sums = np.bincount(inv, weights=scores, minlength=m)
        counts = np.bincount(inv, minlength=m)
        nz = counts > 0
        if not nz.any():
            return
        if nz.all():
            self.board.update_batch(dsts, sums / counts)
        else:
            self.board.update_batch(
                [d for d, keep in zip(dsts, nz) if keep],
                sums[nz] / counts[nz])

    # at most this many per-request scorer spans per drained batch: a
    # 1024-row batch must not turn into 1024 span records per 50ms
    MAX_SPANS_PER_BATCH = 128

    def _record_scorer_spans(self, items, t_drain: float, ts_us: int,
                             dur_us: int, scorer) -> None:
        """Scorer-path spans for one drained micro-batch: a batch span
        (own trace) that links its constituent request traces via
        annotations, plus one child span per SAMPLED originating request
        carrying the queue/device/transfer decomposition."""
        from linkerd_tpu.router.tracing import TraceId

        timing = getattr(scorer, "last_timing", None) or {}
        timing_tags = {f"scorer.{k}": (f"{v:.3f}" if isinstance(v, float)
                                       else str(v))
                       for k, v in timing.items()}
        traced = [(it[2], it[3]) for it in items
                  if it[2] is not None and it[2].sampled]
        batch = TraceId.mk_root(True)
        batch_tags = dict(timing_tags)
        batch_tags["scorer.batch_size"] = str(len(items))
        batch_tags["scorer.linked"] = str(len(traced))
        self._span_sink.record({
            "traceId": f"{batch.trace_id:032x}",
            "id": f"{batch.span_id:016x}",
            "parentId": None,
            "kind": "CONSUMER",
            "name": "scorer.batch",
            "timestamp": ts_us,
            "duration": dur_us,
            "localEndpoint": {"serviceName": "scorer"},
            # constituent request spans, linked (zipkin has no otel-style
            # span links; annotations are the v2-JSON-native equivalent)
            "annotations": [
                {"timestamp": ts_us,
                 "value": f"link:{t.trace_id:032x}:{t.span_id:016x}"}
                for t, _ in traced[:self.MAX_SPANS_PER_BATCH]],
            "tags": batch_tags,
        })
        self._spans_recorded.incr()
        for trace, enq in traced[:self.MAX_SPANS_PER_BATCH]:
            child = trace.child()
            tags = dict(timing_tags)
            tags["scorer.batch_span"] = f"{batch.span_id:016x}"
            if enq is not None:
                # ring wait: enqueue (request completion) -> drain start
                tags["scorer.queue_ms"] = f"{(t_drain - enq) * 1e3:.3f}"
            self._span_sink.record({
                "traceId": f"{child.trace_id:032x}",
                "id": f"{child.span_id:016x}",
                "parentId": f"{child.parent_id:016x}",
                "kind": "CONSUMER",
                "name": "scorer",
                "timestamp": ts_us,
                "duration": dur_us,
                "localEndpoint": {"serviceName": "scorer"},
                "tags": tags,
            })
            self._spans_recorded.incr()

    def _publish_gauges(self) -> None:
        for dst, score in self.board.scores.sample().items():
            key = dst.lstrip("/").replace("/", ".") or "root"
            g = self._gauges.get(key)
            if g is None:
                g = self._node.scope("dst").gauge(key)
                self._gauges[key] = g
            g.set(score)

    def admin_handlers(self):
        from linkerd_tpu.admin.server import json_response

        async def anomaly_json(req: Request) -> Response:
            return json_response({
                "scores": self.board.scores.sample(),
                "threshold": self.cfg.scoreThreshold,
                "ring_depth": len(self.ring),
            })

        async def model_json(req: Request) -> Response:
            return json_response(self.model_state())

        handlers = [("/anomaly.json", anomaly_json),
                    ("/model.json", model_json)]
        if self.control is not None:
            async def control_json(req: Request) -> Response:
                st = self.control.status()
                # the control loop actuates on scores; surface WHICH
                # tier produced them (and which model version/CRC the
                # engines are serving) next to the actuation state
                st["native_tier"] = self.native_tier_state()
                return json_response(st)

            handlers.append(("/control.json", control_json))
            if self.control.fleet is not None:
                # /fleet.json + the gossip push/pull endpoint ride the
                # admin server alongside the rest of the control surface
                from linkerd_tpu.fleet.gossip import fleet_admin_handlers
                handlers.extend(fleet_admin_handlers(self.control.fleet))
        return handlers

    def model_state(self) -> dict:
        """Model-lifecycle state for /model.json: version, step, last
        promotion/rollback, drift gauges, store inventory."""
        out: dict = {
            "lifecycle_enabled": self._lifecycle is not None,
            "live_step": getattr(self._scorer, "_step", None),
            "scorer": type(self._scorer).__name__
            if self._scorer is not None else None,
            "degraded": bool(self.board.degraded),
            # "100% scored" is measured, not asserted
            "requests_total": self._requests.value,
            "scored_total": self._scored.value,
            "scored_fraction": round(self._scored_fraction(), 6),
            "line_rate": bool(self.cfg.lineRate),
            # in-data-plane tier: blob version/CRC, publish (swap)
            # count, native-vs-JAX scored split
            "native_tier": self.native_tier_state(),
        }
        breaker = getattr(self._scorer, "breaker", None)
        if breaker is not None:
            out["breaker"] = {
                "state": breaker.state,
                "next_probe_in_s": round(breaker.next_probe_in_s(), 3),
            }
        tier_fn = getattr(self._scorer, "tier_state", None)
        if tier_fn is not None:
            out["tiers"] = tier_fn()
        if self._scorer_pool is not None:
            out["scorer_pool"] = self._scorer_pool.status()
        if self.distill is not None:
            # the per-route bank view: generation, every specialist
            # head's lineage, live drift shifts, pending retrains
            out["distill"] = self.distill.state()
        if self._lifecycle is not None:
            out.update(self._lifecycle.status())
        return out

    def close(self) -> None:
        self._stop.set()
        if self.control is not None:
            self.control.close()
        if self._lifecycle is not None and self._scorer is not None:
            # best-effort shutdown snapshot (sync/in-process scorers
            # only): a router restart must not silently reset the model
            # to random init. Saved as a candidate — restart prefers the
            # last PROMOTED version when one exists (latest_good()).
            snap_fn = getattr(self._scorer, "snapshot", None)
            if snap_fn is not None \
                    and not asyncio.iscoroutinefunction(snap_fn):
                try:
                    self._lifecycle.store.save(
                        snap_fn(), status="candidate",
                        parent=self._lifecycle.serving_version)
                except Exception:  # noqa: BLE001 — shutdown must proceed
                    log.exception("shutdown checkpoint failed")
        if self._scorer is not None:
            self._scorer.close()


# -- score-driven failure accrual -------------------------------------------


@register("failureAccrual", "io.l5d.jaxAnomaly")
@dataclass
class AnomalyFailureAccrualConfig:
    """Failure accrual that tightens when the anomaly scorer flags the mesh:
    endpoints are marked dead after ``anomalousFailures`` consecutive
    failures while the (EWMA) anomaly level exceeds ``threshold``, else
    after ``failures`` — learned signal replacing the hand-tuned constant
    (the BASELINE.json north-star feedback loop)."""

    failures: int = 5
    anomalousFailures: int = 2
    threshold: float = 0.5

    needs_board = True

    def mk(self, board: Optional[ScoreBoard] = None):
        from linkerd_tpu.router.failure_accrual import FailureAccrualPolicy
        return AnomalyFailureAccrualPolicy(
            board or ScoreBoard(), self.failures, self.anomalousFailures,
            self.threshold)


class AnomalyFailureAccrualPolicy:
    """See AnomalyFailureAccrualConfig. Implements FailureAccrualPolicy."""

    def __init__(self, board: ScoreBoard, failures: int,
                 anomalous_failures: int, threshold: float,
                 backoffs=None):
        from linkerd_tpu.router.failure_accrual import _default_backoffs
        self.board = board
        self.failures = failures
        self.anomalous_failures = anomalous_failures
        self.threshold = threshold
        self._consecutive = 0
        self._mk_backoffs = (lambda: backoffs) if backoffs else _default_backoffs
        self._backoffs = self._mk_backoffs()

    def _anomaly_level(self) -> float:
        # staleness-decayed and degraded-aware: while the scorer path is
        # down or its scores are stale, this reads 0 and the policy
        # degrades to its reference `failures` threshold
        return self.board.anomaly_level()

    def record_success(self) -> None:
        self._consecutive = 0

    def record_failure(self):
        self._consecutive += 1
        limit = (self.anomalous_failures
                 if self._anomaly_level() >= self.threshold
                 else self.failures)
        if self._consecutive >= limit:
            return next(self._backoffs)
        return None

    def revived(self) -> None:
        self._consecutive = 0
        self._backoffs = self._mk_backoffs()
