"""Telemeter SPI.

Reference parity: telemetry/core/.../Telemeter.scala:11-15 — a telemeter
optionally provides a stats receiver (here: a MetricsTree it populates or
reads), a tracer, and a ``run()`` lifecycle. Telemeters are configured via
the ``telemeter`` registry category (``kind: io.l5d.prometheus`` etc.) and
wired by the Linker (Linker.scala:115-135).
"""

from __future__ import annotations

import abc
from typing import Any, List, Optional, Sequence, Tuple


class Tracer(abc.ABC):
    """Span sink. Records completed spans (dicts with trace/span ids,
    timestamps, annotations)."""

    @abc.abstractmethod
    def record(self, span: dict) -> None: ...

    def close(self) -> None:  # pragma: no cover - default no-op
        pass


class NullTracer(Tracer):
    def record(self, span: dict) -> None:  # pragma: no cover - no-op
        pass


class BroadcastTracer(Tracer):
    """Fan a span out to several tracers (ref: Linker.scala:152-157)."""

    def __init__(self, tracers: Sequence[Tracer]):
        self.tracers = list(tracers)

    def record(self, span: dict) -> None:
        for t in self.tracers:
            t.record(span)

    def close(self) -> None:
        for t in self.tracers:
            t.close()


class Telemeter(abc.ABC):
    """A telemetry plugin: may expose a tracer, admin handlers, and a
    background task started by ``run()``."""

    @property
    def tracer(self) -> Optional[Tracer]:
        return None

    def admin_handlers(self) -> List[Tuple[str, Any]]:
        """(url_path, handler) pairs contributed to the admin server."""
        return []

    async def run(self) -> None:
        """Long-running background work; default none."""
        return

    def close(self) -> None:
        return
