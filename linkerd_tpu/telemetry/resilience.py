"""Scorer-path graceful degradation: deadlines + circuit breaking.

The jaxAnomaly telemeter must never become a failure domain of the data
plane it protects (Taurus arXiv:2002.08987, FENIX arXiv:2507.14891): a
hung TPU sidecar must cost the drain loop one bounded call, not a wedge.
``ResilientScorer`` wraps any Scorer (in practice the gRPC sidecar
client) with

- a per-call deadline (``asyncio.wait_for``) so a black-holed sidecar
  surfaces as a bounded TimeoutError instead of an indefinite stall, and
- a circuit breaker reusing the failure-accrual probing idiom
  (router/failure_accrual.py): after ``failures`` consecutive failures
  the breaker opens and calls fail fast with ``ScorerUnavailable``;
  after each jittered backoff ONE probe call is admitted — success
  closes the breaker, failure re-opens it with a doubled (capped)
  backoff.

The telemeter maps ScorerUnavailable to degraded mode: scoring pauses
(batches drop, requests never block), ``anomaly/degraded`` flips to 1,
``ScoreBoard.degraded`` makes anomaly-aware accrual policies fall back
to their reference behavior, and the first successful probe restores
normal operation.
"""

from __future__ import annotations

import asyncio
import time
from typing import Iterator, Optional, Tuple

import numpy as np


def _jittered_backoffs(min_s: float, max_s: float) -> Iterator[float]:
    """Jittered exponential probe schedule (the failure-accrual
    _default_backoffs idiom, with configurable bounds)."""
    import random
    cur = min_s
    while True:
        yield random.uniform(cur / 2, cur)
        cur = min(max_s, cur * 2)


class ScorerUnavailable(Exception):
    """The scorer call failed or was refused by the open breaker; the
    caller should degrade (skip scoring), never block or crash."""


class CircuitBreaker:
    """closed -> open (after ``failures`` consecutive failures) ->
    half-open (one probe per backoff interval) -> closed | open.

    Concurrent in-flight failures from a single outage advance the
    consecutive count but open the breaker only once; a failed PROBE is
    what advances the backoff schedule (mirrors FailFastService)."""

    def __init__(self, failures: int = 3, min_backoff_s: float = 0.5,
                 max_backoff_s: float = 30.0, backoffs=None):
        if failures < 1:
            raise ValueError("failures must be >= 1")
        self.failures = failures
        self._mk_backoffs = ((lambda: backoffs) if backoffs is not None
                             else lambda: _jittered_backoffs(
                                 min_backoff_s, max_backoff_s))
        self._backoffs = self._mk_backoffs()
        self._consecutive = 0
        self._open_until: Optional[float] = None
        self._probing = False

    @property
    def state(self) -> str:
        if self._open_until is None:
            return "closed"
        if self._probing:
            return "half_open"
        if time.monotonic() >= self._open_until:
            return "half_open"  # a probe slot is available
        return "open"

    def next_probe_in_s(self) -> float:
        """Seconds until the next probe slot opens (0 when available or
        the breaker is closed)."""
        if self._open_until is None:
            return 0.0
        return max(0.0, self._open_until - time.monotonic())

    def acquire(self) -> Tuple[bool, bool]:
        """-> (admitted, is_probe). While open, only the single probe
        slot per backoff interval admits."""
        if self._open_until is None:
            return True, False
        if time.monotonic() >= self._open_until and not self._probing:
            self._probing = True
            return True, True
        return False, False

    def on_success(self, probe: bool) -> None:
        if probe or self._open_until is not None:
            self._open_until = None
            self._probing = False
            self._backoffs = self._mk_backoffs()
        self._consecutive = 0

    def on_failure(self, probe: bool) -> None:
        self._consecutive += 1
        if probe:
            # the failed probe advances the schedule; concurrent
            # non-probe failures from one outage must not
            self._probing = False
            self._open_until = time.monotonic() + next(self._backoffs)
        elif self._open_until is None \
                and self._consecutive >= self.failures:
            self._open_until = time.monotonic() + next(self._backoffs)

    def on_cancel(self, probe: bool) -> None:
        """Outcome unknown: release the probe slot without reviving."""
        if probe:
            self._probing = False


class ResilientScorer:
    """Wraps ``inner`` (typically GrpcScorerClient) with per-call
    deadlines and a circuit breaker. ``score``/``fit`` raise
    ScorerUnavailable on any failure or refusal; lifecycle hooks
    (snapshot/restore/swap/warmup) delegate untouched via __getattr__,
    preserving the inner hook's sync/async nature for the lifecycle
    manager's ``_call_scorer`` dispatch. Deliberately NOT a Scorer
    subclass: the base class's concrete snapshot/restore stubs would
    shadow the delegation (``__getattr__`` only fires on failed
    lookups)."""

    def __init__(self, inner, call_timeout_s: float = 2.0,
                 breaker: Optional[CircuitBreaker] = None):
        self._inner = inner
        self.call_timeout_s = call_timeout_s
        self.breaker = breaker if breaker is not None else CircuitBreaker()

    def __getattr__(self, name):
        if name == "_inner":  # guard re-entrancy before __init__ ran
            raise AttributeError(name)
        return getattr(self._inner, name)

    async def _guarded(self, what: str, coro):
        admitted, probe = self.breaker.acquire()
        if not admitted:
            coro.close()  # refused before dispatch: don't leak the coroutine
            raise ScorerUnavailable(
                f"{what}: breaker open, next probe in "
                f"{self.breaker.next_probe_in_s():.2f}s")
        try:
            rsp = await asyncio.wait_for(coro, self.call_timeout_s)
        except asyncio.CancelledError:
            self.breaker.on_cancel(probe)
            raise
        except Exception as e:  # noqa: BLE001 — degradation boundary:
            # every failure kind (deadline, transport, codec) becomes
            # the one signal the telemeter degrades on
            self.breaker.on_failure(probe)
            raise ScorerUnavailable(f"{what} failed: {e!r}") from e
        self.breaker.on_success(probe)
        return rsp

    async def score(self, x: np.ndarray) -> np.ndarray:
        return await self._guarded("score", self._inner.score(x))

    async def fit(self, x: np.ndarray, labels: np.ndarray,
                  mask: np.ndarray) -> float:
        return await self._guarded("fit", self._inner.fit(x, labels, mask))

    def close(self) -> None:
        self._inner.close()
