"""Line-rate scoring plumbing for the ``io.l5d.jaxAnomaly`` telemeter.

Three pieces that together take the score dispatch path off the
RPC/thread seam (ROADMAP item 2, the Taurus/FENIX model: in-network
inference pays only when feature extraction and dispatch live in the
data plane itself):

- ``RingDispatcher`` — persistent double-buffered device dispatch.
  Feature batches land in preallocated staging buffers (two per batch
  bucket), the jitted score step takes the device copy with
  ``donate_argnums``, and dispatch rides JAX async dispatch; a single
  background drainer thread does the blocking readback and resolves
  asyncio futures, so the event loop never blocks on the device and
  host→device transfer of batch N overlaps device compute of batch N-1.

- ``NativeFeatureRing`` — a preallocated float32 ring the native
  fastpath engines drain their per-request feature rows into directly
  (``FastPathEngine.drain_features_into`` writes C → ring memory, no
  per-row Python objects), consumed zero-copy by the micro-batcher.
  ``featurize_native_block`` turns a consumed block into model features
  with vectorized numpy ops only.

- ``TieredScorer`` — in-process primary at line rate with the gRPC
  sidecar demoted to a fallback tier behind its own breaker: a failing
  in-process path falls back to the (ResilientScorer-wrapped) sidecar
  instead of dropping batches outright.
"""

from __future__ import annotations

import asyncio
import atexit
import logging
import queue
import threading
import warnings
import weakref
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)

# On backends/shapes where XLA cannot fold the donated [B, D] input
# into the [B] output it declines the donation and warns once per
# compiled shape at lowering time. Donation is still correct (the
# buffer is freed at dispatch); the warning is expected here and only
# here, so it is suppressed around OUR step invocation rather than via
# a process-wide filter that would hide a user's own donation bugs.
_DONATION_DECLINED_MSG = "Some donated buffers were not usable"


# Every live dispatcher's drainer must be woken AND JOINED before the
# interpreter starts finalizing: a daemon thread that wakes during
# finalization is killed via pthread_exit inside C frames, which
# unwinds through noexcept C++ (CPython gh-87135 shape) and calls
# std::terminate — an rc=134 abort AFTER a green test run. The
# per-instance weakref finalizer only enqueues the sentinel; this
# atexit hook (running while the runtime is still healthy) also joins.
_LIVE_DISPATCHERS: "weakref.WeakSet" = weakref.WeakSet()


def _shutdown_drainers() -> None:
    for d in list(_LIVE_DISPATCHERS):
        try:
            d._queue.put(None)
            t = d._thread
            if t is not None and t.is_alive():
                t.join(timeout=2.0)
        except Exception:  # noqa: BLE001  # l5d: ignore[swallowed-exception] — interpreter-exit hook: logging may itself be torn down; remaining dispatchers still get their sentinel
            pass


atexit.register(_shutdown_drainers)


# -- donated double-buffered device dispatch ---------------------------------


class _Slot:
    """One staging buffer of a double-buffered bucket ring. ``busy``
    from dispatch until the drainer finishes readback of the batch
    dispatched from it — readback done implies the whole chain
    (host→device copy included) is done, so the staging memory is safe
    to refill. All fields are touched under the dispatcher lock."""

    __slots__ = ("staging", "busy", "bucket")

    def __init__(self, staging: np.ndarray, bucket: int):
        self.staging = staging
        self.bucket = bucket
        self.busy = False


class RingDispatcher:
    """Persistent double-buffered score dispatch.

    ``dispatch(x, step)`` copies ``x`` (float32 [n, D]) into a
    preallocated staging buffer for the padded batch bucket, hands the
    buffer to ``step`` (which places it on device and invokes the
    DONATING jitted score step — async dispatch, no barrier), and
    returns an awaitable resolved by the background drainer thread once
    readback completes. Two slots per bucket: batch N fills slot B
    while slot A's transfer+compute+readback chain is in flight.

    Donation rules: ``step`` receives the staging buffer and must hand
    its device copy to a step compiled with ``donate_argnums`` —
    neither the dispatcher nor any caller may re-read the device array
    after dispatch (JAX deletes donated buffers; re-reads raise).
    Staging rows beyond ``n`` may hold stale rows from earlier batches;
    the model scores rows independently and the result is sliced to
    ``n``, so stale padding never contaminates live scores.
    """

    def __init__(self, in_dim: int, bucket_fn: Callable[[int], int],
                 depth: int = 2):
        self.in_dim = in_dim
        self._bucket_fn = bucket_fn
        self.depth = max(1, depth)
        self._slots: Dict[int, List[_Slot]] = {}
        self._waiters: List[Tuple[int, asyncio.AbstractEventLoop,
                                  asyncio.Future]] = []
        self._lock = threading.Lock()
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # a GC'd dispatcher must not leak its drainer: the sentinel
        # unblocks queue.get and the thread exits
        self._finalizer = weakref.finalize(self, self._queue.put, None)
        _LIVE_DISPATCHERS.add(self)

    # -- drainer ----------------------------------------------------------
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._drain_loop, name="l5d-score-drainer",
                daemon=True)
            self._thread.start()

    def _drain_loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            result, n, loop, fut, slot = item
            out: Optional[np.ndarray] = None
            err: Optional[BaseException] = None
            try:
                # the ONLY blocking readback on the score path, and it
                # blocks this drainer thread, never the event loop
                out = np.asarray(result, dtype=np.float32)[:n].copy()
            except BaseException as e:  # noqa: BLE001 — surfaced via fut
                err = e
            self._release(slot)
            try:
                if err is None:
                    loop.call_soon_threadsafe(self._resolve, fut, out)
                else:
                    loop.call_soon_threadsafe(self._reject, fut, err)
            except RuntimeError:
                pass  # loop already closed; result is moot

    @staticmethod
    def _resolve(fut: asyncio.Future, out: np.ndarray) -> None:
        if not fut.done():
            fut.set_result(out)

    @staticmethod
    def _reject(fut: asyncio.Future, err: BaseException) -> None:
        if not fut.done():
            fut.set_exception(err)

    # -- slot ring --------------------------------------------------------
    def _acquire_nowait(self, bucket: int) -> Optional[_Slot]:
        slots = self._slots.get(bucket)
        if slots is None:
            slots = [_Slot(np.zeros((bucket, self.in_dim), np.float32),
                           bucket)
                     for _ in range(self.depth)]
            self._slots[bucket] = slots
        for s in slots:
            if not s.busy:
                s.busy = True
                return s
        return None

    async def _acquire(self, bucket: int) -> _Slot:
        loop = asyncio.get_running_loop()
        while True:
            waiter: Optional[asyncio.Future] = None
            with self._lock:
                slot = self._acquire_nowait(bucket)
                if slot is None:
                    waiter = loop.create_future()
                    self._waiters.append((bucket, loop, waiter))
            if slot is not None:
                return slot
            await waiter  # backpressure: both slots in flight

    def _release(self, slot: _Slot) -> None:
        """Free ``slot`` and wake the oldest waiter for the SAME bucket
        (a freed bucket-A slot cannot admit a bucket-B dispatch)."""
        wake: List[Tuple[asyncio.AbstractEventLoop, asyncio.Future]] = []
        with self._lock:
            slot.busy = False
            still = []
            for bucket, loop, fut in self._waiters:
                if fut.done():
                    continue
                if bucket == slot.bucket and not wake:
                    wake.append((loop, fut))
                else:
                    still.append((bucket, loop, fut))
            self._waiters = still
        for loop, fut in wake:
            try:
                loop.call_soon_threadsafe(self._resolve_waiter, fut)
            except RuntimeError:
                pass

    @staticmethod
    def _resolve_waiter(fut: asyncio.Future) -> None:
        if not fut.done():
            fut.set_result(None)

    # -- dispatch ---------------------------------------------------------
    async def dispatch(self, x: np.ndarray,
                       step: Callable[[np.ndarray], object]) -> np.ndarray:
        """Score one batch through the donated ring; returns f32 [n]."""
        if self._closed:
            raise RuntimeError("dispatcher closed")
        n = len(x)
        loop = asyncio.get_running_loop()
        bucket = int(self._bucket_fn(n))
        slot = await self._acquire(bucket)
        if self._closed:  # re-check: close() may have raced the acquire
            self._release(slot)
            raise RuntimeError("dispatcher closed")
        try:
            np.copyto(slot.staging[:n], x, casting="unsafe")
            with warnings.catch_warnings():
                warnings.filterwarnings(
                    "ignore", message=_DONATION_DECLINED_MSG)
                # async dispatch; the step donates the device copy
                result = step(slot.staging)
        except BaseException:
            self._release(slot)
            raise
        fut = loop.create_future()
        self._ensure_thread()
        self._queue.put((result, n, loop, fut, slot))
        return await fut

    def close(self) -> None:
        self._closed = True
        self._finalizer()  # idempotent: enqueues the drainer sentinel
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
        # a dispatch that raced close() past the sentinel would wait
        # forever on an item the drainer never saw: reject it instead
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is None:
                continue
            _result, _n, loop, fut, slot = item
            self._release(slot)
            try:
                loop.call_soon_threadsafe(
                    self._reject, fut, RuntimeError("dispatcher closed"))
            except RuntimeError:
                pass


# -- native feature ring ------------------------------------------------------


# engine row: route_id, lat_ms, status, req_b, rsp_b, ts, score,
# scored, tenant, kind, stream, frame_seq. score/scored are the
# in-data-plane scorer's output (native/scorer.h): scored == 1.0 rows
# arrive pre-scored from the engine; 0.0 rows (no weight blob
# published, route hash not pushed yet, nativeTier: off) fall back to
# the JAX tier in the micro-batcher. tenant is the 24-bit-folded
# FNV-1a tenant hash (0 = no tenant) the engine extracted per its
# tenantIdentifier config. kind (native/stream_track.h row kinds) is
# 0 for request rows, 1 for h2 stream samples, 2 for tunnel samples;
# kind > 0 rows carry the 24-bit stream-lifetime key in `stream` and
# the frame count at sample time in `frame_seq`, and repeat per
# stream — the training path must keep them out of request-shaped
# aggregation (the micro-batcher routes them to the stream sentinel).
NATIVE_ROW_WIDTH = 12
NATIVE_COL_SCORE = 6
NATIVE_COL_SCORED = 7
NATIVE_COL_TENANT = 8
NATIVE_COL_KIND = 9
NATIVE_COL_STREAM = 10
NATIVE_COL_SEQ = 11

# row kinds (mirror native/stream_track.h)
NATIVE_KIND_REQUEST = 0.0
NATIVE_KIND_STREAM = 1.0
NATIVE_KIND_TUNNEL = 2.0


class NativeFeatureRing:
    """Preallocated single-producer single-consumer ring of raw native
    feature rows (float32 [capacity, NATIVE_ROW_WIDTH], the engines'
    FeatureRow layout incl. the in-data-plane score/scored columns).
    Both sides run on the event loop thread; views are valid
    until the holder's next await (no interleaved producer).

    The producer (FastPathController) drains engine rows straight into
    ring memory via ``produce_views`` + ``commit`` — no per-row Python
    objects on the C++→Python seam. Under backpressure (consumer
    behind), overflow rows are dropped-and-counted, never written over
    unconsumed rows: wraparound can lose NEW rows, not corrupt old
    ones.
    """

    def __init__(self, capacity: int = 65536, width: int = NATIVE_ROW_WIDTH):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.buf = np.zeros((capacity, width), np.float32)
        self.capacity = capacity
        self.head = 0   # next row to consume
        self.count = 0  # readable rows
        self.dropped = 0

    def __len__(self) -> int:
        return self.count

    @property
    def free(self) -> int:
        return self.capacity - self.count

    def produce_views(self, max_rows: Optional[int] = None
                      ) -> List[np.ndarray]:
        """Up to two contiguous writable views (tail, then wrapped
        head). Fill in order, then ``commit(rows_written)``."""
        avail = self.free if max_rows is None else min(self.free, max_rows)
        if avail <= 0:
            return []
        tail = (self.head + self.count) % self.capacity
        first = min(avail, self.capacity - tail)
        views = [self.buf[tail:tail + first]]
        if avail > first:
            views.append(self.buf[:avail - first])
        return views

    def commit(self, rows: int) -> None:
        if rows < 0 or rows > self.free:
            raise ValueError(f"commit({rows}) with free={self.free}")
        self.count += rows

    def drop(self, rows: int) -> None:
        """Record ``rows`` overflow rows dropped at the producer."""
        self.dropped += rows

    def consume(self, max_rows: int) -> np.ndarray:
        """Zero-copy view of up to ``max_rows`` oldest rows (one
        contiguous chunk; call again for a wrapped remainder). The view
        is valid until the caller's next await."""
        n = min(self.count, max_rows, self.capacity - self.head)
        if n <= 0:
            return self.buf[:0]
        view = self.buf[self.head:self.head + n]
        self.head = (self.head + n) % self.capacity
        self.count -= n
        return view


class RouteTemporal:
    """Vectorized per-route latency-drift context for native feature
    blocks: the block-granular analogue of the one temporal signal that
    survived feature ablation (``models.features`` layout note: drift
    is column 32; the error-rate/rate-delta columns are deliberately
    zero). ``DstTemporal``'s per-row ``observe`` is exactly the
    per-row Python churn the native seam must avoid, so each consumed
    block updates one robust EWMA per route from the block's group
    mean; per-row drift is computed against the EWMA *before* the
    update, vectorized."""

    def __init__(self, lat_alpha: float = 0.05, dev_clip: float = 3.0,
                 dev_alpha: float = 0.05, max_routes: int = 4096):
        self._lat_alpha = lat_alpha
        self._dev_clip = dev_clip
        self._dev_alpha = dev_alpha
        self._max_routes = max_routes
        self._ewma: Dict[int, float] = {}
        self._dev: Dict[int, float] = {}

    def drift_block(self, route_ids: np.ndarray,
                    lat_ms: np.ndarray) -> np.ndarray:
        """-> per-row latency drift (ms) against state BEFORE this
        block updates it."""
        drift = np.zeros(len(route_ids), np.float32)
        uniq, inv = np.unique(route_ids, return_inverse=True)
        for j, rid in enumerate(uniq):
            rid = int(rid)
            rows = inv == j
            prev = self._ewma.get(rid)
            if prev is not None:
                drift[rows] = lat_ms[rows] - prev
            mean = float(lat_ms[rows].mean())
            if prev is None:
                if len(self._ewma) >= self._max_routes:
                    continue  # bounded cardinality: overflow routes get 0s
                self._ewma[rid] = mean
                self._dev[rid] = max(abs(mean) * 0.1, 0.25)
            else:
                dev = self._dev.get(rid, 0.25)
                lim = self._dev_clip * max(dev, 0.25)
                inc = min(max(mean - prev, -lim), lim)
                self._ewma[rid] = prev + self._lat_alpha * inc
                self._dev[rid] = dev + self._dev_alpha * (
                    min(abs(mean - prev), lim) - dev)
        return drift


class NativeFeaturizer:
    """Vectorized native-row → model-feature encoding. One numpy pass
    per block; the only per-ROUTE (not per-row) Python work is the
    cached dst-path hash lookup."""

    def __init__(self, resolver: Optional[Callable[[int], str]] = None):
        from linkerd_tpu.models.features import FEATURE_DIM
        self.dim = FEATURE_DIM
        self.resolver = resolver
        self.temporal = RouteTemporal()
        self._hash_cache: Dict[int, Tuple[int, float, str]] = {}

    def _route_info(self, rid: int) -> Tuple[int, float, str]:
        from linkerd_tpu.models.features import path_hash_cols
        info = self._hash_cache.get(rid)
        if info is None:
            dst = self.resolver(rid) if self.resolver is not None else None
            cacheable = dst is not None
            if dst is None:
                # resolver doesn't know this route yet (the id→host map
                # rides the 1s stats loop): attribute to a placeholder
                # but do NOT cache it — the next block re-resolves, so
                # the board key self-corrects once the mapping lands
                dst = f"/fp-{rid}"
            col, sign = path_hash_cols(dst)
            info = (col, sign, dst)
            if cacheable and len(self._hash_cache) < 65536:
                self._hash_cache[rid] = info
        return info

    def encode_block(self, block: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray, List[str]]:
        """float32 [k, 6] engine rows -> (x [k, FEATURE_DIM], route
        index per row, dst path per unique route index)."""
        from linkerd_tpu.models.features import STATUS_ONEHOT_OFF
        k = len(block)
        x = np.zeros((k, self.dim), np.float32)
        if k == 0:
            return x, np.zeros(0, np.int64), []
        rid = block[:, 0].astype(np.int64)
        lat = np.maximum(block[:, 1], 0.0)
        status = block[:, 2].astype(np.int64)
        x[:, 0] = np.log1p(lat)
        sc = status // 100
        ok = (sc >= 1) & (sc <= 5)
        x[np.flatnonzero(ok), STATUS_ONEHOT_OFF + sc[ok] - 1] = 1.0
        x[:, 8] = np.log1p(np.maximum(block[:, 3], 0.0))
        x[:, 9] = np.log1p(np.maximum(block[:, 4], 0.0))
        x[:, 10] = np.log1p(1.0)  # engine rows carry no concurrency
        x[:, 31] = 1.0
        uniq, inv = np.unique(rid, return_inverse=True)
        dsts: List[str] = []
        for j, r in enumerate(uniq):
            col, sign, dst = self._route_info(int(r))
            dsts.append(dst)
            x[inv == j, col] += sign
        drift = self.temporal.drift_block(rid, lat.astype(np.float32))
        x[:, 32] = np.sign(drift) * np.log1p(np.abs(drift))
        return x, inv, dsts


# -- tiered scorer ------------------------------------------------------------


class TieredScorer:
    """In-process primary with the gRPC sidecar as the fallback tier.

    The primary (InProcessScorer) serves every batch at line rate; its
    own breaker opens after consecutive failures so a sick local device
    doesn't add a failed attempt to every batch. While the primary is
    open, batches route to the fallback (a ResilientScorer-wrapped
    sidecar, with its own breaker + per-call deadline). Both tiers
    failing surfaces the fallback's error, which the telemeter maps to
    degraded mode as before.

    Lifecycle hooks (snapshot/restore/swap/warmup) bind to the primary:
    the in-process model is the one the lifecycle manager owns.
    """

    def __init__(self, primary, fallback, breaker=None):
        from linkerd_tpu.telemetry.resilience import CircuitBreaker
        self.primary = primary
        self.fallback = fallback
        self.primary_breaker = breaker or CircuitBreaker(
            failures=3, min_backoff_s=1.0, max_backoff_s=30.0)
        self.primary_calls = 0
        self.fallback_calls = 0

    # the telemeter reads/steers these on whatever scorer it holds
    @property
    def breaker(self):
        return getattr(self.fallback, "breaker", None)

    @property
    def last_timing(self):
        return getattr(self.primary, "last_timing", None)

    @property
    def timing_enabled(self) -> bool:
        return bool(getattr(self.primary, "timing_enabled", False))

    @timing_enabled.setter
    def timing_enabled(self, v: bool) -> None:
        if hasattr(self.primary, "timing_enabled"):
            self.primary.timing_enabled = v

    @property
    def timing_sample_every(self) -> int:
        return int(getattr(self.primary, "timing_sample_every", 1))

    @timing_sample_every.setter
    def timing_sample_every(self, v: int) -> None:
        if hasattr(self.primary, "timing_sample_every"):
            self.primary.timing_sample_every = v

    @property
    def _step(self):
        return getattr(self.primary, "_step", None)

    async def _tiered(self, what: str, primary_call, fallback_call):
        admitted, probe = self.primary_breaker.acquire()
        if admitted:
            try:
                out = await primary_call()
            except asyncio.CancelledError:
                self.primary_breaker.on_cancel(probe)
                raise
            except Exception as e:  # noqa: BLE001 — tier boundary: any
                # primary failure demotes this call to the fallback tier
                self.primary_breaker.on_failure(probe)
                log.warning("in-process scorer %s failed; using fallback "
                            "tier: %r", what, e)
            else:
                self.primary_breaker.on_success(probe)
                self.primary_calls += 1
                return out
        self.fallback_calls += 1
        return await fallback_call()

    async def score(self, x: np.ndarray) -> np.ndarray:
        return await self._tiered(
            "score", lambda: self.primary.score(x),
            lambda: self.fallback.score(x))

    async def fit(self, x: np.ndarray, labels: np.ndarray,
                  mask: np.ndarray) -> float:
        """Training binds to the PRIMARY only — it is the model the
        lifecycle manager snapshots/promotes. Routing fit() to the
        fallback would silently train the sidecar's remote model,
        which no checkpoint ever sees and which would diverge from the
        primary for the rest of the outage. While the primary breaker
        is open, training is skipped (the telemeter logs and counts a
        skipped fit; scoring continues on the fallback)."""
        from linkerd_tpu.telemetry.resilience import ScorerUnavailable
        admitted, probe = self.primary_breaker.acquire()
        if not admitted:
            raise ScorerUnavailable(
                "fit skipped: in-process primary breaker open "
                "(training never routes to the fallback tier)")
        try:
            out = await self.primary.fit(x, labels, mask)
        except asyncio.CancelledError:
            self.primary_breaker.on_cancel(probe)
            raise
        except Exception:
            self.primary_breaker.on_failure(probe)
            raise
        self.primary_breaker.on_success(probe)
        self.primary_calls += 1
        return out

    def snapshot(self):
        return self.primary.snapshot()

    def restore(self, snap) -> None:
        self.primary.restore(snap)

    def swap(self, snap):
        return self.primary.swap(snap)

    async def warmup(self, rows: int = 4) -> None:
        warm = getattr(self.primary, "warmup", None)
        if warm is not None:
            await warm(rows)

    def tier_state(self) -> dict:
        return {
            "primary": type(self.primary).__name__,
            "primary_breaker": self.primary_breaker.state,
            "primary_calls": self.primary_calls,
            "fallback_calls": self.fallback_calls,
        }

    def close(self) -> None:
        self.primary.close()
        self.fallback.close()
