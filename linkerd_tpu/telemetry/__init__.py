"""Telemetry: MetricsTree, Telemeter SPI, stats plumbing, exporters.

Reference parity: /root/reference/telemetry/core (Telemeter.scala:11,
MetricsTree.scala:9) and the exporter plugins (§2.3 of SURVEY.md).
"""

from linkerd_tpu.telemetry.metrics import MetricsTree, Counter, Gauge, Stat
from linkerd_tpu.telemetry.telemeter import Telemeter

__all__ = ["MetricsTree", "Counter", "Gauge", "Stat", "Telemeter"]
