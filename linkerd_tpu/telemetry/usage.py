"""Anonymized usage telemetry (opt-out).

Ref: linkerd/core/.../UsageDataTelemeter.scala:183 — an hourly POST of
anonymized config/runtime shape (kinds in use, router count, uptime; no
names, paths, or addresses) to stats.buoyant.io unless
``usage: {enabled: false}``. JSON instead of the reference's proto
(usage.proto); the target is configurable so tests point it at a local
sink (this environment has zero egress).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
import uuid
from typing import Any, Dict, Optional

log = logging.getLogger(__name__)

DEFAULT_HOST = "stats.buoyant.io"
DEFAULT_PORT = 443
INTERVAL_S = 3600.0


def build_report(spec: Any, orgId: str, instance_id: str,
                 start_time: float,
                 uptime_s: float = 0.0) -> Dict[str, Any]:
    """Anonymized shape only: kinds and counts, never user values
    (ref: UsageMessage fields in usage.proto). ``start_time`` is the
    reported wall-clock instant; ``uptime_s`` is measured by the caller
    on the monotonic clock (an NTP step must not skew it)."""
    routers = []
    for r in getattr(spec, "routers", []) or []:
        ids = r.identifier
        if isinstance(ids, dict):
            ids = [ids]
        routers.append({
            "protocol": r.protocol,
            "identifiers": [c.get("kind") for c in (ids or [])],
            "transformers": [],
        })
    namers = [n.get("kind") for n in (getattr(spec, "namers", None) or [])
              if isinstance(n, dict)]
    telemeters = [t.get("kind")
                  for t in (getattr(spec, "telemetry", None) or [])
                  if isinstance(t, dict)]
    return {
        "pid": instance_id,
        "orgId": orgId,
        "linkerd_version": "tpu-0.1",
        "start_time": int(start_time),
        "uptime_s": int(uptime_s),
        "routers": routers,
        "namers": namers,
        "telemeters": telemeters,
    }


class UsageDataTelemeter:
    """Posts a usage report hourly; disabled via usage.enabled=false."""

    def __init__(self, spec: Any, orgId: str = "",
                 host: str = DEFAULT_HOST, port: int = DEFAULT_PORT,
                 interval_s: float = INTERVAL_S):
        self._spec = spec
        self._orgId = orgId
        self._host = host
        self._port = port
        self._interval = interval_s
        self._instance_id = str(uuid.uuid4())
        self._start = time.time()        # reported instant (wall clock)
        self._start_mono = time.monotonic()  # uptime measurement
        self.tracer = None

    def admin_handlers(self):
        return []

    async def _post(self) -> None:
        body = json.dumps(build_report(
            self._spec, self._orgId, self._instance_id, self._start,
            uptime_s=time.monotonic() - self._start_mono)
        ).encode()
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(
                self._host, self._port,
                ssl=(self._port == 443)), 10.0)
        try:
            head = (f"POST /ping HTTP/1.1\r\nHost: {self._host}\r\n"
                    f"Content-Type: application/json\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    f"Connection: close\r\n\r\n").encode()
            writer.write(head + body)
            await writer.drain()
            await asyncio.wait_for(reader.read(256), 10.0)
        finally:
            writer.close()

    async def run(self) -> None:
        while True:
            try:
                await self._post()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 - usage is best-effort
                log.debug("usage post failed: %s", e)
            await asyncio.sleep(self._interval)
