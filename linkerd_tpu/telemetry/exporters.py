"""Exporter telemeters: prometheus, influxdb, statsd, tracelog,
recentRequests.

Reference parity (SURVEY.md §2.3): telemetry/prometheus
(label-rewriting text exposition, PrometheusTelemeter.scala:62-80),
telemetry/influxdb (LINE protocol for Telegraf pull), telemetry/statsd
(dogstatsd push), telemetry/tracelog (sampled span logging),
telemetry/recent-requests (in-memory ring + admin table).
"""

from __future__ import annotations

import asyncio
import collections
import json
import logging
import re
import time
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Tuple

from linkerd_tpu.config import register
from linkerd_tpu.protocol.http.message import Request, Response
from linkerd_tpu.telemetry.metrics import Counter, Gauge, MetricsTree, Stat
from linkerd_tpu.telemetry.telemeter import Telemeter, Tracer

log = logging.getLogger(__name__)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_STATSD_RE = re.compile(r"[^a-zA-Z0-9_.]")


def _sanitize(name: str) -> str:
    return _NAME_RE.sub("_", name)


def _sanitize_statsd(name: str) -> str:
    return _STATSD_RE.sub("_", name)


def _labeled_name(names: Tuple[str, ...]) -> Tuple[str, Dict[str, str]]:
    """Rewrite the rt/<router>/{server,service/<svc>,client/<id>} scope
    convention into labels (ref: PrometheusTelemeter.scala:62-80)."""
    labels: Dict[str, str] = {}
    rest = list(names)
    if len(rest) >= 2 and rest[0] == "rt":
        labels["rt"] = rest[1]
        rest = rest[2:]
        if rest and rest[0] == "server":
            rest = rest[1:]
        elif len(rest) >= 2 and rest[0] in ("service", "client"):
            labels[rest[0]] = rest[1]
            rest = rest[2:]
    return _sanitize("_".join(rest) or "value"), labels


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def prometheus_text(metrics: MetricsTree) -> str:
    lines: List[str] = []
    for names, metric in metrics.walk():
        name, labels = _labeled_name(names)
        if isinstance(metric, Counter):
            lines.append(f"{name}{_fmt_labels(labels)} {metric.value}")
        elif isinstance(metric, Gauge):
            lines.append(f"{name}{_fmt_labels(labels)} {metric.value}")
        elif isinstance(metric, Stat):
            snap = metric.snapshot()
            if snap["count"] == 0:
                continue
            quantiles = {"p50": "0.5", "p90": "0.9", "p95": "0.95",
                         "p99": "0.99", "p999": "0.999"}
            for q, qv in quantiles.items():
                ql = dict(labels)
                ql["quantile"] = qv
                lines.append(f"{name}{_fmt_labels(ql)} {snap[q]}")
            lines.append(f"{name}_count{_fmt_labels(labels)} {snap['count']}")
            lines.append(f"{name}_sum{_fmt_labels(labels)} {snap['sum']}")
            lines.append(f"{name}_avg{_fmt_labels(labels)} {snap['avg']}")
    return "\n".join(lines) + "\n"


def influxdb_line(metrics: MetricsTree, host: str = "localhost") -> str:
    """LINE protocol, one measurement per scope prefix
    (ref: InfluxDbTelemeter.scala:17)."""
    by_prefix: Dict[Tuple[Tuple[str, ...], Tuple[Tuple[str, str], ...]],
                    Dict[str, float]] = {}
    for names, metric in metrics.walk():
        if len(names) < 1:
            continue
        name, labels = _labeled_name(names)
        key_prefix = tuple(sorted(labels.items()))
        measurement = names[0] if names[0] != "rt" else "rt"
        if isinstance(metric, (Counter, Gauge)):
            fields = {name: float(metric.value)}
        else:
            snap = metric.snapshot()
            if snap["count"] == 0:
                continue
            fields = {f"{name}_{k}": float(v) for k, v in snap.items()}
        by_prefix.setdefault((measurement, key_prefix), {}).update(fields)
    lines = []
    for (measurement, labels), fields in sorted(by_prefix.items()):
        tag_str = "".join(f",{k}={v}" for k, v in labels)
        field_str = ",".join(f"{k}={v}" for k, v in sorted(fields.items()))
        lines.append(f"{measurement},host={host}{tag_str} {field_str}")
    return "\n".join(lines) + "\n"


@register("telemeter", "io.l5d.prometheus")
@dataclass
class PrometheusConfig:
    """Expose the MetricsTree in Prometheus text format at ``path``
    on the admin server."""

    path: str = "/admin/metrics/prometheus"

    def mk(self, metrics: MetricsTree) -> Telemeter:
        return PrometheusTelemeter(metrics, self.path)


class PrometheusTelemeter(Telemeter):
    def __init__(self, metrics: MetricsTree, path: str):
        self.metrics = metrics
        self.path = path

    def admin_handlers(self):
        async def handler(req: Request) -> Response:
            rsp = Response(body=prometheus_text(self.metrics).encode())
            rsp.headers.set("Content-Type", "text/plain; version=0.0.4")
            return rsp

        return [(self.path, handler)]


@register("telemeter", "io.l5d.influxdb")
@dataclass
class InfluxDbConfig:
    """Expose the MetricsTree as InfluxDB line protocol at ``path``
    on the admin server (for Telegraf scrapes)."""

    path: str = "/admin/metrics/influxdb"

    def mk(self, metrics: MetricsTree) -> Telemeter:
        return InfluxDbTelemeter(metrics, self.path)


class InfluxDbTelemeter(Telemeter):
    def __init__(self, metrics: MetricsTree, path: str):
        self.metrics = metrics
        self.path = path

    def admin_handlers(self):
        async def handler(req: Request) -> Response:
            rsp = Response(body=influxdb_line(self.metrics).encode())
            rsp.headers.set("Content-Type", "text/plain")
            return rsp

        return [(self.path, handler)]


@register("telemeter", "io.l5d.statsd", experimental=True)
@dataclass
class StatsDConfig:
    """Push counters/timings to a StatsD agent over UDP; gauges
    flush every ``gaugeIntervalMs``."""

    host: str = "127.0.0.1"
    port: int = 8125
    prefix: str = "linkerd"
    gaugeIntervalMs: int = 10000

    def mk(self, metrics: MetricsTree) -> Telemeter:
        return StatsDTelemeter(metrics, self)


class StatsDTelemeter(Telemeter):
    """Pushes counters (as deltas) and gauges over UDP dogstatsd lines
    every gaugeIntervalMs (ref: StatsDTelemeter.scala:9)."""

    def __init__(self, metrics: MetricsTree, cfg: StatsDConfig):
        self.metrics = metrics
        self.cfg = cfg
        self._last_counters: Dict[str, int] = {}
        self._transport = None
        self._stop = asyncio.Event()

    async def run(self) -> None:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            asyncio.DatagramProtocol,
            remote_addr=(self.cfg.host, self.cfg.port))
        try:
            while not self._stop.is_set():
                await asyncio.sleep(self.cfg.gaugeIntervalMs / 1e3)
                self.flush()
        except asyncio.CancelledError:
            pass
        finally:
            if self._transport:
                self._transport.close()

    def flush(self) -> None:
        if self._transport is None:
            return
        out = []
        for names, metric in self.metrics.walk():
            key = f"{self.cfg.prefix}.{'.'.join(names)}"
            key = _sanitize_statsd(key.replace("/", "."))
            if isinstance(metric, Counter):
                delta = metric.value - self._last_counters.get(key, 0)
                self._last_counters[key] = metric.value
                if delta:
                    out.append(f"{key}:{delta}|c")
            elif isinstance(metric, Gauge):
                out.append(f"{key}:{metric.value}|g")
            elif isinstance(metric, Stat):
                snap = metric.snapshot()
                if snap["count"]:
                    out.append(f"{key}.p99:{snap['p99']}|g")
                    out.append(f"{key}.p50:{snap['p50']}|g")
        for line in out:
            self._transport.sendto(line.encode())

    def close(self) -> None:
        self._stop.set()


@register("telemeter", "io.l5d.tracelog")
@dataclass
class TracelogConfig:
    """Write sampled trace annotations to the python log at
    ``level``."""

    sampleRate: float = 1.0
    level: str = "INFO"

    def mk(self, metrics: MetricsTree) -> Telemeter:
        return TracelogTelemeter(self)


class TracelogTelemeter(Telemeter):
    """Logs sampled spans (ref: TracelogInitializer.scala:47)."""

    def __init__(self, cfg: TracelogConfig):
        self.cfg = cfg
        self._log = logging.getLogger("linkerd_tpu.tracelog")
        self._level = getattr(logging, cfg.level.upper(), logging.INFO)
        self._tracer = _FnTracer(self._record)
        import random
        self._rng = random.Random()

    def _record(self, span: dict) -> None:
        if self._rng.random() < self.cfg.sampleRate:
            self._log.log(self._level, "trace %s span %s %s %sus %s",
                          span.get("traceId"), span.get("id"),
                          span.get("name"), span.get("duration"),
                          span.get("tags"))

    @property
    def tracer(self) -> Tracer:
        return self._tracer


class _FnTracer(Tracer):
    def __init__(self, fn):
        self._fn = fn

    def record(self, span: dict) -> None:
        self._fn(span)


@register("telemeter", "io.l5d.recentRequests")
@dataclass
class RecentRequestsConfig:
    """Keep an in-memory ring of the last ``capacity`` sampled
    requests, served at /requests.json on the admin server."""

    sampleRate: float = 1.0
    capacity: int = 100

    def mk(self, metrics: MetricsTree) -> Telemeter:
        return RecentRequestsTelemeter(self)


class RecentRequestsTelemeter(Telemeter):
    """In-memory ring of sampled spans + /requests admin table
    (ref: RecentRequetsTracer.scala:14)."""

    def __init__(self, cfg: RecentRequestsConfig):
        self.cfg = cfg
        self.ring: Deque[dict] = collections.deque(maxlen=cfg.capacity)
        import random
        self._rng = random.Random()
        self._tracer = _FnTracer(self._record)

    def _record(self, span: dict) -> None:
        if span.get("kind") == "SERVER" and (
                self._rng.random() < self.cfg.sampleRate):
            self.ring.append(span)

    @property
    def tracer(self) -> Tracer:
        return self._tracer

    def admin_handlers(self):
        from linkerd_tpu.admin.server import json_response

        async def requests(req: Request) -> Response:
            return json_response(list(self.ring))

        return [("/requests.json", requests)]


@register("telemeter", "io.l5d.zipkin")
@dataclass
class ZipkinConfig:
    """Ship sampled spans to a Zipkin collector (v2 JSON API) in
    batches every ``batchIntervalMs``; bounded buffering, exponential
    backoff on collector failure, stats at ``/tracer.json``."""

    host: str = "127.0.0.1"
    port: int = 9411
    sampleRate: float = 0.001
    batchIntervalMs: int = 1000
    # bounded buffering: spans beyond this are dropped (and counted) —
    # a dead collector must cost memory-bounded, never unbounded
    maxBufferedSpans: int = 10000
    # spans per POST (zipkin collectors reject oversized bodies)
    maxBatch: int = 500
    # backoff bounds after a failed POST
    backoffMinMs: int = 1000
    backoffMaxMs: int = 30000

    def mk(self, metrics: MetricsTree) -> Telemeter:
        return ZipkinTelemeter(self)


class ZipkinTelemeter(Telemeter):
    """Zipkin v2 JSON span sink over HTTP POST /api/v2/spans.

    The reference ships scribe-thrift (ZipkinInitializer.scala:27-60, a
    2017-era protocol); the v2 HTTP API is the modern equivalent of the
    same component. Sampling is decided at trace creation (the
    ``l5d-sample`` header / router sampleRate drive span.sampled, and
    the trace filters only record sampled spans), so everything handed
    to this tracer ships — unless a span explicitly carries
    ``sampled: false``, which is dropped here and counted.

    Failure posture: telemetry must never block or destabilize the data
    plane. The buffer is bounded (overflow drops the NEWEST span and
    counts it), a failed POST re-buffers its batch and backs off
    exponentially, and all of it is observable at ``/tracer.json``.
    """

    def __init__(self, cfg: ZipkinConfig):
        self.cfg = cfg
        self._buf: Deque[dict] = collections.deque()
        self._tracer = _FnTracer(self._record)
        self._stop = asyncio.Event()
        self._client = None
        # stats surfaced at /tracer.json
        self.sent_spans = 0
        self.dropped_spans = 0
        self.sampled_out = 0
        self.failed_posts = 0
        self.posts = 0
        self._backoff_s = 0.0
        self._next_send_after = 0.0  # monotonic gate while backing off

    @property
    def tracer(self) -> Tracer:
        return self._tracer

    @property
    def sample_rate(self) -> float:
        return self.cfg.sampleRate

    @property
    def buffer_depth(self) -> int:
        return len(self._buf)

    def _record(self, span: dict) -> None:
        if span.get("sampled") is False:
            self.sampled_out += 1
            return
        if len(self._buf) >= self.cfg.maxBufferedSpans:
            self.dropped_spans += 1
            return
        self._buf.append(span)

    def _ensure_client(self):
        if self._client is None:
            from linkerd_tpu.protocol.http.client import HttpClient
            self._client = HttpClient(self.cfg.host, self.cfg.port,
                                      max_connections=2)
        return self._client

    async def run(self) -> None:
        try:
            while not self._stop.is_set():
                await asyncio.sleep(self.cfg.batchIntervalMs / 1e3)
                if time.monotonic() < self._next_send_after:
                    continue  # backing off after a failed POST
                await self.flush()
        except asyncio.CancelledError:
            pass
        finally:
            # detach before awaiting: a flush() racing this teardown
            # sees None and builds a fresh client instead of a closed one
            client, self._client = self._client, None
            if client is not None:
                await client.close()

    async def flush(self, client=None) -> int:
        """POST buffered spans in bounded batches; returns spans sent.
        On failure the batch is re-buffered (oldest-first, dropped if
        the buffer refilled meanwhile) and the backoff window opens."""
        sent = 0
        client = client or self._ensure_client()
        while self._buf:
            batch = [self._buf.popleft()
                     for _ in range(min(len(self._buf), self.cfg.maxBatch))]
            req = Request(method="POST", uri="/api/v2/spans",
                          body=json.dumps(batch).encode())
            req.headers.set("Content-Type", "application/json")
            req.headers.set("Host", f"{self.cfg.host}:{self.cfg.port}")
            self.posts += 1
            try:
                rsp = await client(req)
                if rsp.status >= 300:
                    raise ConnectionError(
                        f"zipkin rejected spans: {rsp.status}")
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — re-buffer + back off
                self.failed_posts += 1
                self._backoff_s = min(
                    max(self._backoff_s * 2, self.cfg.backoffMinMs / 1e3),
                    self.cfg.backoffMaxMs / 1e3)
                self._next_send_after = time.monotonic() + self._backoff_s
                for i, span in enumerate(reversed(batch)):
                    if len(self._buf) >= self.cfg.maxBufferedSpans:
                        # everything not re-buffered is lost — count all
                        # of it, not just the span that hit the wall
                        self.dropped_spans += len(batch) - i
                        break
                    self._buf.appendleft(span)
                log.debug("zipkin send failed (backoff %.1fs): %r",
                          self._backoff_s, e)
                return sent
            sent += len(batch)
            self.sent_spans += len(batch)
        self._backoff_s = 0.0
        self._next_send_after = 0.0
        return sent

    def stats(self) -> dict:
        return {
            "collector": f"{self.cfg.host}:{self.cfg.port}",
            "buffer_depth": len(self._buf),
            "buffer_capacity": self.cfg.maxBufferedSpans,
            "sent_spans": self.sent_spans,
            "dropped_spans": self.dropped_spans,
            "sampled_out": self.sampled_out,
            "posts": self.posts,
            "failed_posts": self.failed_posts,
            "backoff_s": round(self._backoff_s, 3),
            "sample_rate": self.cfg.sampleRate,
        }

    def admin_handlers(self):
        from linkerd_tpu.admin.server import json_response

        async def tracer_json(req: Request) -> Response:
            return json_response(self.stats())

        return [("/tracer.json", tracer_json)]

    def close(self) -> None:
        self._stop.set()
