"""ctypes loader for the native hot-path library.

Mirrors the reference's optional-native pattern (Netty loads its epoll
transport when present, falls back to NIO): if ``libl5d_native.so`` is
missing, it is built on first import when a toolchain is available;
failing that, callers fall back to the pure-Python implementations.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import sys
from typing import List, Optional, Tuple

log = logging.getLogger(__name__)

_SO_PATH = os.path.join(os.path.dirname(__file__), "libl5d_native.so")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    build_py = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                            "native", "build.py")
    build_py = os.path.abspath(build_py)
    if not os.path.exists(build_py):
        return False
    try:
        subprocess.run([sys.executable, build_py], check=True,
                       capture_output=True, timeout=120)
        return True
    except Exception as e:  # noqa: BLE001 - fall back to pure python
        log.debug("native build failed: %s", e)
        return False


def ensure_built() -> bool:
    """Build + load the native library if possible. Call at process
    startup (linker/namerd assembly) — NEVER from the data path: the
    compile shells out to g++ and would freeze the event loop."""
    global _tried
    if not os.path.exists(_SO_PATH):
        _build()
    _tried = False  # allow lib() to (re)load
    return lib() is not None


def lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_SO_PATH):
        return None  # ensure_built() (startup) does the building
    try:
        cdll = ctypes.CDLL(_SO_PATH)
        cdll.l5d_huffman_decode.restype = ctypes.c_long
        cdll.l5d_huffman_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t]
        cdll.l5d_huffman_encode.restype = ctypes.c_long
        cdll.l5d_huffman_encode.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t]
        cdll.l5d_parse_http1_head.restype = ctypes.c_long
        cdll.l5d_parse_http1_head.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_size_t]
        _lib = cdll
    except OSError as e:
        log.debug("native lib load failed: %s", e)
    return _lib


def available() -> bool:
    return lib() is not None


def huffman_decode(data: bytes) -> Optional[bytes]:
    """None => native unavailable or refused (caller falls back /
    raises per its own validation)."""
    cdll = lib()
    if cdll is None:
        return None
    cap = max(16, len(data) * 2)
    for _ in range(2):
        out = ctypes.create_string_buffer(cap)
        n = cdll.l5d_huffman_decode(data, len(data), out, cap)
        if n == -2:
            cap *= 4
            continue
        if n < 0:
            return None  # malformed: let the python path raise precisely
        return out.raw[:n]
    return None


def huffman_encode(data: bytes) -> Optional[bytes]:
    cdll = lib()
    if cdll is None:
        return None
    # rare symbols are up to 30 bits (3.75 bytes) each
    cap = len(data) * 4 + 8
    out = ctypes.create_string_buffer(cap)
    n = cdll.l5d_huffman_encode(data, len(data), out, cap)
    if n < 0:
        return None
    return out.raw[:n]


MAX_HEADERS = 1024
_SPANS = ctypes.c_int32 * (6 + MAX_HEADERS * 4)


def parse_http1_head(head: bytes
                     ) -> Optional[Tuple[str, str, str,
                                         List[Tuple[str, str]]]]:
    """Parse a full request head block -> (method, uri, version, headers).
    None => native unavailable or malformed (caller falls back)."""
    cdll = lib()
    if cdll is None:
        return None
    spans = _SPANS()
    n = cdll.l5d_parse_http1_head(head, len(head), spans, MAX_HEADERS)
    if n < 0:
        return None
    method = head[spans[0]:spans[0] + spans[1]].decode("latin-1")
    uri = head[spans[2]:spans[2] + spans[3]].decode("latin-1")
    version = head[spans[4]:spans[4] + spans[5]].decode("latin-1")
    headers = []
    for i in range(n):
        o = 6 + i * 4
        name = head[spans[o]:spans[o] + spans[o + 1]].decode("latin-1")
        val = head[spans[o + 2]:spans[o + 2] + spans[o + 3]].decode("latin-1")
        headers.append((name, val))
    return method, uri, version, headers
