"""ctypes loader for the native hot-path library.

Mirrors the reference's optional-native pattern (Netty loads its epoll
transport when present, falls back to NIO): if ``libl5d_native.so`` is
missing, it is built on first import when a toolchain is available;
failing that, callers fall back to the pure-Python implementations.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import sys
from typing import List, Optional, Tuple

log = logging.getLogger(__name__)

_SO_PATH = os.path.join(os.path.dirname(__file__), "libl5d_native.so")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    build_py = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                            "native", "build.py")
    build_py = os.path.abspath(build_py)
    if not os.path.exists(build_py):
        return False
    try:
        subprocess.run([sys.executable, build_py], check=True,
                       capture_output=True, timeout=120)
        return True
    except Exception as e:  # noqa: BLE001 - fall back to pure python
        log.debug("native build failed: %s", e)
        return False


def ensure_built() -> bool:
    """Build + load the native library if possible. Call at process
    startup (linker/namerd assembly) — NEVER from the data path: the
    compile shells out to g++ and would freeze the event loop.

    A stale .so (from an older source revision, missing newer symbols)
    is rebuilt once: lib() refuses to load it, so we retry the build."""
    global _tried
    if not os.path.exists(_SO_PATH):
        _build()
    _tried = False  # allow lib() to (re)load
    if lib() is None and os.path.exists(_SO_PATH):
        _build()
        _tried = False
    return lib() is not None


def lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_SO_PATH):
        return None  # ensure_built() (startup) does the building
    try:
        cdll = ctypes.CDLL(_SO_PATH)
        _declare_fastpath(cdll)
        _declare_h2_fastpath(cdll)
        _declare_scorer(cdll)
        cdll.l5d_huffman_decode.restype = ctypes.c_long
        cdll.l5d_huffman_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t]
        cdll.l5d_huffman_encode.restype = ctypes.c_long
        cdll.l5d_huffman_encode.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.c_char_p, ctypes.c_size_t]
        cdll.l5d_parse_http1_head.restype = ctypes.c_long
        cdll.l5d_parse_http1_head.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_size_t]
        _lib = cdll
    except (OSError, AttributeError) as e:
        # AttributeError => stale .so missing newer symbols; treat as
        # unavailable so ensure_built() can rebuild it
        log.debug("native lib load failed: %s", e)
    return _lib


def available() -> bool:
    return lib() is not None


def huffman_decode(data: bytes) -> Optional[bytes]:
    """None => native unavailable or refused (caller falls back /
    raises per its own validation)."""
    cdll = lib()
    if cdll is None:
        return None
    cap = max(16, len(data) * 2)
    for _ in range(2):
        out = ctypes.create_string_buffer(cap)
        n = cdll.l5d_huffman_decode(data, len(data), out, cap)
        if n == -2:
            cap *= 4
            continue
        if n < 0:
            return None  # malformed: let the python path raise precisely
        return out.raw[:n]
    return None


def huffman_encode(data: bytes) -> Optional[bytes]:
    cdll = lib()
    if cdll is None:
        return None
    # rare symbols are up to 30 bits (3.75 bytes) each
    cap = len(data) * 4 + 8
    out = ctypes.create_string_buffer(cap)
    n = cdll.l5d_huffman_encode(data, len(data), out, cap)
    if n < 0:
        return None
    return out.raw[:n]


def _declare_scorer(cdll: ctypes.CDLL) -> None:
    """Engine-independent in-data-plane scorer exports (l5d_score_* /
    l5d_slab_*) plus the per-engine publish/feature hooks."""
    cdll.l5d_score_feature_dim.restype = ctypes.c_int
    cdll.l5d_score_feature_dim.argtypes = []
    cdll.l5d_score_blob_info.restype = ctypes.c_long
    cdll.l5d_score_blob_info.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
        ctypes.c_size_t]
    cdll.l5d_score_eval.restype = ctypes.c_long
    cdll.l5d_score_eval.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_float), ctypes.c_long, ctypes.c_long,
        ctypes.POINTER(ctypes.c_float), ctypes.c_char_p, ctypes.c_size_t]
    cdll.l5d_score_eval_route.restype = ctypes.c_long
    cdll.l5d_score_eval_route.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_float), ctypes.c_long, ctypes.c_long,
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_char_p, ctypes.c_size_t]
    cdll.l5d_score_eval_raw.restype = ctypes.c_long
    cdll.l5d_score_eval_raw.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_float), ctypes.c_long,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float), ctypes.c_char_p, ctypes.c_size_t]
    cdll.l5d_slab_create.restype = ctypes.c_void_p
    cdll.l5d_slab_create.argtypes = []
    cdll.l5d_slab_publish.restype = ctypes.c_int
    cdll.l5d_slab_publish.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t]
    cdll.l5d_slab_publish_delta.restype = ctypes.c_int
    cdll.l5d_slab_publish_delta.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
        ctypes.c_char_p, ctypes.c_size_t]
    cdll.l5d_slab_score.restype = ctypes.c_long
    cdll.l5d_slab_score.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_float), ctypes.c_long,
        ctypes.POINTER(ctypes.c_float)]
    cdll.l5d_slab_score_route.restype = ctypes.c_long
    cdll.l5d_slab_score_route.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_float), ctypes.c_long,
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32)]
    cdll.l5d_slab_stats.restype = ctypes.c_long
    cdll.l5d_slab_stats.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
    cdll.l5d_slab_free.restype = None
    cdll.l5d_slab_free.argtypes = [ctypes.c_void_p]
    cdll.l5d_score_test_blob.restype = ctypes.c_long
    cdll.l5d_score_test_blob.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32, ctypes.c_int,
        ctypes.c_uint32]
    cdll.l5d_score_test_bank.restype = ctypes.c_long
    cdll.l5d_score_test_bank.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32, ctypes.c_int,
        ctypes.c_uint32, ctypes.c_uint32]
    cdll.l5d_score_test_delta.restype = ctypes.c_long
    cdll.l5d_score_test_delta.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint32,
        ctypes.c_uint32, ctypes.c_uint32, ctypes.c_int, ctypes.c_uint32,
        ctypes.c_int]
    for prefix in ("fp", "fph2"):
        fn = getattr(cdll, prefix + "_publish_weights")
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
                       ctypes.c_char_p, ctypes.c_size_t]
        fn = getattr(cdll, prefix + "_publish_delta")
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
                       ctypes.c_char_p, ctypes.c_size_t]
        fn = getattr(cdll, prefix + "_set_route_feature")
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                       ctypes.c_float]
        fn = getattr(cdll, prefix + "_set_route_hash")
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
        fn = getattr(cdll, prefix + "_set_tenant")
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p,
                       ctypes.c_int]
        fn = getattr(cdll, prefix + "_set_tenant_quota")
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int]
        fn = getattr(cdll, prefix + "_set_guard")
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_void_p] + [ctypes.c_long] * 6
        # stream sentinel: per-stream scoring cadence/hysteresis,
        # /streams.json snapshot, and the mid-stream RST queue
        fn = getattr(cdll, prefix + "_set_stream_cfg")
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_void_p, ctypes.c_long, ctypes.c_long,
                       ctypes.c_long, ctypes.c_long, ctypes.c_double,
                       ctypes.c_double, ctypes.c_long, ctypes.c_long,
                       ctypes.c_long]
        fn = getattr(cdll, prefix + "_streams_json")
        fn.restype = ctypes.c_long
        fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
        fn = getattr(cdll, prefix + "_rst_stream")
        fn.restype = ctypes.c_int
        fn.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    cdll.fp_set_tunnel_guard.restype = ctypes.c_int  # h1-only budgets
    cdll.fp_set_tunnel_guard.argtypes = \
        [ctypes.c_void_p, ctypes.c_long, ctypes.c_long]
    cdll.fph2_set_flood_guard.restype = ctypes.c_int
    cdll.fph2_set_flood_guard.argtypes = \
        [ctypes.c_void_p] + [ctypes.c_long] * 5
    cdll.l5d_tenant_hash.restype = ctypes.c_uint32
    cdll.l5d_tenant_hash.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    cdll.l5d_stream_accum.restype = ctypes.c_long
    cdll.l5d_stream_accum.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float), ctypes.c_long,
        ctypes.POINTER(ctypes.c_float)]


def _declare_tls(cdll: ctypes.CDLL, prefix: str) -> None:
    """TLS exports shared by both engines (fp_* / fph2_*)."""
    fn = getattr(cdll, prefix + "_tls_runtime_available")
    fn.restype = ctypes.c_int
    fn.argtypes = []
    fn = getattr(cdll, prefix + "_set_tls")
    fn.restype = ctypes.c_int
    fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
                   ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t]
    fn = getattr(cdll, prefix + "_listen_tls")
    fn.restype = ctypes.c_int
    fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    fn = getattr(cdll, prefix + "_set_client_tls")
    fn.restype = ctypes.c_int
    fn.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
                   ctypes.c_char_p, ctypes.c_char_p, ctypes.c_size_t]


def _declare_h2_fastpath(cdll: ctypes.CDLL) -> None:
    _declare_tls(cdll, "fph2")
    cdll.fph2_create.restype = ctypes.c_void_p
    cdll.fph2_create.argtypes = []
    cdll.fph2_start.restype = ctypes.c_int
    cdll.fph2_start.argtypes = [ctypes.c_void_p]
    cdll.fph2_listen.restype = ctypes.c_int
    cdll.fph2_listen.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_int]
    cdll.fph2_listen_shared.restype = ctypes.c_int
    cdll.fph2_listen_shared.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_int]
    cdll.fph2_listen_tls_shared.restype = ctypes.c_int
    cdll.fph2_listen_tls_shared.argtypes = [ctypes.c_void_p,
                                            ctypes.c_char_p, ctypes.c_int]
    cdll.fph2_attach_slab.restype = ctypes.c_int
    cdll.fph2_attach_slab.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    cdll.fph2_set_route.restype = ctypes.c_int
    cdll.fph2_set_route.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_char_p]
    cdll.fph2_remove_route.restype = ctypes.c_int
    cdll.fph2_remove_route.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    cdll.fph2_drain_misses.restype = ctypes.c_long
    cdll.fph2_drain_misses.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_size_t]
    cdll.fph2_stats_json.restype = ctypes.c_long
    cdll.fph2_stats_json.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_size_t]
    cdll.fph2_drain_features.restype = ctypes.c_long
    cdll.fph2_drain_features.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_float),
                                         ctypes.c_long]
    cdll.fph2_shutdown.restype = None
    cdll.fph2_shutdown.argtypes = [ctypes.c_void_p]
    cdll.fph2_set_response_timeout_ms.restype = None
    cdll.fph2_set_response_timeout_ms.argtypes = [ctypes.c_void_p,
                                                  ctypes.c_long]


def _declare_fastpath(cdll: ctypes.CDLL) -> None:
    _declare_tls(cdll, "fp")
    cdll.fp_create.restype = ctypes.c_void_p
    cdll.fp_create.argtypes = []
    cdll.fp_start.restype = ctypes.c_int
    cdll.fp_start.argtypes = [ctypes.c_void_p]
    cdll.fp_listen.restype = ctypes.c_int
    cdll.fp_listen.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_int]
    cdll.fp_listen_shared.restype = ctypes.c_int
    cdll.fp_listen_shared.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_int]
    cdll.fp_listen_tls_shared.restype = ctypes.c_int
    cdll.fp_listen_tls_shared.argtypes = [ctypes.c_void_p,
                                          ctypes.c_char_p, ctypes.c_int]
    cdll.fp_attach_slab.restype = ctypes.c_int
    cdll.fp_attach_slab.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    cdll.fp_set_route.restype = ctypes.c_int
    cdll.fp_set_route.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_char_p]
    cdll.fp_remove_route.restype = ctypes.c_int
    cdll.fp_remove_route.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    cdll.fp_drain_misses.restype = ctypes.c_long
    cdll.fp_drain_misses.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_size_t]
    cdll.fp_stats_json.restype = ctypes.c_long
    cdll.fp_stats_json.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_size_t]
    cdll.fp_drain_features.restype = ctypes.c_long
    cdll.fp_drain_features.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_float),
                                       ctypes.c_long]
    cdll.fp_shutdown.restype = None
    cdll.fp_shutdown.argtypes = [ctypes.c_void_p]


def auto_workers() -> int:
    """The ``workers: 0`` auto-size rule — one definition shared by
    the linker's knob resolution and l5dcheck's ``fastpath-workers``
    rule: min(4, hw cores)."""
    return min(4, os.cpu_count() or 1)


def _sum_hists(a, b):
    if not a:
        return list(b)
    if not b:
        return list(a)
    return [int(x) + int(y) for x, y in zip(a, b)]


def _merge_worker_stats(snaps: List[dict], n_workers: int) -> dict:
    """Merge N per-worker engine stats snapshots into one router-level
    view — the merge-at-scrape rule: the hot path never shares a
    counter; the control plane adds the per-core slabs up here, once a
    second. Counters and histograms sum; shared-slab scorer fields
    (weights/version/crc/swaps/retries live in the ONE process-wide
    slab) are taken from the first worker; per-tenant score EWMAs
    average weighted by each worker's scored count."""
    if not snaps:
        return {}
    out: dict = {"routes": {}}
    for key in ("accepted", "features_dropped"):
        out[key] = sum(int(s.get(key, 0)) for s in snaps)
    for s in snaps:
        for host, r in (s.get("routes") or {}).items():
            m = out["routes"].get(host)
            if m is None:
                out["routes"][host] = dict(r)
                continue
            for k in ("requests", "success", "f4xx", "f5xx",
                      "conn_fail"):
                m[k] = int(m.get(k, 0)) + int(r.get(k, 0))
            m["hist"] = _sum_hists(m.get("hist") or [],
                                   r.get("hist") or [])
    tls_snaps = [s["tls"] for s in snaps if s.get("tls")]
    if tls_snaps:
        tls = {k: sum(int(t.get(k, 0)) for t in tls_snaps)
               for k in ("handshakes", "failures", "resumed", "alpn_h2",
                         "alpn_http1", "upstream_handshakes",
                         "upstream_resumed", "upstream_failures")}
        tls["enabled"] = any(t.get("enabled") for t in tls_snaps)
        tls["client_enabled"] = any(t.get("client_enabled")
                                    for t in tls_snaps)
        out["tls"] = tls
    guard_snaps = [s["guard"] for s in snaps if s.get("guard")]
    if guard_snaps:
        keys = set()
        for g in guard_snaps:
            keys.update(g)
        out["guard"] = {k: sum(int(g.get(k, 0)) for g in guard_snaps)
                        for k in keys}
    tn_snaps = [s["tenants"] for s in snaps if s.get("tenants")]
    if tn_snaps:
        by: dict = {}
        for tn in tn_snaps:
            for thash, t in (tn.get("by_tenant") or {}).items():
                m = by.get(thash)
                if m is None:
                    by[thash] = dict(t)
                    continue
                # score_ewma: scored-weighted mean across workers
                w_old, w_new = int(m.get("scored", 0)), int(
                    t.get("scored", 0))
                if w_old + w_new > 0:
                    m["score_ewma"] = (
                        float(m.get("score_ewma", 0.0)) * w_old
                        + float(t.get("score_ewma", 0.0)) * w_new
                    ) / (w_old + w_new)
                for k in ("requests", "shed", "errors", "scored",
                          "inflight"):
                    m[k] = int(m.get(k, 0)) + int(t.get(k, 0))
                # per-worker quota splits are equal; fold to the max
                # here (-1 = unlimited wins), scaled back to the
                # global cap below
                qa, qb = int(m.get("quota", -1)), int(t.get("quota", -1))
                m["quota"] = -1 if (qa < 0 or qb < 0) else max(qa, qb)
        # quota: report the GLOBAL cap, per-worker split x the TRUE
        # worker count (not a sum over the workers whose bounded-LRU
        # stats table still happens to hold the tenant, nor over the
        # scrapes that succeeded this tick — quota maps survive stats
        # eviction, so every worker enforces the same split even when
        # only some reported the tenant)
        for t in by.values():
            q = int(t.get("quota", -1))
            if q >= 0:
                t["quota"] = q * n_workers
        out["tenants"] = {
            "count": len(by),
            "evicted": sum(int(t.get("evicted", 0)) for t in tn_snaps),
            "by_tenant": by,
        }
    ns_snaps = [s["native_scorer"] for s in snaps
                if s.get("native_scorer")]
    if ns_snaps:
        ns = dict(ns_snaps[0])  # slab fields (version/crc/generation/
        # heads/swaps/delta_swaps/retries): shared, identical
        ns["scored"] = sum(int(x.get("scored", 0)) for x in ns_snaps)
        ns["specialist_scored"] = sum(
            int(x.get("specialist_scored", 0)) for x in ns_snaps)
        ns["unscored"] = sum(int(x.get("unscored", 0)) for x in ns_snaps)
        hist = ns_snaps[0].get("score_ns_hist") or []
        for x in ns_snaps[1:]:
            hist = _sum_hists(hist, x.get("score_ns_hist") or [])
        ns["score_ns_hist"] = hist
        out["native_scorer"] = ns
    return out


class FastPathEngine:
    """Handle on the native epoll proxy data plane (native/fastpath.cpp).

    Python is the control plane: it binds listeners before start(), then
    installs/updates concrete routes (host -> [(ip, port), ...]) as the
    naming system publishes address changes, and periodically drains route
    misses, stats, and per-request feature rows.

    Multi-core sharding (``workers`` > 1): N per-core C++ engine
    instances, each with its own epoll loop, upstream pools, and
    stats/tenant/guard slabs; ``listen()`` binds every worker to the
    SAME port via SO_REUSEPORT so the kernel distributes connections —
    no shared counters on the hot path, no cache-line ping-pong.
    Control-plane calls (routes, quotas, TLS, guards) broadcast to all
    workers; drains fan in; ``stats()`` merges the per-worker slabs at
    scrape time and carries the raw per-worker snapshots under
    ``workers``. The scorer's double-buffered weight slab becomes ONE
    process-wide slab shared read-only across workers (attached before
    start), so a single ``publish_weights`` flips every core to the new
    blob atomically. ``workers=1`` is byte-for-byte today's single
    engine: the legacy (non-REUSEPORT) bind, the embedded slab, and the
    unmerged stats shape."""

    # engine feature-row width: route_id, latency_ms, status, req_b,
    # rsp_b, ts_s, score, scored, tenant, kind, stream, frame_seq
    # (score/scored are the in-data-plane scorer's output; scored ==
    # 0.0 rows fall back to the JAX tier; tenant is the 24-bit-folded
    # tenant hash, 0 = none; kind 0 = request, 1 = h2 stream sample,
    # 2 = tunnel sample; stream is the 24-bit stream key for kind > 0
    # rows, frame_seq the frame count at sample time)
    FEATURE_DIM = 12
    _PREFIX = "fp"  # C symbol prefix; the h2 engine overrides to "fph2"
    # ALPN preference list the engine's TLS contexts advertise/offer
    _ALPN = "http/1.1"

    MAX_WORKERS = 64

    def __init__(self, workers: int = 1):
        cdll = lib()
        if cdll is None:
            raise RuntimeError("native library unavailable; fastPath "
                               "requires a working toolchain")
        workers = int(workers)
        if not 1 <= workers <= self.MAX_WORKERS:
            raise ValueError(
                f"workers must be in 1..{self.MAX_WORKERS}, got {workers}")
        self._lib = cdll
        p = self._PREFIX
        self._fn_listen = getattr(cdll, p + "_listen")
        self._fn_listen_shared = getattr(cdll, p + "_listen_shared")
        self._fn_listen_tls_shared = getattr(cdll,
                                             p + "_listen_tls_shared")
        self._fn_start = getattr(cdll, p + "_start")
        self._fn_set_route = getattr(cdll, p + "_set_route")
        self._fn_remove_route = getattr(cdll, p + "_remove_route")
        self._fn_drain_misses = getattr(cdll, p + "_drain_misses")
        self._fn_stats = getattr(cdll, p + "_stats_json")
        self._fn_features = getattr(cdll, p + "_drain_features")
        self._fn_shutdown = getattr(cdll, p + "_shutdown")
        self._fn_publish = getattr(cdll, p + "_publish_weights")
        self._fn_publish_delta = getattr(cdll, p + "_publish_delta")
        self._fn_route_feat = getattr(cdll, p + "_set_route_feature")
        self._fn_route_hash = getattr(cdll, p + "_set_route_hash")
        self.workers = workers
        self._es = [getattr(cdll, p + "_create")()
                    for _ in range(workers)]
        self._e = self._es[0]  # single-worker compat handle
        # multi-worker: ONE process-wide weight slab, shared read-only
        # by every worker's epoll thread — one publish fans out to all
        # cores atomically (freed in close(), after every worker's loop
        # thread has joined)
        self._slab = None
        if workers > 1:
            self._slab = cdll.l5d_slab_create()
            attach = getattr(cdll, p + "_attach_slab")
            for h in self._es:
                attach(h, self._slab)
        self._started = False
        self._closed = False
        self._miss_buf = ctypes.create_string_buffer(64 * 1024)
        self._stats_buf = ctypes.create_string_buffer(1024 * 1024)
        self._feat_rows = 16384
        self._feat_buf = (ctypes.c_float
                          * (self._feat_rows * self.FEATURE_DIM))()

    def listen(self, ip: str, port: int) -> int:
        """Bind a listener; returns the bound port. Call before start().
        With ``workers`` > 1 every worker binds the same port via
        SO_REUSEPORT (the first worker resolves port 0 to a concrete
        port; the rest join it)."""
        assert not self._started
        if self.workers == 1:
            got = self._fn_listen(self._e, ip.encode(), port)
            if got < 0:
                raise OSError(f"fastpath listen {ip}:{port} failed")
            return got
        return self._listen_all(self._fn_listen_shared, ip, port)

    def _listen_all(self, fn, ip: str, port: int) -> int:
        got = fn(self._es[0], ip.encode(), port)
        if got < 0:
            raise OSError(f"fastpath listen {ip}:{port} failed")
        for h in self._es[1:]:
            if fn(h, ip.encode(), got) < 0:
                raise OSError(
                    f"fastpath shared listen {ip}:{got} failed")
        return got

    @classmethod
    def tls_runtime_available(cls) -> bool:
        """True when the engine could dlopen the OpenSSL runtime (TLS
        termination/origination available natively)."""
        cdll = lib()
        if cdll is None:
            return False
        return bool(getattr(cdll, cls._PREFIX + "_tls_runtime_available")())

    def set_tls(self, cert_path: str, key_path: str) -> None:
        """Install the accept-leg TLS context (PEM cert chain + key).
        Call before start(); listeners bound with listen_tls() then
        terminate TLS with this identity (ALPN per engine protocol)."""
        assert not self._started
        err = ctypes.create_string_buffer(512)
        fn = getattr(self._lib, self._PREFIX + "_set_tls")
        for h in self._es:
            rc = fn(h, cert_path.encode(), key_path.encode(),
                    self._ALPN.encode(), err, len(err))
            if rc != 0:
                raise OSError(
                    f"fastpath TLS config failed: "
                    f"{err.value.decode('latin-1') or 'unknown error'}")

    def listen_tls(self, ip: str, port: int) -> int:
        """Bind a TLS-terminating listener (requires set_tls first);
        returns the bound port. Call before start(). Multi-worker
        engines share the port via SO_REUSEPORT like listen()."""
        assert not self._started
        if self.workers == 1:
            got = getattr(self._lib, self._PREFIX + "_listen_tls")(
                self._e, ip.encode(), port)
            if got < 0:
                raise OSError(f"fastpath TLS listen {ip}:{port} failed")
            return got
        return self._listen_all(self._fn_listen_tls_shared, ip, port)

    def set_client_tls(self, verify: bool = True,
                       ca_path: Optional[str] = None) -> None:
        """Originate TLS to every upstream endpoint (router-wide
        client.tls). The route authority is sent as SNI and, when
        ``verify`` is set, pinned against the peer certificate;
        ``ca_path`` replaces the default trust roots. Call before
        start()."""
        assert not self._started
        err = ctypes.create_string_buffer(512)
        fn = getattr(self._lib, self._PREFIX + "_set_client_tls")
        for h in self._es:
            rc = fn(h, self._ALPN.encode(), 1 if verify else 0,
                    ca_path.encode() if ca_path else None, err, len(err))
            if rc != 0:
                raise OSError(
                    f"fastpath client TLS config failed: "
                    f"{err.value.decode('latin-1') or 'unknown error'}")

    def start(self) -> None:
        if not self._started:
            for h in self._es:
                if self._fn_start(h) != 0:
                    raise RuntimeError("fastpath thread start failed")
            self._started = True

    @staticmethod
    def _key(host: str) -> bytes:
        # Header bytes are latin-1; bytes.lower() folds ASCII only —
        # exactly matching the engine's lower() keying (fastpath.cpp).
        return host.encode("latin-1", "replace").lower()

    def set_route(self, host: str, endpoints: List[Tuple[str, int]]) -> None:
        # Broadcast in WORKER ORDER, always: each worker assigns route
        # ids by install order, so identical broadcast order keeps ids
        # in lockstep across workers — feature rows drained from any
        # worker then attribute to the same dst path.
        eps = " ".join(f"{ip}:{port}" for ip, port in endpoints) + " "
        for h in self._es:
            self._fn_set_route(h, self._key(host), eps.encode())

    TENANT_KINDS = {"off": 0, "header": 1, "pathSegment": 2, "sni": 3}

    def set_tenant(self, kind: str, header: str = "l5d-tenant",
                   segment: int = 0) -> None:
        """Install the tenant-extraction mode (call before start()):
        ``header`` hashes the named request header's value,
        ``pathSegment`` the ``segment``th path element, ``sni`` the TLS
        server name. The engine stamps the FNV-1a hash into per-request
        feature rows and the per-tenant stats table."""
        assert not self._started
        k = self.TENANT_KINDS.get(kind)
        if k is None:
            raise ValueError(f"unknown tenant extraction kind {kind!r}")
        fn = getattr(self._lib, self._PREFIX + "_set_tenant")
        for h in self._es:
            if fn(h, k, header.encode("latin-1", "replace"),
                  int(segment)) != 0:
                raise ValueError("tenant extraction config rejected")

    def set_tenant_quota(self, tenant_hash: int,
                         limit: Optional[int]) -> None:
        """Push (or clear, with ``limit=None``) a per-tenant
        concurrency quota, keyed by the tenant's 32-bit hash. The
        engine sheds over-quota requests retryably in the data plane
        (h1: 503 + l5d-retryable, h2: RST REFUSED_STREAM). Safe at any
        time; raises when the native quota map is full.

        Multi-worker engines split the limit N ways (floor division:
        per-worker tables are independent, so the global cap is never
        exceeded). A limit below ``workers`` rounds to a per-worker
        quota of ZERO — every worker sheds that tenant entirely; the
        l5dcheck ``fastpath-workers`` rule flags floor quotas that
        round to zero at config load."""
        if self._closed:
            raise RuntimeError("engine is closed")
        if limit is None:
            per_worker = -1
        else:
            per_worker = max(0, int(limit))
            if self.workers > 1:
                per_worker //= self.workers
        fn = getattr(self._lib, self._PREFIX + "_set_tenant_quota")
        for h in self._es:
            if fn(h, int(tenant_hash) & 0xFFFFFFFF, per_worker) != 0:
                raise ValueError("native tenant quota map is full")

    def set_guard(self, header_budget_ms: int = 10_000,
                  body_stall_ms: int = 30_000, accept_burst: int = 0,
                  accept_window_ms: int = 1000,
                  max_hs_inflight: int = 0,
                  tenant_cap: int = 1024) -> None:
        """Connection-plane defense knobs (call before start()): the
        slowloris header/body budgets, the per-source accept throttle,
        TLS handshake-churn backpressure, and the tenant-stats LRU
        bound. 0 disables an individual defense."""
        assert not self._started
        fn = getattr(self._lib, self._PREFIX + "_set_guard")
        for h in self._es:
            rc = fn(h, int(header_budget_ms), int(body_stall_ms),
                    int(accept_burst), int(accept_window_ms),
                    int(max_hs_inflight), int(tenant_cap))
            if rc != 0:
                raise ValueError("guard config rejected")

    STREAM_ACTIONS = {"observe": 0, "rst": 1}

    def set_stream_cfg(self, enabled: bool = True,
                       sample_every_frames: int = 8,
                       min_gap_ms: int = 10, table_cap: int = 4096,
                       enter: float = 0.8, exit: float = 0.5,
                       quorum: int = 3, dwell_ms: int = 1000,
                       action: str = "rst") -> None:
        """Stream-sentinel knobs (call before start()): per-stream
        scoring cadence (every N frames, min gap between samples), the
        bounded stream-table cap, and the native hysteresis governor
        mirroring control.state.HysteresisGovernor (0 < exit < enter
        <= 1, quorum consecutive samples, dwell after a transition).
        ``action`` "rst" sheds a sick stream in-engine (h2: RST_STREAM
        / gRPC UNAVAILABLE trailers; h1: tunnel close); "observe" only
        records transitions."""
        assert not self._started
        a = self.STREAM_ACTIONS.get(action)
        if a is None:
            raise ValueError(f"unknown stream action {action!r}")
        fn = getattr(self._lib, self._PREFIX + "_set_stream_cfg")
        for h in self._es:
            rc = fn(h, 1 if enabled else 0, int(sample_every_frames),
                    int(min_gap_ms), int(table_cap), float(enter),
                    float(exit), int(quorum), int(dwell_ms), a)
            if rc != 0:
                raise ValueError("stream config rejected")

    def streams(self) -> dict:
        """Stream-table snapshot (/streams.json shape). Multi-worker
        engines carry per-worker snapshots under ``workers`` — stream
        keys are per-worker sequences, so by_stream maps must not be
        merged across workers — with engine-wide counters summed."""
        import json
        fn = getattr(self._lib, self._PREFIX + "_streams_json")

        def one(h) -> dict:
            for _ in range(6):
                n = fn(h, self._stats_buf, len(self._stats_buf))
                if n == -2:
                    if len(self._stats_buf) >= 64 << 20:
                        return {}
                    self._stats_buf = ctypes.create_string_buffer(
                        len(self._stats_buf) * 4)
                    continue
                if n < 0:
                    return {}
                return json.loads(self._stats_buf.value.decode("latin-1"))
            return {}

        if self.workers == 1:
            return one(self._e)
        snaps = [one(h) for h in self._es]
        out: dict = {"enabled": any(s.get("enabled") for s in snaps)}
        for k in ("count", "evicted", "sick_transitions", "rst_sent",
                  "tunnels_opened", "tunnel_idle_closed",
                  "tunnel_bytes_closed"):
            out[k] = sum(int(s.get(k, 0)) for s in snaps)
        out["workers"] = snaps
        return out

    def rst_stream(self, skey: int, worker: Optional[int] = None) -> None:
        """Queue a mid-stream shed by 24-bit stream key (the ``stream``
        column of kind > 0 feature rows): the engine's loop thread
        RSTs the h2 stream (gRPC UNAVAILABLE trailers when possible)
        or closes the tunnel. Keys are per-worker sequences — pass
        ``worker`` when the engine is sharded; a broadcast would shed
        whatever stream holds that key on EVERY worker."""
        if self._closed:
            raise RuntimeError("engine is closed")
        fn = getattr(self._lib, self._PREFIX + "_rst_stream")
        handles = self._es if worker is None \
            else [self._es[int(worker)]]
        for h in handles:
            fn(h, int(skey) & 0xFFFFFF)

    def set_tunnel_guard(self, idle_ms: int = 0,
                         max_bytes: int = 0) -> None:
        """Byte-tunnel budgets (h1 engine only; call before start()):
        zero-activity window and lifetime byte cap for CONNECT /
        101-upgrade tunnels. 0 disables the individual cap. Enforced
        even when stream scoring is off — these are connection-plane
        defenses like the slowloris budgets."""
        assert not self._started
        if self._PREFIX != "fp":
            raise RuntimeError(
                "tunnel budgets are an h1-engine knob (h2 streams are "
                "bounded by the flood guard and response timeout)")
        for h in self._es:
            if self._lib.fp_set_tunnel_guard(h, int(idle_ms),
                                             int(max_bytes)) != 0:
                raise ValueError("tunnel guard config rejected")

    def set_route_feature(self, host: str, col: int, sign: float) -> bool:
        """Install the dst-path feature-hash (column, sign) for a route
        so the in-engine scorer can featurize its rows; call after
        set_route. Returns False while the route does not exist (on
        any worker — set_route broadcasts, so all workers agree)."""
        ok = True
        for h in self._es:
            if self._fn_route_feat(h, self._key(host), int(col),
                                   float(sign)) != 0:
                ok = False
        return ok

    def set_route_hash(self, host: str, rhash: int) -> bool:
        """Install a route's specialist-bank key (FNV-1a of the bound
        dst path, ``lifecycle.export.route_hash``); call after
        set_route. Until this lands the route's rows score on the
        bank's base model. Returns False while the route does not
        exist on some worker."""
        ok = True
        for h in self._es:
            if self._fn_route_hash(h, self._key(host),
                                   int(rhash) & 0xFFFFFFFF) != 0:
                ok = False
        return ok

    def publish_weights(self, blob: bytes) -> None:
        """Hot-swap the in-engine scorer's weights from a versioned
        blob — a v1 model or a v2 specialist bank
        (lifecycle/export.export_weight_blob / export_bank_blob).
        Raises ValueError on a rejected blob (bad magic/CRC/geometry);
        the data plane never pauses — scoring flips to the new weights
        per-row. With ``workers`` > 1 the publish goes ONCE into the
        shared slab and every worker observes the new blob atomically."""
        if self._closed:
            # a stale sink calling into a freed C++ engine would be a
            # native use-after-free, not a catchable Python error
            raise RuntimeError("engine is closed")
        err = ctypes.create_string_buffer(256)
        if self._slab is not None:
            rc = self._lib.l5d_slab_publish(self._slab, blob, len(blob),
                                            err, len(err))
        else:
            rc = self._fn_publish(self._e, blob, len(blob), err,
                                  len(err))
        if rc != 0:
            raise ValueError(
                f"weight blob rejected: "
                f"{err.value.decode('latin-1') or 'unknown error'}")

    def publish_delta(self, blob: bytes) -> None:
        """Apply a per-route delta patch (``L5DWTD01``) to the ACTIVE
        bank — generation-fenced: raises ValueError when the patch was
        built against a different bank generation (the caller falls
        back to a full publish), when it removes an absent head, or on
        any corruption. One apply flips every worker (shared slab)."""
        if self._closed:
            raise RuntimeError("engine is closed")
        err = ctypes.create_string_buffer(256)
        if self._slab is not None:
            rc = self._lib.l5d_slab_publish_delta(
                self._slab, blob, len(blob), err, len(err))
        else:
            rc = self._fn_publish_delta(self._e, blob, len(blob), err,
                                        len(err))
        if rc != 0:
            raise ValueError(
                f"delta blob rejected: "
                f"{err.value.decode('latin-1') or 'unknown error'}")

    def remove_route(self, host: str) -> None:
        for h in self._es:
            self._fn_remove_route(h, self._key(host))

    def drain_misses(self) -> List[str]:
        if self.workers == 1:
            n = self._fn_drain_misses(self._e, self._miss_buf,
                                      len(self._miss_buf))
            if n <= 0:
                return []
            return self._miss_buf.value.decode("latin-1").split("\n")[:n]
        # fan-in: the same host typically misses on several workers at
        # once (the kernel spread its first connections); one entry is
        # enough — set_route broadcasts the resolution to all of them
        out: List[str] = []
        seen = set()
        for h in self._es:
            n = self._fn_drain_misses(h, self._miss_buf,
                                      len(self._miss_buf))
            if n <= 0:
                continue
            for host in self._miss_buf.value.decode(
                    "latin-1").split("\n")[:n]:
                if host not in seen:
                    seen.add(host)
                    out.append(host)
        return out

    def _stats_one(self, handle) -> dict:
        import json
        for _ in range(6):
            n = self._fn_stats(handle, self._stats_buf,
                               len(self._stats_buf))
            if n == -2:  # buffer too small: grow (capped at 64MB)
                if len(self._stats_buf) >= 64 << 20:
                    log.warning("fastpath stats exceed 64MB; dropping")
                    return {}
                self._stats_buf = ctypes.create_string_buffer(
                    len(self._stats_buf) * 4)
                continue
            if n < 0:
                return {}
            return json.loads(self._stats_buf.value.decode("latin-1"))
        return {}

    def stats(self) -> dict:
        """Engine stats snapshot. ``workers == 1``: the single engine's
        snapshot, unchanged. ``workers > 1``: per-worker slabs merged at
        scrape time (counters summed, histograms added element-wise,
        shared-slab fields taken once), with the raw per-worker
        snapshots under ``workers`` for ``worker/<i>/*`` breakdowns."""
        if self.workers == 1:
            return self._stats_one(self._e)
        snaps = [self._stats_one(h) for h in self._es]
        if any(not s for s in snaps):
            # a PARTIAL merge would report totals below the
            # controller's delta baselines, and the next full scrape
            # would then re-count the missing worker's whole history
            # as one giant delta — skip this scrape entirely instead
            # (an empty snapshot is the established failure shape:
            # every consumer skips it and keeps its baselines)
            return {}
        merged = _merge_worker_stats(snaps, self.workers)
        merged["workers"] = snaps
        return merged

    def drain_features(self):
        """-> float32 ndarray [n, FEATURE_DIM] of per-request rows
        (fan-in over every worker's ring segment)."""
        import numpy as np
        blocks = []
        for h in self._es:
            n = self._fn_features(h, self._feat_buf, self._feat_rows)
            if n > 0:
                arr = np.ctypeslib.as_array(self._feat_buf)
                blocks.append(arr[:n * self.FEATURE_DIM].reshape(
                    n, self.FEATURE_DIM).copy())
        if not blocks:
            return np.zeros((0, self.FEATURE_DIM), dtype=np.float32)
        return blocks[0] if len(blocks) == 1 else np.concatenate(blocks)

    def drain_features_into(self, out) -> int:
        """Drain up to ``len(out)`` feature rows directly into ``out``
        (a C-contiguous float32 [rows, FEATURE_DIM] ndarray — in
        practice a writable view of the telemeter's NativeFeatureRing):
        the engine memcpys rows straight into ring memory, no
        intermediate buffer and no per-row Python objects. Returns the
        number of rows written."""
        import numpy as np
        if len(out) == 0:
            return 0
        if out.dtype != np.float32:
            raise ValueError(f"want float32 rows, got {out.dtype}")
        if out.ndim != 2 or out.shape[1] != self.FEATURE_DIM \
                or not out.flags["C_CONTIGUOUS"]:
            raise ValueError(
                f"want C-contiguous [n, {self.FEATURE_DIM}] f32, got "
                f"shape {out.shape}")
        # fan-in: fill `out` from each worker's per-core ring segment in
        # turn until it is full (each drain memcpys straight into ring
        # memory at the right row offset — still zero-copy per worker)
        total = 0
        row_bytes = self.FEATURE_DIM * 4
        base = out.ctypes.data
        for h in self._es:
            if total >= len(out):
                break
            ptr = ctypes.cast(base + total * row_bytes,
                              ctypes.POINTER(ctypes.c_float))
            n = self._fn_features(h, ptr, len(out) - total)
            if n > 0:
                total += int(n)
        return total

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            # every worker's epoll thread joins before the shared slab
            # is freed: no core can be mid-eval on freed weights
            for h in self._es:
                self._fn_shutdown(h)
            if self._slab is not None:
                self._lib.l5d_slab_free(self._slab)
                self._slab = None


class H2FastPathEngine(FastPathEngine):
    """Handle on the native h2/gRPC proxy data plane
    (native/h2_fastpath.cpp).

    Same control surface as FastPathEngine — FastPathController drives
    either interchangeably — but the engine speaks HTTP/2 (h2c prior
    knowledge) on both sides and routes by ``:authority``."""

    _PREFIX = "fph2"
    _ALPN = "h2"

    def set_flood_guard(self, max_streams: int = 512,
                        rst_burst: int = 200, ping_burst: int = 256,
                        settings_burst: int = 64,
                        window_ms: int = 1000) -> None:
        """h2 control-frame flood caps, per client conn per window:
        stream-concurrency bound, RST (rapid-reset, CVE-2023-44487),
        PING and SETTINGS bursts. 0 disables one cap. Call before
        start()."""
        assert not self._started
        for h in self._es:
            rc = self._lib.fph2_set_flood_guard(
                h, int(max_streams), int(rst_burst), int(ping_burst),
                int(settings_burst), int(window_ms))
            if rc != 0:
                raise ValueError("flood guard config rejected")

    def set_response_timeout_ms(self, ms: int) -> None:
        """Window within which a dispatched stream's backend must START
        its response (504 otherwise); streaming bodies are unbounded.
        Must be >= 1 (0 would time out everything immediately)."""
        ms = int(ms)
        if ms < 1:
            raise ValueError("response timeout must be >= 1 ms")
        for h in self._es:
            self._lib.fph2_set_response_timeout_ms(h, ms)


MAX_HEADERS = 1024
_SPANS = ctypes.c_int32 * (6 + MAX_HEADERS * 4)


def parse_http1_head(head: bytes
                     ) -> Optional[Tuple[str, str, str,
                                         List[Tuple[str, str]]]]:
    """Parse a full request head block -> (method, uri, version, headers).
    None => native unavailable or malformed (caller falls back)."""
    cdll = lib()
    if cdll is None:
        return None
    spans = _SPANS()
    n = cdll.l5d_parse_http1_head(head, len(head), spans, MAX_HEADERS)
    if n < 0:
        return None
    method = head[spans[0]:spans[0] + spans[1]].decode("latin-1")
    uri = head[spans[2]:spans[2] + spans[3]].decode("latin-1")
    version = head[spans[4]:spans[4] + spans[5]].decode("latin-1")
    headers = []
    for i in range(n):
        o = 6 + i * 4
        name = head[spans[o]:spans[o] + spans[o + 1]].decode("latin-1")
        val = head[spans[o + 2]:spans[o + 2] + spans[o + 3]].decode("latin-1")
        headers.append((name, val))
    return method, uri, version, headers


def tenant_hash_native(tenant_id: bytes) -> Optional[int]:
    """The C engines' FNV-1a tenant hash (parity surface for
    router.tenancy.tenant_hash); None = native unavailable."""
    cdll = lib()
    if cdll is None:
        return None
    return int(cdll.l5d_tenant_hash(tenant_id, len(tenant_id)))


def stream_accum(kinds, gaps_ms, sizes):
    """Drive the engines' per-frame stream accumulator
    (l5dstream::accum_frame) over a whole frame trace — the parity
    surface for linkerd_tpu.streams.tracker.StreamTracker, which must
    reproduce the float32 EWMA arithmetic bit-for-bit. ``kinds`` are
    ints (0 DATA / 1 WINDOW_UPDATE / 2 anomaly), ``gaps_ms``/``sizes``
    per-frame floats. Returns f32 [9]: [gap_ewma_ms, gap_dev_ms,
    bpf_ewma, bpf_dev, frames, data_frames, wu_frames, anomalies,
    bytes]; None = native unavailable; ValueError on a bad kind."""
    import numpy as np
    cdll = lib()
    if cdll is None:
        return None
    k = np.ascontiguousarray(kinds, np.int32)
    g = np.ascontiguousarray(gaps_ms, np.float32)
    s = np.ascontiguousarray(sizes, np.float32)
    if not (len(k) == len(g) == len(s)):
        raise ValueError("kinds/gaps/sizes length mismatch")
    out = np.zeros(9, np.float32)
    rc = cdll.l5d_stream_accum(
        k.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        _as_f32_ptr(g), _as_f32_ptr(s), len(k), _as_f32_ptr(out))
    if rc != 0:
        raise ValueError("bad frame kind in trace")
    return out


# -- in-data-plane scorer (engine-independent surface) ------------------------


def _as_f32_ptr(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def score_feature_dim() -> Optional[int]:
    """The C featurizer's FEATURE_DIM (None = native unavailable)."""
    cdll = lib()
    return None if cdll is None else int(cdll.l5d_score_feature_dim())


def score_blob_info(blob: bytes) -> Optional[dict]:
    """Parse+validate a weight blob. Returns its header dict, or raises
    ValueError with the parser's reason; None = native unavailable."""
    import json
    cdll = lib()
    if cdll is None:
        return None
    out = ctypes.create_string_buffer(512)
    n = cdll.l5d_score_blob_info(blob, len(blob), out, len(out))
    if n < 0:
        raise ValueError(out.value.decode("latin-1"))
    return json.loads(out.value.decode("latin-1"))


def score_eval(blob: bytes, x) -> Optional["object"]:
    """Score featurized rows (f32 [n, in_dim]) with the C evaluator.
    Returns f32 [n] scores; ValueError on a rejected blob; None when
    the native lib is unavailable."""
    import numpy as np
    cdll = lib()
    if cdll is None:
        return None
    x = np.ascontiguousarray(x, np.float32)
    out = np.zeros(len(x), np.float32)
    err = ctypes.create_string_buffer(256)
    n = cdll.l5d_score_eval(blob, len(blob), _as_f32_ptr(x), len(x),
                            x.shape[1], _as_f32_ptr(out), err, len(err))
    if n < 0:
        raise ValueError(err.value.decode("latin-1"))
    return out


def score_eval_raw(blob: bytes, rows, cols, signs, drifts,
                   return_features: bool = False):
    """Score RAW engine rows (f32 [n, 12] FeatureRow layout) through the
    in-engine featurizer, with per-row dst-hash (cols/signs) and
    pre-update drift supplied by the caller — the parity surface for the
    C featurizer. Returns scores [n] (and features [n, FEATURE_DIM]
    when requested); None = native unavailable."""
    import numpy as np
    cdll = lib()
    if cdll is None:
        return None
    rows = np.ascontiguousarray(rows, np.float32)
    n = len(rows)
    cols = np.ascontiguousarray(cols, np.int32)
    signs = np.ascontiguousarray(signs, np.float32)
    drifts = np.ascontiguousarray(drifts, np.float32)
    scores = np.zeros(n, np.float32)
    dim = score_feature_dim()
    feats = np.zeros((n, dim), np.float32) if return_features else None
    err = ctypes.create_string_buffer(256)
    rc = cdll.l5d_score_eval_raw(
        blob, len(blob), _as_f32_ptr(rows), n,
        cols.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        _as_f32_ptr(signs), _as_f32_ptr(drifts), _as_f32_ptr(scores),
        _as_f32_ptr(feats) if feats is not None else None, err, len(err))
    if rc < 0:
        raise ValueError(err.value.decode("latin-1"))
    return (scores, feats) if return_features else scores


def score_eval_route(blob: bytes, route_hash: int, x):
    """Score featurized rows through a bank blob's head for
    ``route_hash`` (base model when the bank has no such head).
    Returns (scores [n], specialist bool); ValueError on a rejected
    blob; None when the native lib is unavailable."""
    import numpy as np
    cdll = lib()
    if cdll is None:
        return None
    x = np.ascontiguousarray(x, np.float32)
    out = np.zeros(len(x), np.float32)
    spec = ctypes.c_int32(0)
    err = ctypes.create_string_buffer(256)
    n = cdll.l5d_score_eval_route(
        blob, len(blob), int(route_hash) & 0xFFFFFFFF, _as_f32_ptr(x),
        len(x), x.shape[1], _as_f32_ptr(out), ctypes.byref(spec), err,
        len(err))
    if n < 0:
        raise ValueError(err.value.decode("latin-1"))
    return out, bool(spec.value)


_QUANT_CODES = {"f32": 0, "int8": 1, "int4": 2}


def score_test_blob(version: int = 1, quant: str = "f32",
                    seed: int = 0) -> Optional[bytes]:
    """Deterministic valid weight blob from the C-side generator (the
    stress drivers' model) — lets tests exercise publish/score without
    a JAX snapshot. None = native unavailable."""
    cdll = lib()
    if cdll is None:
        return None
    buf = ctypes.create_string_buffer(1 << 20)
    n = cdll.l5d_score_test_blob(buf, len(buf), int(version),
                                 _QUANT_CODES[quant], int(seed))
    if n < 0:
        raise ValueError("test blob generation failed")
    return buf.raw[:n]


def score_test_bank(generation: int = 1, quant: str = "f32",
                    seed: int = 0, n_heads: int = 2) -> Optional[bytes]:
    """Deterministic valid v2 bank blob (seeded base + ``n_heads``
    specialists keyed 1000+k). None = native unavailable."""
    cdll = lib()
    if cdll is None:
        return None
    buf = ctypes.create_string_buffer(16 << 20)
    n = cdll.l5d_score_test_bank(buf, len(buf), int(generation),
                                 _QUANT_CODES[quant], int(seed),
                                 int(n_heads))
    if n < 0:
        raise ValueError("test bank generation failed")
    return buf.raw[:n]


def score_test_delta(base_gen: int, new_gen: int, route_hash: int,
                     quant: str = "f32", seed: int = 0,
                     remove: bool = False) -> Optional[bytes]:
    """Deterministic valid delta patch: one seeded upsert (or remove)
    at ``route_hash``, fenced base_gen -> new_gen. None = native
    unavailable."""
    cdll = lib()
    if cdll is None:
        return None
    buf = ctypes.create_string_buffer(1 << 20)
    n = cdll.l5d_score_test_delta(buf, len(buf), int(base_gen),
                                  int(new_gen),
                                  int(route_hash) & 0xFFFFFFFF,
                                  _QUANT_CODES[quant], int(seed),
                                  1 if remove else 0)
    if n < 0:
        raise ValueError("test delta generation failed")
    return buf.raw[:n]


class ScoreSlab:
    """Standalone handle on the double-buffered weight slab — the same
    hot-swap machinery the engines embed, without an engine. Used by the
    torn-weights concurrency tests and the bench's evaluator probe."""

    def __init__(self):
        cdll = lib()
        if cdll is None:
            raise RuntimeError("native library unavailable")
        self._lib = cdll
        self._s = cdll.l5d_slab_create()

    def _handle(self):
        if self._s is None:
            raise RuntimeError("slab is closed")
        return self._s

    def publish(self, blob: bytes) -> None:
        # the C side rejects a valid blob whose in_dim disagrees with
        # the featurizer width (l5d_slab_score strides by FEATURE_DIM)
        s = self._handle()
        err = ctypes.create_string_buffer(256)
        if self._lib.l5d_slab_publish(s, blob, len(blob), err,
                                      len(err)) != 0:
            raise ValueError(
                f"weight blob rejected: "
                f"{err.value.decode('latin-1') or 'unknown error'}")

    def publish_delta(self, blob: bytes) -> None:
        """Apply a generation-fenced per-route delta patch to the
        active bank; ValueError on rejection (fence/corruption/absent
        head) — the serving bank is untouched then."""
        s = self._handle()
        err = ctypes.create_string_buffer(256)
        if self._lib.l5d_slab_publish_delta(s, blob, len(blob), err,
                                            len(err)) != 0:
            raise ValueError(
                f"delta blob rejected: "
                f"{err.value.decode('latin-1') or 'unknown error'}")

    def score_route(self, x, route_hash: int):
        """Score featurized rows with per-route head selection.
        Returns (scores [n], specialist flags [n] int32) or None while
        no weights are published."""
        import numpy as np
        s = self._handle()
        x = np.ascontiguousarray(x, np.float32)
        dim = int(self._lib.l5d_score_feature_dim())
        if x.ndim != 2 or x.shape[1] != dim:
            raise ValueError(
                f"expected [n, {dim}] featurized rows, got {x.shape}")
        out = np.zeros(len(x), np.float32)
        spec = np.zeros(len(x), np.int32)
        n = self._lib.l5d_slab_score_route(
            s, int(route_hash) & 0xFFFFFFFF, _as_f32_ptr(x), len(x),
            _as_f32_ptr(out),
            spec.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return None if n < 0 else (out, spec)

    def score(self, x) -> Optional["object"]:
        """Score featurized f32 [n, FEATURE_DIM] rows; None while no
        weights are published. Rejects wrong-width input up front — the
        C side strides by FEATURE_DIM unchecked (an engine-row-shaped
        [n, 12] array would read out of bounds)."""
        import numpy as np
        s = self._handle()
        x = np.ascontiguousarray(x, np.float32)
        dim = int(self._lib.l5d_score_feature_dim())
        if x.ndim != 2 or x.shape[1] != dim:
            raise ValueError(
                f"expected [n, {dim}] featurized rows, got {x.shape}")
        out = np.zeros(len(x), np.float32)
        n = self._lib.l5d_slab_score(s, _as_f32_ptr(x), len(x),
                                     _as_f32_ptr(out))
        return None if n < 0 else out

    def stats(self) -> dict:
        import json
        out = ctypes.create_string_buffer(256)
        self._lib.l5d_slab_stats(self._handle(), out, len(out))
        return json.loads(out.value.decode("latin-1"))

    def close(self) -> None:
        if self._s is not None:
            self._lib.l5d_slab_free(self._s)
            self._s = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
