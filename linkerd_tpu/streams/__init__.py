"""Stream sentinel: incremental scoring and mid-stream actuation for
long-lived streams.

Request-shaped scoring (one feature row at exchange completion) is
blind to everything that happens *inside* a long-lived h2/gRPC stream,
WebSocket upgrade, or CONNECT tunnel — which is where most of the
bytes are. This package is the Python half of the stream-tracking
layer (the native half lives in ``native/stream_track.h``, embedded in
both epoll engines):

- :mod:`.tracker` — per-frame feature deltas (gap EWMA, bytes/frame
  drift, WINDOW_UPDATE cadence, reset / flow-control anomalies) in
  float32 arithmetic bit-identical to the C accumulator;
- :mod:`.sentinel` — the score-EWMA hysteresis governor (reusing
  ``control.state.HysteresisGovernor``) that sheds sick streams
  mid-flight: RST with gRPC UNAVAILABLE trailers when possible,
  connection drain, or tenant-quota shrink.
"""

from linkerd_tpu.streams.observer import H2FrameObserver
from linkerd_tpu.streams.sentinel import (
    ACTION_DRAIN, ACTION_OBSERVE, ACTION_QUOTA, ACTION_RST, ACTIONS,
    StreamEntry, StreamSentinel,
)
from linkerd_tpu.streams.tracker import (
    FRAME_ANOMALY, FRAME_DATA, FRAME_WINDOW_UPDATE, ROW_REQUEST,
    ROW_STREAM, ROW_TUNNEL, StreamTracker, fold_key,
    stream_feature_vector,
)

__all__ = [
    "ACTION_DRAIN", "ACTION_OBSERVE", "ACTION_QUOTA", "ACTION_RST",
    "ACTIONS", "FRAME_ANOMALY", "FRAME_DATA", "FRAME_WINDOW_UPDATE",
    "H2FrameObserver",
    "ROW_REQUEST", "ROW_STREAM", "ROW_TUNNEL", "StreamEntry",
    "StreamSentinel", "StreamTracker", "fold_key",
    "stream_feature_vector",
]
