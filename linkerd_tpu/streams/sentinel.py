"""Mid-stream actuation: the stream sentinel governor.

The tracker (:mod:`linkerd_tpu.streams.tracker`) turns frames into
features; the scorer turns features into an anomaly score; this module
turns the *sequence* of scores a long-lived stream produces into an
actuation decision while the stream is still open. It reuses
:class:`linkerd_tpu.control.state.HysteresisGovernor` — the same
split-threshold / quorum / dwell machine every other actuator in the
mesh runs on — keyed by stream-lifetime key, so a stream whose score
EWMA crosses ``enter`` for ``quorum`` consecutive samples is declared
SICK and shed (RST with gRPC UNAVAILABLE trailers when the engine can,
connection drain, or tenant-quota shrink), and flapping scores change
nothing.

The sentinel's stream table is bounded: hostile stream churn (a client
opening and abandoning streams to bloat the table) buys eviction of
the stalest *closed* entries, never growth. Evicted keys are
``forget()``-ed from the governor so it stays bounded too — the same
contract ``HysteresisGovernor.forget`` documents for tenant churn.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

import numpy as np

from linkerd_tpu.control.state import SICK, HysteresisGovernor
from linkerd_tpu.streams.tracker import ROW_STREAM

# Actuation modes (mirror the native StreamCfg.action values, plus the
# Python-plane-only drain/quota modes the native engines delegate up).
ACTION_OBSERVE = "observe"
ACTION_RST = "rst"
ACTION_DRAIN = "drain"
ACTION_QUOTA = "quota"
ACTIONS = (ACTION_OBSERVE, ACTION_RST, ACTION_DRAIN, ACTION_QUOTA)

# Score-EWMA smoothing: alpha 1/4 in float32, mirroring the native
# gov_observe so a score sequence produces the same level either side.
_SCORE_ALPHA = np.float32(0.25)


@dataclass
class StreamEntry:
    """Per-stream sentinel state."""

    key: int
    kind: int = ROW_STREAM
    route: Optional[str] = None     # pinned at stream open: the route
    #                                 (specialist head) scoring sticks to
    tenant: int = 0
    score_ewma: np.float32 = field(
        default_factory=lambda: np.float32(0.0))
    samples: int = 0
    scored: int = 0
    frames: int = 0
    bytes: int = 0
    live: bool = True
    shed: bool = False
    last_seen: float = 0.0


class StreamSentinel:
    """Score-driven mid-stream governor over a bounded stream table.

    ``observe`` is the hot path: fold one score sample in, run the
    hysteresis machine, and on a healthy->SICK edge fire the configured
    actuation callback exactly once per transition. Callbacks receive
    the :class:`StreamEntry`; what "RST" or "drain" concretely means is
    the caller's business (the fastpath router forwards RST to the
    native engine; the Python h2 server resets its own stream).
    """

    def __init__(self, enter: float = 0.8, exit: float = 0.5,
                 quorum: int = 3, dwell_s: float = 1.0,
                 table_cap: int = 4096, action: str = ACTION_RST,
                 on_rst: Optional[Callable[[StreamEntry], None]] = None,
                 on_drain: Optional[Callable[[StreamEntry], None]] = None,
                 on_quota: Optional[Callable[[StreamEntry], None]] = None):
        if action not in ACTIONS:
            raise ValueError(
                f"action must be one of {ACTIONS} (got {action!r})")
        if table_cap < 1:
            raise ValueError("table_cap must be >= 1")
        # threshold/quorum/dwell validation lives in the governor —
        # one place, same errors as every other actuator
        self._gov = HysteresisGovernor(enter=enter, exit=exit,
                                       quorum=quorum, dwell_s=dwell_s)
        self.action = action
        self.table_cap = table_cap
        self._on = {ACTION_RST: on_rst, ACTION_DRAIN: on_drain,
                    ACTION_QUOTA: on_quota}
        self._streams: "OrderedDict[int, StreamEntry]" = OrderedDict()
        self.sick_transitions = 0
        self.actions_fired = 0
        self.evicted = 0

    # ---- lifecycle ----------------------------------------------------------

    def open(self, key: int, kind: int = ROW_STREAM,
             route: Optional[str] = None, tenant: int = 0,
             now: Optional[float] = None) -> StreamEntry:
        """Register a stream at open time, pinning its route (and so
        its specialist head) for the stream's lifetime. Idempotent per
        key: re-opening an existing key refreshes liveness but keeps
        the pinned route — mid-stream re-routing must not flip which
        head scores it."""
        now = time.monotonic() if now is None else now
        ent = self._streams.get(key)
        if ent is None:
            ent = StreamEntry(key=key, kind=kind, route=route,
                              tenant=tenant, last_seen=now)
            self._streams[key] = ent
            self._evict_over_cap()
        else:
            self._streams.move_to_end(key)
        ent.live = True
        ent.last_seen = now
        return ent

    def close(self, key: int, now: Optional[float] = None) -> None:
        """Mark a stream closed. The entry stays (bounded by the LRU)
        so /streams.json can show recently-finished streams; only
        closed entries are eviction candidates."""
        ent = self._streams.get(key)
        if ent is not None:
            ent.live = False
            ent.last_seen = time.monotonic() if now is None else now

    # ---- scoring ------------------------------------------------------------

    def observe(self, key: int, score: float, scored: bool = True,
                frames: int = 0, nbytes: int = 0,
                now: Optional[float] = None) -> Optional[str]:
        """Fold one score sample for ``key``; returns the actuation
        mode fired on a healthy->SICK edge (``None`` otherwise).
        Unscored samples (no weights published yet) refresh liveness
        but never move the governor."""
        now = time.monotonic() if now is None else now
        ent = self._streams.get(key)
        if ent is None:
            ent = self.open(key, now=now)
        else:
            self._streams.move_to_end(key)
        ent.samples += 1
        ent.frames = max(ent.frames, int(frames))
        ent.bytes = max(ent.bytes, int(nbytes))
        ent.last_seen = now
        if not scored:
            return None
        ent.scored += 1
        ent.score_ewma = np.float32(
            ent.score_ewma
            + np.float32(_SCORE_ALPHA
                         * np.float32(np.float32(score) - ent.score_ewma)))
        was_shed = ent.shed
        state = self._gov.observe(str(key), float(ent.score_ewma), now=now)
        if state == SICK and not was_shed:
            ent.shed = True
            self.sick_transitions += 1
            return self._fire(ent)
        if state != SICK:
            ent.shed = False
        return None

    def _fire(self, ent: StreamEntry) -> Optional[str]:
        if self.action == ACTION_OBSERVE:
            return ACTION_OBSERVE
        cb = self._on.get(self.action)
        if cb is not None:
            cb(ent)
            self.actions_fired += 1
        return self.action

    # ---- native-row ingestion ----------------------------------------------

    def ingest_rows(self, rows, now: Optional[float] = None) -> int:
        """Feed drained native feature rows (f32 [n, 12]) — stream and
        tunnel samples only; request rows pass through untouched.
        Returns the number of actuations fired. The engines score and
        actuate in-plane already; this keeps the Python-side table (and
        any drain/quota escalation) in sync with what they saw."""
        from linkerd_tpu.telemetry.linerate import (
            NATIVE_COL_KIND, NATIVE_COL_SCORE, NATIVE_COL_SCORED,
            NATIVE_COL_SEQ, NATIVE_COL_STREAM, NATIVE_COL_TENANT)
        fired = 0
        now = time.monotonic() if now is None else now
        for r in rows:
            kind = int(r[NATIVE_COL_KIND])
            if kind == 0:
                continue
            key = int(r[NATIVE_COL_STREAM])
            if key == 0:
                continue
            ent = self._streams.get(key)
            if ent is None:
                ent = self.open(key, kind=kind,
                                tenant=int(r[NATIVE_COL_TENANT]), now=now)
            if self.observe(key, float(r[NATIVE_COL_SCORE]),
                            scored=r[NATIVE_COL_SCORED] > 0.5,
                            frames=int(r[NATIVE_COL_SEQ]),
                            now=now) not in (None, ACTION_OBSERVE):
                fired += 1
        return fired

    # ---- bounds + introspection ---------------------------------------------

    def _evict_over_cap(self) -> None:
        # stalest-first over *closed* entries only; live streams are
        # never evicted (their state is load-bearing for actuation)
        while len(self._streams) > self.table_cap:
            victim = None
            for k, ent in self._streams.items():  # oldest-first order
                if not ent.live:
                    victim = k
                    break
            if victim is None:
                return  # all live: over cap but un-evictable
            del self._streams[victim]
            self._gov.forget(str(victim))
            self.evicted += 1

    def __len__(self) -> int:
        return len(self._streams)

    def entry(self, key: int) -> Optional[StreamEntry]:
        return self._streams.get(key)

    def snapshot(self) -> Dict[str, object]:
        """/streams.json shape, mirroring the native streams_json doc
        so the admin plane can merge both without translation."""
        return {
            "enabled": True,
            "action": self.action,
            "count": len(self._streams),
            "evicted": self.evicted,
            "sick_transitions": self.sick_transitions,
            "actions_fired": self.actions_fired,
            "by_stream": {
                str(k): {
                    "kind": ent.kind,
                    "route": ent.route,
                    "samples": ent.samples,
                    "scored": ent.scored,
                    "score_ewma": round(float(ent.score_ewma), 6),
                    "frames": ent.frames,
                    "bytes": ent.bytes,
                    "sick": ent.shed,
                    "live": ent.live,
                }
                for k, ent in self._streams.items()
            },
        }
