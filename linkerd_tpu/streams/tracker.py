"""Incremental per-stream featurization — the Python mirror of
``native/stream_track.h``.

Long-lived h2/gRPC streams, WebSocket upgrades, and CONNECT tunnels
carry most of their bytes after the opening exchange, so the
request-shaped "one row at completion" featurizer never sees them go
bad. ``StreamTracker`` accumulates per-frame deltas — inter-frame gap
EWMA + mean-abs-deviation, bytes-per-DATA-frame EWMA + deviation,
WINDOW_UPDATE cadence, reset / flow-control anomaly counts — exactly
like the C ``StreamAccum`` the epoll engines embed.

Bit-exactness contract: every arithmetic step here is performed in
float32 with multiply-then-add ordering (no fused multiply-add), so a
frame sequence driven through this class and through the native
``l5d_stream_accum`` parity entry point produces *identical* bits.
``tests/test_stream_scoring.py`` pins that; do not "simplify" the
numpy scalar dance below into Python-float math.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

# Feature-row kinds (column NATIVE_COL_KIND of the 12-wide engine
# row). Request rows are 0 so old 9-wide readers see zero-fill.
ROW_REQUEST = 0
ROW_STREAM = 1   # h2 stream sample
ROW_TUNNEL = 2   # CONNECT / 101-upgrade byte tunnel

# Frame kinds fed to StreamTracker.frame (mirror stream_track.h).
FRAME_DATA = 0
FRAME_WINDOW_UPDATE = 1
FRAME_ANOMALY = 2  # RST / flow-control violation

_ALPHA = np.float32(0.125)  # all EWMAs use alpha = 1/8


def fold_key(key: int) -> int:
    """Fold a stream key to 24 bits (float32-integer-exact so it can
    ride a feature-row column); 0 is reserved for "not a stream row"
    and folds to 1, same as the C ``fold_key``."""
    f = int(key) & 0xFFFFFF
    return 1 if f == 0 else f


class StreamTracker:
    """Per-stream frame accumulator (float32, C-parity).

    One instance per live stream/tunnel; feed it every observed frame
    via :meth:`frame` and read the current feature vector with
    :meth:`features` whenever a score sample is due.
    """

    __slots__ = ("gap_ewma_ms", "gap_dev_ms", "bpf_ewma", "bpf_dev",
                 "frames", "data_frames", "wu_frames", "anomalies",
                 "bytes")

    def __init__(self) -> None:
        self.gap_ewma_ms = np.float32(0.0)
        self.gap_dev_ms = np.float32(0.0)
        self.bpf_ewma = np.float32(0.0)
        self.bpf_dev = np.float32(0.0)
        self.frames = 0
        self.data_frames = 0
        self.wu_frames = 0
        self.anomalies = 0
        self.bytes = 0

    def frame(self, kind: int, gap_ms: float, size: float = 0.0) -> None:
        """Fold one frame in: ``kind`` is FRAME_DATA /
        FRAME_WINDOW_UPDATE / FRAME_ANOMALY, ``gap_ms`` the gap since
        the previous frame, ``size`` the DATA payload bytes (ignored
        for the other kinds, exactly like the C accumulator)."""
        gap = np.float32(gap_ms)
        self.frames += 1
        if self.frames == 1:
            self.gap_ewma_ms = gap
        else:
            d = np.float32(gap - self.gap_ewma_ms)
            self.gap_ewma_ms = np.float32(
                self.gap_ewma_ms + np.float32(_ALPHA * d))
            self.gap_dev_ms = np.float32(
                self.gap_dev_ms
                + np.float32(_ALPHA * np.float32(abs(d) - self.gap_dev_ms)))
        if kind == FRAME_DATA:
            sz = np.float32(size)
            self.data_frames += 1
            self.bytes += int(sz)
            if self.data_frames == 1:
                self.bpf_ewma = sz
            else:
                db = np.float32(sz - self.bpf_ewma)
                self.bpf_ewma = np.float32(
                    self.bpf_ewma + np.float32(_ALPHA * db))
                self.bpf_dev = np.float32(
                    self.bpf_dev
                    + np.float32(_ALPHA * np.float32(abs(db) - self.bpf_dev)))
        elif kind == FRAME_WINDOW_UPDATE:
            self.wu_frames += 1
        else:
            self.anomalies += 1

    def as_row(self) -> np.ndarray:
        """Accumulator state in the exact layout ``l5d_stream_accum``
        writes (the parity surface): [gap_ewma_ms, gap_dev_ms,
        bpf_ewma, bpf_dev, frames, data_frames, wu_frames, anomalies,
        bytes] as float32[9]."""
        return np.array(
            [self.gap_ewma_ms, self.gap_dev_ms, self.bpf_ewma,
             self.bpf_dev, self.frames, self.data_frames,
             self.wu_frames, self.anomalies, self.bytes],
            dtype=np.float32)

    def snapshot(self) -> Dict[str, float]:
        return {
            "gap_ewma_ms": float(self.gap_ewma_ms),
            "gap_dev_ms": float(self.gap_dev_ms),
            "bpf_ewma": float(self.bpf_ewma),
            "bpf_dev": float(self.bpf_dev),
            "frames": self.frames,
            "data_frames": self.data_frames,
            "wu_frames": self.wu_frames,
            "anomalies": self.anomalies,
            "bytes": self.bytes,
        }


def stream_feature_vector(tracker: StreamTracker,
                          dst_path: str = "/") -> np.ndarray:
    """Map a tracker onto the request featurizer's input slots the way
    the engines' ``featurize_stream`` does: gap EWMA rides the latency
    slot, a synthetic status (500 when anomalies were seen, 200
    otherwise) rides status, bytes/frame rides request_bytes, total
    bytes rides response_bytes, gap deviation rides the drift slot.
    Used by the Python scoring path so stream samples and request rows
    share one model (and one specialist bank)."""
    from linkerd_tpu.models.features import FeatureVector, featurize
    fv = FeatureVector(
        latency_ms=float(tracker.gap_ewma_ms),
        status=500 if tracker.anomalies > 0 else 200,
        request_bytes=int(tracker.bpf_ewma),
        response_bytes=int(tracker.bytes),
        dst_path=dst_path,
        lat_drift_ms=float(tracker.gap_dev_ms))
    return featurize(fv)
