"""Per-connection frame observer for the Python h2 data plane.

The native engines featurize frames inline in their epoll loops; the
Python h2 path gets the same treatment here. One ``H2FrameObserver``
rides each server-side ``H2Connection``: every DATA / WINDOW_UPDATE /
RST (or flow-control violation) folds into the stream's
:class:`~linkerd_tpu.streams.tracker.StreamTracker`, and on the same
sampling cadence the engines use (every N frames, min-gap-bounded) the
accumulated features are scored and fed to the shared
:class:`~linkerd_tpu.streams.sentinel.StreamSentinel`. A SICK verdict
sheds the stream mid-flight via the connection's ``shed_stream`` —
RST_STREAM ENHANCE_YOUR_CALM, the Python twin of the engine's
actuation.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

import numpy as np

from linkerd_tpu.streams.sentinel import ACTION_OBSERVE, StreamSentinel
from linkerd_tpu.streams.tracker import (
    FRAME_ANOMALY, ROW_STREAM, StreamTracker, fold_key,
    stream_feature_vector,
)


class _StreamSlot:
    __slots__ = ("skey", "tracker", "last_frame", "last_sample_frames",
                 "last_sample_t", "dst_path")

    def __init__(self, skey: int, now: float, dst_path: str):
        self.skey = skey
        self.tracker = StreamTracker()
        self.last_frame = now
        self.last_sample_frames = 0
        self.last_sample_t = 0.0
        self.dst_path = dst_path


class H2FrameObserver:
    """Frame-to-sample bridge for one h2 connection.

    ``scorer`` is an optional synchronous ``f32[FEATURE_DIM] -> float``
    (the JAX/native tier adapter); without one, samples reach the
    sentinel unscored — the table tracks liveness/frames but the
    governor never moves, exactly like an engine with no weight blob
    published.
    """

    def __init__(self, sentinel: StreamSentinel,
                 next_skey: Callable[[], int],
                 scorer: Optional[Callable[[np.ndarray],
                                           Optional[float]]] = None,
                 sample_every_frames: int = 8, min_gap_ms: int = 10,
                 action: str = "rst", dst_path: str = "/",
                 emit_row: Optional[Callable[[np.ndarray], None]] = None):
        self.sentinel = sentinel
        self.scorer = scorer
        self.sample_every = max(1, int(sample_every_frames))
        self.min_gap_s = max(0, int(min_gap_ms)) / 1000.0
        self.action = action
        self.dst_path = dst_path
        self.emit_row = emit_row
        self._next_skey = next_skey
        self._conn = None
        self._slots: Dict[int, _StreamSlot] = {}
        self.sheds = 0

    def bind(self, conn) -> "H2FrameObserver":
        """Attach the connection actuation runs against (the observer
        is constructed before the connection that owns it)."""
        self._conn = conn
        return self

    # ── frame feed (called from the connection's read loop) ──────────────

    def on_frame(self, sid: int, kind: int, nbytes: int = 0,
                 now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        slot = self._slots.get(sid)
        if slot is None:
            slot = _StreamSlot(fold_key(self._next_skey()), now,
                               self.dst_path)
            self._slots[sid] = slot
            self.sentinel.open(slot.skey, kind=ROW_STREAM,
                               route=self.dst_path, now=now)
        gap_ms = (now - slot.last_frame) * 1000.0
        slot.last_frame = now
        slot.tracker.frame(kind, gap_ms, float(nbytes))
        if self._sample_due(slot, now):
            self._sample(sid, slot, now)

    def on_close(self, sid: int, now: Optional[float] = None) -> None:
        slot = self._slots.pop(sid, None)
        if slot is not None:
            self.sentinel.close(slot.skey, now=now)

    def close(self) -> None:
        """Connection teardown: every remaining stream is closed."""
        for sid in list(self._slots):
            self.on_close(sid)

    # ── sampling ─────────────────────────────────────────────────────────

    def _sample_due(self, slot: _StreamSlot, now: float) -> bool:
        t = slot.tracker
        if t.frames < slot.last_sample_frames + self.sample_every:
            return False
        return now - slot.last_sample_t >= self.min_gap_s

    def _sample(self, sid: int, slot: _StreamSlot, now: float) -> None:
        slot.last_sample_frames = slot.tracker.frames
        slot.last_sample_t = now
        score, scored = 0.0, False
        if self.scorer is not None:
            x = stream_feature_vector(slot.tracker, slot.dst_path)
            got = self.scorer(x)
            if got is not None:
                score, scored = float(got), True
        if self.emit_row is not None:
            self.emit_row(self._row(slot, score, scored, now))
        action = self.sentinel.observe(
            slot.skey, score, scored=scored, frames=slot.tracker.frames,
            nbytes=slot.tracker.bytes, now=now)
        if action is not None and action != ACTION_OBSERVE \
                and self.action != "observe":
            self._shed(sid)

    def _row(self, slot: _StreamSlot, score: float, scored: bool,
             now: float) -> np.ndarray:
        """A 12-wide native-layout feature row for this sample, so
        Python-path stream samples ride the same ring format as engine
        rows (NATIVE_ROW_WIDTH columns, kind=ROW_STREAM)."""
        t = slot.tracker
        return np.array(
            [0.0, float(t.gap_ewma_ms),
             500.0 if t.anomalies > 0 else 200.0,
             float(t.bpf_ewma), float(t.bytes), now, score,
             1.0 if scored else 0.0, 0.0, float(ROW_STREAM),
             float(slot.skey), float(t.frames)], dtype=np.float32)

    def _shed(self, sid: int) -> None:
        conn = self._conn
        if conn is None:
            return
        if conn.shed_stream(sid):
            self.sheds += 1
        self.on_close(sid)


__all__ = ["H2FrameObserver", "FRAME_ANOMALY"]
