"""The linkerd<->namerd mesh API.

Wire-compatible with the reference's proto3 schema
(ref: mesh/core/src/main/protobuf/{interpreter,resolver,delegator,dtab,path}.proto):
``Interpreter.{Get,Stream}BoundTree``, ``Resolver.{Get,Stream}Replicas``,
``Delegator.{Get,Stream}Dtab`` / ``{Get,Stream}DelegateTree`` under package
``io.linkerd.mesh``, served over our gRPC runtime.
"""

from linkerd_tpu.mesh.messages import (
    MBoundNameTree, MBindReq, MBoundTreeRsp, MDtab, MDtabReq, MDtabRsp,
    MEndpoint, MPath, MPathNameTree, MReplicas, MReplicasReq, MVersionedDtab,
)
from linkerd_tpu.mesh.api import DELEGATOR_SVC, INTERPRETER_SVC, RESOLVER_SVC
from linkerd_tpu.mesh import converters

__all__ = [
    "MBoundNameTree", "MBindReq", "MBoundTreeRsp", "MDtab", "MDtabReq",
    "MDtabRsp", "MEndpoint", "MPath", "MPathNameTree", "MReplicas",
    "MReplicasReq", "MVersionedDtab", "DELEGATOR_SVC", "INTERPRETER_SVC",
    "RESOLVER_SVC", "converters",
]
