"""Mesh gRPC service definitions (ref: the three services in
mesh/core/src/main/protobuf/: Interpreter, Resolver, Delegator)."""

from linkerd_tpu.grpc import Rpc, ServiceDef
from linkerd_tpu.mesh import messages as m

INTERPRETER_SVC = ServiceDef("io.linkerd.mesh.Interpreter", [
    Rpc("GetBoundTree", m.MBindReq, m.MBoundTreeRsp),
    Rpc("StreamBoundTree", m.MBindReq, m.MBoundTreeRsp,
        server_streaming=True),
])

RESOLVER_SVC = ServiceDef("io.linkerd.mesh.Resolver", [
    Rpc("GetReplicas", m.MReplicasReq, m.MReplicas),
    Rpc("StreamReplicas", m.MReplicasReq, m.MReplicas,
        server_streaming=True),
])

DELEGATOR_SVC = ServiceDef("io.linkerd.mesh.Delegator", [
    Rpc("GetDtab", m.MDtabReq, m.MDtabRsp),
    Rpc("StreamDtab", m.MDtabReq, m.MDtabRsp, server_streaming=True),
])
