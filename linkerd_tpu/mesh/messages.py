"""Mesh API proto messages (field numbers match the reference schema).

Ref: mesh/core/src/main/protobuf/path.proto (Path, PathNameTree, Dtab,
VersionedDtab live in dtab.proto there), interpreter.proto (BindReq,
BoundTreeRsp, BoundNameTree), resolver.proto (ReplicasReq, Endpoint,
Replicas), delegator.proto (DtabReq, DtabRsp). oneof members are modeled
as optional fields — presence (is not None) selects the arm, which is
wire-identical for proto3 message-typed oneofs.
"""

from __future__ import annotations

from linkerd_tpu.grpc.proto import Enum, Field, ProtoMessage


class MPath(ProtoMessage):
    FIELDS = {"elems": Field(1, "bytes", repeated=True)}


class MEmpty(ProtoMessage):
    FIELDS = {}


# ---- PathNameTree (dtab.proto PathNameTree) --------------------------------

class MPathLeaf(ProtoMessage):
    FIELDS = {"id": Field(1, "message", message=MPath)}


class MPathNameTree(ProtoMessage):
    pass  # populated below (self-referential)


class MPathWeighted(ProtoMessage):
    pass


class MPathAlt(ProtoMessage):
    pass


class MPathUnion(ProtoMessage):
    pass


MPathAlt.FIELDS = {
    "trees": Field(1, "message", message=MPathNameTree, repeated=True)}
MPathWeighted.FIELDS = {
    "weight": Field(1, "double"),
    "tree": Field(2, "message", message=MPathNameTree)}
MPathUnion.FIELDS = {
    "trees": Field(1, "message", message=MPathWeighted, repeated=True)}
MPathNameTree.FIELDS = {
    "neg": Field(1, "message", message=MEmpty),
    "fail": Field(2, "message", message=MEmpty),
    "empty": Field(3, "message", message=MEmpty),
    "alt": Field(4, "message", message=MPathAlt),
    "union": Field(5, "message", message=MPathUnion),
    "leaf": Field(6, "message", message=MPathLeaf),
}


# ---- Dtab (dtab.proto) -----------------------------------------------------

class MPrefixElem(ProtoMessage):
    FIELDS = {
        "label": Field(1, "bytes"),
        "wildcard": Field(2, "message", message=MEmpty),
    }


class MPrefix(ProtoMessage):
    FIELDS = {"elems": Field(1, "message", message=MPrefixElem, repeated=True)}


class MDentry(ProtoMessage):
    FIELDS = {
        "prefix": Field(1, "message", message=MPrefix),
        "dst": Field(2, "message", message=MPathNameTree),
    }


class MDtab(ProtoMessage):
    FIELDS = {"dentries": Field(1, "message", message=MDentry, repeated=True)}


class MDtabVersion(ProtoMessage):
    FIELDS = {"id": Field(1, "bytes")}


class MVersionedDtab(ProtoMessage):
    FIELDS = {
        "version": Field(1, "message", message=MDtabVersion),
        "dtab": Field(2, "message", message=MDtab),
    }


# ---- Interpreter (interpreter.proto) ---------------------------------------

class MBindReq(ProtoMessage):
    FIELDS = {
        "root": Field(1, "message", message=MPath),
        "name": Field(2, "message", message=MPath),
        "dtab": Field(3, "message", message=MDtab),
    }


class MBoundLeaf(ProtoMessage):
    FIELDS = {
        "id": Field(1, "message", message=MPath),
        "residual": Field(2, "message", message=MPath),
    }


class MBoundNameTree(ProtoMessage):
    pass


class MBoundWeighted(ProtoMessage):
    pass


class MBoundAlt(ProtoMessage):
    pass


class MBoundUnion(ProtoMessage):
    pass


MBoundAlt.FIELDS = {
    "trees": Field(1, "message", message=MBoundNameTree, repeated=True)}
MBoundWeighted.FIELDS = {
    "weight": Field(1, "double"),
    "tree": Field(2, "message", message=MBoundNameTree)}
MBoundUnion.FIELDS = {
    "trees": Field(1, "message", message=MBoundWeighted, repeated=True)}
MBoundNameTree.FIELDS = {
    "neg": Field(1, "message", message=MEmpty),
    "fail": Field(2, "message", message=MEmpty),
    "empty": Field(3, "message", message=MEmpty),
    "alt": Field(4, "message", message=MBoundAlt),
    "union": Field(5, "message", message=MBoundUnion),
    "leaf": Field(6, "message", message=MBoundLeaf),
}


class MBoundTreeRsp(ProtoMessage):
    FIELDS = {"tree": Field(1, "message", message=MBoundNameTree)}


# ---- Resolver (resolver.proto) ---------------------------------------------

class AddressFamily(Enum):
    INET4 = 0
    INET6 = 1


class MEndpointMeta(ProtoMessage):
    FIELDS = {"nodeName": Field(1, "string")}


class MEndpoint(ProtoMessage):
    FIELDS = {
        "inet_af": Field(1, "enum"),
        "address": Field(2, "bytes"),
        "port": Field(3, "int32"),
        "meta": Field(4, "message", message=MEndpointMeta),
    }


class MReplicasReq(ProtoMessage):
    FIELDS = {"id": Field(1, "message", message=MPath)}


class MReplicasFailed(ProtoMessage):
    FIELDS = {"message": Field(1, "string")}


class MReplicasBound(ProtoMessage):
    FIELDS = {"endpoints": Field(1, "message", message=MEndpoint,
                                 repeated=True)}


class MReplicas(ProtoMessage):
    FIELDS = {
        "pending": Field(1, "message", message=MEmpty),
        "neg": Field(2, "message", message=MEmpty),
        "failed": Field(3, "message", message=MReplicasFailed),
        "bound": Field(4, "message", message=MReplicasBound),
    }


# ---- Delegator (delegator.proto) -------------------------------------------

class MDtabReq(ProtoMessage):
    FIELDS = {"root": Field(1, "message", message=MPath)}


class MDtabRsp(ProtoMessage):
    FIELDS = {"dtab": Field(1, "message", message=MVersionedDtab)}
