"""Proto <-> core-type converters for the mesh API.

Ref: mesh/core/src/main/scala/io/buoyant/linkerd/mesh/Converters.scala —
same role: Path/Dtab/NameTree/Addr to and from their proto forms.
"""

from __future__ import annotations

import ipaddress
from typing import Optional

from linkerd_tpu.core import Dtab, Path
from linkerd_tpu.core.addr import (
    ADDR_NEG, ADDR_PENDING, Addr, AddrFailed, Address, Bound, BoundName,
)
from linkerd_tpu.core.dtab import WILDCARD, Dentry, Prefix
from linkerd_tpu.core.nametree import (
    Alt, Empty, Fail, Leaf, NameTree, Neg, Union, Weighted,
)
from linkerd_tpu.mesh import messages as m


# ---- Path ------------------------------------------------------------------

def path_to_proto(p: Path) -> m.MPath:
    return m.MPath(elems=[seg.encode("utf-8") for seg in p])


def path_from_proto(mp: Optional[m.MPath]) -> Path:
    if mp is None:
        return Path()
    return Path(e.decode("utf-8") for e in mp.elems)


# ---- NameTree[Path] --------------------------------------------------------

def pathtree_to_proto(t: NameTree) -> m.MPathNameTree:
    if isinstance(t, Leaf):
        return m.MPathNameTree(leaf=m.MPathLeaf(id=path_to_proto(t.value)))
    if isinstance(t, Alt):
        return m.MPathNameTree(alt=m.MPathAlt(
            trees=[pathtree_to_proto(s) for s in t.trees]))
    if isinstance(t, Union):
        return m.MPathNameTree(union=m.MPathUnion(trees=[
            m.MPathWeighted(weight=w.weight, tree=pathtree_to_proto(w.tree))
            for w in t.weighted]))
    if isinstance(t, Fail):
        return m.MPathNameTree(fail=m.MEmpty())
    if isinstance(t, Empty):
        return m.MPathNameTree(empty=m.MEmpty())
    return m.MPathNameTree(neg=m.MEmpty())


def pathtree_from_proto(mt: Optional[m.MPathNameTree]) -> NameTree:
    from linkerd_tpu.core.nametree import EMPTY, FAIL, NEG
    if mt is None:
        return NEG
    if mt.leaf is not None:
        return Leaf(path_from_proto(mt.leaf.id))
    if mt.alt is not None:
        return Alt(*(pathtree_from_proto(s) for s in mt.alt.trees))
    if mt.union is not None:
        return Union(*(Weighted(w.weight, pathtree_from_proto(w.tree))
                       for w in mt.union.trees))
    if mt.fail is not None:
        return FAIL
    if mt.empty is not None:
        return EMPTY
    return NEG


# ---- Dtab ------------------------------------------------------------------

def dtab_to_proto(dtab: Dtab) -> m.MDtab:
    dentries = []
    for d in dtab:
        elems = []
        for seg in d.prefix.segments:
            if seg == WILDCARD:
                elems.append(m.MPrefixElem(wildcard=m.MEmpty()))
            else:
                elems.append(m.MPrefixElem(label=seg.encode("utf-8")))
        dentries.append(m.MDentry(
            prefix=m.MPrefix(elems=elems),
            dst=pathtree_to_proto(d.dst)))
    return m.MDtab(dentries=dentries)


def dtab_from_proto(md: Optional[m.MDtab]) -> Dtab:
    if md is None:
        return Dtab.empty()
    dentries = []
    for d in md.dentries:
        segs = []
        for e in (d.prefix.elems if d.prefix is not None else []):
            if e.wildcard is not None:
                segs.append(WILDCARD)
            else:
                segs.append(e.label.decode("utf-8"))
        dentries.append(Dentry(Prefix(tuple(segs)),
                               pathtree_from_proto(d.dst)))
    return Dtab(dentries)


# ---- NameTree[BoundName] ---------------------------------------------------

def boundtree_to_proto(t: NameTree) -> m.MBoundNameTree:
    if isinstance(t, Leaf):
        bn: BoundName = t.value
        return m.MBoundNameTree(leaf=m.MBoundLeaf(
            id=path_to_proto(bn.id_), residual=path_to_proto(bn.residual)))
    if isinstance(t, Alt):
        return m.MBoundNameTree(alt=m.MBoundAlt(
            trees=[boundtree_to_proto(s) for s in t.trees]))
    if isinstance(t, Union):
        return m.MBoundNameTree(union=m.MBoundUnion(trees=[
            m.MBoundWeighted(weight=w.weight, tree=boundtree_to_proto(w.tree))
            for w in t.weighted]))
    if isinstance(t, Fail):
        return m.MBoundNameTree(fail=m.MEmpty())
    if isinstance(t, Empty):
        return m.MBoundNameTree(empty=m.MEmpty())
    return m.MBoundNameTree(neg=m.MEmpty())


def boundtree_from_proto(mt: Optional[m.MBoundNameTree],
                         mk_leaf) -> NameTree:
    """mk_leaf(id_path, residual_path) -> BoundName (caller supplies the
    live Var[Addr], typically backed by a Resolver stream)."""
    from linkerd_tpu.core.nametree import EMPTY, FAIL, NEG
    if mt is None:
        return NEG
    if mt.leaf is not None:
        return Leaf(mk_leaf(path_from_proto(mt.leaf.id),
                            path_from_proto(mt.leaf.residual)))
    if mt.alt is not None:
        return Alt(*(boundtree_from_proto(s, mk_leaf) for s in mt.alt.trees))
    if mt.union is not None:
        return Union(*(Weighted(w.weight,
                                boundtree_from_proto(w.tree, mk_leaf))
                       for w in mt.union.trees))
    if mt.fail is not None:
        return FAIL
    if mt.empty is not None:
        return EMPTY
    return NEG


# ---- Addr <-> Replicas -----------------------------------------------------

def addr_to_replicas(addr: Addr) -> m.MReplicas:
    if isinstance(addr, Bound):
        eps = []
        for a in addr.addresses:
            try:
                ip = ipaddress.ip_address(a.host)
                af = (m.AddressFamily.INET6 if ip.version == 6
                      else m.AddressFamily.INET4)
                raw = ip.packed
            except ValueError:
                # unresolved hostname: ship utf-8 bytes under INET4 af
                # (the reference resolves before shipping; we defer)
                af = m.AddressFamily.INET4
                raw = a.host.encode("utf-8")
            meta = None
            node = dict(a.meta).get("nodeName")
            if node:
                meta = m.MEndpointMeta(nodeName=str(node))
            eps.append(m.MEndpoint(inet_af=af, address=raw, port=a.port,
                                   meta=meta))
        return m.MReplicas(bound=m.MReplicasBound(endpoints=eps))
    if isinstance(addr, AddrFailed):
        return m.MReplicas(failed=m.MReplicasFailed(message=addr.why))
    if addr is ADDR_NEG or type(addr).__name__ == "AddrNeg":
        return m.MReplicas(neg=m.MEmpty())
    return m.MReplicas(pending=m.MEmpty())


def addr_from_replicas(rep: m.MReplicas) -> Addr:
    if rep.bound is not None:
        addrs = []
        for ep in rep.bound.endpoints:
            try:
                host = str(ipaddress.ip_address(ep.address))
            except ValueError:
                host = ep.address.decode("utf-8", "replace")
            meta = {}
            if ep.meta is not None and ep.meta.nodeName:
                meta["nodeName"] = ep.meta.nodeName
            addrs.append(Address.mk(host, ep.port, **meta))
        return Bound(frozenset(addrs))
    if rep.failed is not None:
        return AddrFailed(rep.failed.message)
    if rep.neg is not None:
        return ADDR_NEG
    return ADDR_PENDING
