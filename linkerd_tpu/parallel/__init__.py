"""Device-mesh construction and sharded train/score steps.

The device fabric is reached only through XLA collectives over ICI/DCN —
this package owns the jax.sharding Mesh, the PartitionSpecs (batch over
"data", hidden axes over "model"), and the jitted steps. The host data
plane never touches device communication directly (SURVEY.md §5
distributed-communication backend mapping).
"""

from linkerd_tpu.parallel.mesh import (
    make_mesh,
    param_shardings,
    batch_sharding,
    make_train_step,
    make_score_step,
    replicated,
)

__all__ = [
    "make_mesh", "param_shardings", "batch_sharding", "make_train_step",
    "make_score_step", "replicated",
]
