"""Mesh + sharding for the anomaly model: dp x tp GSPMD.

TPU-first design (scaling-book recipe): pick a mesh, annotate shardings with
NamedSharding/PartitionSpec, let XLA insert the collectives (all-gather /
reduce-scatter / psum ride ICI), profile, iterate. We do NOT hand-write
collectives for the MLP: GSPMD partitioning of Megatron-style column/row
parallel matmuls is exactly what the compiler does from the specs below.

Axes:
- ``data``  — batch-dim data parallelism (gradient psum inserted by XLA).
- ``model`` — tensor parallelism over hidden dims: encoder layer i alternates
  column-/row-parallel so activations stay sharded between layers.

Sequence/pipeline/expert parallelism intentionally do not apply at this
model's scale (per-request feature vectors, no sequence dim, single dense
model — SURVEY.md §5 "Long-context" scopes ring-attention/Ulysses out);
the mesh machinery here is what a wider model family would extend.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from linkerd_tpu.models.anomaly import (
    AnomalyModelConfig, Params, init_params, anomaly_scores, loss_fn,
    normalize_features,
)


# Per-shard hidden width below which tensor parallelism is pure
# all-gather overhead: at MLP scale (256-wide layers) the matmul per
# shard is microseconds while the collective latency is not — the
# scaling-book rule that the model axis only pays when each shard still
# saturates the MXU (round-3 BENCH: dp4xtp2 was 1.8x SLOWER than one
# device). SURVEY.md §2.4: "no TP/PP needed at MLP scale but the design
# should allow shard_map sharding of wide layers".
MIN_TP_SHARD_WIDTH = 2048


def make_mesh(
    devices: Optional[list] = None,
    tp: Optional[int] = None,
    axis_names: Tuple[str, str] = ("data", "model"),
    model_width: Optional[int] = None,
) -> Mesh:
    """Build a dp x tp mesh over ``devices`` (default: all local devices).

    ``tp`` defaults to 1 (pure data parallelism) unless ``model_width``
    is given and wide enough that each model shard stays above
    ``MIN_TP_SHARD_WIDTH``; callers override ``tp`` for real topologies.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if tp is None:
        tp = 1
        if (model_width is not None and n % 2 == 0 and n > 1
                and model_width // 2 >= MIN_TP_SHARD_WIDTH):
            tp = 2
    if n % tp != 0:
        raise ValueError(f"device count {n} not divisible by tp={tp}")
    arr = np.array(devices).reshape(n // tp, tp)
    return Mesh(arr, axis_names)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch rows over the data axis; feature dim replicated."""
    return NamedSharding(mesh, P("data", None))


def shard_batch(mesh: Mesh, x: np.ndarray) -> jax.Array:
    """Per-device shard feed: place each device's OWN batch shard and
    assemble the global array with
    ``jax.make_array_from_single_device_arrays``.

    The old path handed the full host batch to one ``jax.device_put``
    with a NamedSharding, which on weak-scaled meshes serializes the
    whole transfer through a single host-side staging pass (BENCH_r04:
    cpu8 weak-scaled LOST to cpu1). Here each device receives exactly
    its slice — transfers are per-shard and the assembly is metadata
    only. The batch dim must divide evenly over the data axis (callers
    pad via ``_pad_rows``; ``bucket_rows`` already rounds to a multiple
    of the mesh's data size).
    """
    sh = batch_sharding(mesh)
    idx_map = sh.addressable_devices_indices_map(x.shape)
    shards = [jax.device_put(x[idx], d) for d, idx in idx_map.items()]
    return jax.make_array_from_single_device_arrays(x.shape, sh, shards)


def _layer_specs(n_layers: int, first_col: bool = True):
    """Alternating column-/row-parallel specs for a dense chain.

    Column-parallel layer: w [in, out] sharded (None, "model"), b sharded.
    Row-parallel layer: w sharded ("model", None), b replicated (XLA adds
    the psum over the contracted axis).
    """
    specs = []
    col = first_col
    for _ in range(n_layers):
        if col:
            specs.append({"w": P(None, "model"), "b": P("model")})
        else:
            specs.append({"w": P("model", None), "b": P()})
        col = not col
    return specs


def param_specs(params: Params) -> Params:
    """PartitionSpec pytree matching an anomaly-model param pytree."""
    return {
        "enc": _layer_specs(len(params["enc"])),
        "dec": _layer_specs(len(params["dec"])),
        "cls": _layer_specs(len(params["cls"])),
    }


def param_shardings(mesh: Mesh, params: Params) -> Params:
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        param_specs(params),
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(mesh: Mesh, params: Params) -> Params:
    return jax.device_put(params, param_shardings(mesh, params))


def make_score_step(
    mesh: Mesh, cfg: AnomalyModelConfig = AnomalyModelConfig(),
    donate: bool = False,
) -> Callable[..., jax.Array]:
    """Jitted scoring step: features [B, D] -> scores [B].

    With ``mu``/``var`` (replicated device arrays), feature
    normalization runs on device, fused ahead of the first matmul: each
    data-axis shard z-scores its own rows, the host never touches the
    batch (normalize_features' contract). Without them the step scores
    raw features (pre-normalized or synthetic-test input).

    With ``donate``, the input batch buffer is donated to the step
    (``donate_argnums``): the line-rate dispatcher hands the step a
    device array assembled by ``shard_batch`` and never touches it
    again, so XLA reuses the buffer instead of allocating per batch.
    Donated inputs must not be re-read after dispatch — JAX raises on
    reuse of a deleted buffer.
    """
    xs = batch_sharding(mesh)

    def score(params: Params, x: jax.Array, mu=None, var=None) -> jax.Array:
        x = jax.lax.with_sharding_constraint(x, xs)
        if mu is not None:
            x = normalize_features(x, mu, var)
        return anomaly_scores(params, x, cfg)

    if donate:
        return jax.jit(score, donate_argnums=(1,))
    return jax.jit(score)


def make_train_step(
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    cfg: AnomalyModelConfig = AnomalyModelConfig(),
):
    """Jitted train step over the dp x tp mesh.

    Gradients are averaged over "data" and hidden-dim partial sums reduced
    over "model" by XLA-inserted collectives; we only annotate shardings.
    ``mu``/``var`` (replicated) fold feature normalization into the step
    the same way make_score_step does — train and serve see identical
    normalized inputs.
    """
    xs = batch_sharding(mesh)
    vs = NamedSharding(mesh, P("data"))

    @jax.jit
    def train_step(params: Params, opt_state, x, labels, label_mask,
                   row_mask=None, mu=None, var=None):
        x = jax.lax.with_sharding_constraint(x, xs)
        labels = jax.lax.with_sharding_constraint(labels, vs)
        label_mask = jax.lax.with_sharding_constraint(label_mask, vs)
        if row_mask is not None:
            row_mask = jax.lax.with_sharding_constraint(row_mask, vs)
        if mu is not None:
            x = normalize_features(x, mu, var)
        loss, grads = jax.value_and_grad(loss_fn)(
            params, x, labels, label_mask, cfg, row_mask)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def init_sharded(
    mesh: Mesh,
    key: jax.Array,
    optimizer: optax.GradientTransformation,
    cfg: AnomalyModelConfig = AnomalyModelConfig(),
):
    """Initialize params + opt state and place them per the tp specs."""
    params = shard_params(mesh, init_params(key, cfg))
    opt_state = optimizer.init(params)
    return params, opt_state


def place_snapshot(
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    params_host: Params,
    opt_leaves: Optional[list] = None,
):
    """Re-place a restored (host/numpy) checkpoint onto ``mesh`` with the
    same column/row specs as a fresh init, so a snapshot taken on any
    topology (single chip, other mesh shape) hot-swaps into this one.

    ``opt_leaves`` is the checkpoint's flattened optax state (tree_leaves
    order); the state *structure* is rebuilt from ``optimizer.init`` on
    the placed params — its leaf shardings are the authoritative
    placement for the restored leaves. Returns ``(params, opt_state)``.
    """
    params = shard_params(mesh, params_host)
    template = optimizer.init(params)
    if opt_leaves is None:
        return params, template
    t_leaves, treedef = jax.tree_util.tree_flatten(template)
    if len(opt_leaves) != len(t_leaves):
        raise ValueError(
            f"optimizer state mismatch: checkpoint has {len(opt_leaves)} "
            f"leaves, optimizer expects {len(t_leaves)}")
    placed = []
    for leaf, t in zip(opt_leaves, t_leaves):
        arr = np.asarray(leaf)
        if tuple(arr.shape) != tuple(t.shape):
            raise ValueError(
                f"optimizer leaf shape mismatch: checkpoint {arr.shape} "
                f"vs optimizer {tuple(t.shape)}")
        # param-shaped moments inherit the param NamedShardings via
        # zeros_like; fresh scalars (adam's count) come back with a
        # single-device placement — committing them there would make the
        # jitted train step see mixed device sets, so replicate instead
        sharding = (t.sharding if isinstance(t.sharding, NamedSharding)
                    else replicated(mesh))
        placed.append(jax.device_put(arr.astype(t.dtype), sharding))
    return params, jax.tree_util.tree_unflatten(treedef, placed)
