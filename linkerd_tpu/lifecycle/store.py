"""Checkpoint store: atomic, CRC-checked, versioned model snapshots.

The anomaly scorer's continually-trained parameters are a first-class,
versioned artifact (Taurus, arxiv 2002.08987): every snapshot captures
``(params, opt_state, mu/var normalization stats, AnomalyModelConfig,
step counter)`` so a restored model scores bit-identically to the moment
it was checkpointed — including the optimizer momentum online training
resumes from.

Wire format (one ``.ckpt`` file per version)::

    b"L5DCKPT1" | u32 header_len | header JSON | raw array payload | u32 crc

The CRC32 covers everything before it; a flipped bit anywhere raises
``CheckpointCorruptError`` instead of silently restoring garbage. Files
are written temp-file+``os.replace`` so a crash mid-write never leaves a
half-checkpoint under a valid name, and ``manifest.json`` (also written
atomically) tracks lineage (parent version), status (candidate /
promoted / rejected / rolled_back), and retention.
"""

from __future__ import annotations

import dataclasses
import json
import os
import struct
import time
import zlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

MAGIC = b"L5DCKPT1"
MANIFEST = "manifest.json"
FORMAT = 1


class CheckpointError(Exception):
    """Base for checkpoint store failures."""


class CheckpointCorruptError(CheckpointError):
    """CRC mismatch, bad magic, or a truncated checkpoint file."""


def _cfg_to_dict(cfg) -> Dict[str, Any]:
    """AnomalyModelConfig -> JSON-safe dict (dtype by name)."""
    import jax.numpy as jnp

    return {
        "in_dim": cfg.in_dim,
        "enc_dims": list(cfg.enc_dims),
        "bottleneck": cfg.bottleneck,
        "cls_hidden": cfg.cls_hidden,
        "compute_dtype": jnp.dtype(cfg.compute_dtype).name,
        "recon_weight": cfg.recon_weight,
    }


def _cfg_from_dict(d: Dict[str, Any]):
    import jax.numpy as jnp

    from linkerd_tpu.models.anomaly import AnomalyModelConfig

    return AnomalyModelConfig(
        in_dim=int(d["in_dim"]),
        enc_dims=tuple(int(v) for v in d["enc_dims"]),
        bottleneck=int(d["bottleneck"]),
        cls_hidden=int(d["cls_hidden"]),
        compute_dtype=jnp.dtype(d["compute_dtype"]).type,
        recon_weight=float(d["recon_weight"]),
    )


@dataclass
class ModelSnapshot:
    """Host-side (numpy) capture of one scorer's full training state."""

    params: Any                      # dict/list pytree of np.ndarray
    opt_leaves: List[np.ndarray]     # tree_leaves of the optax state
    mu: np.ndarray                   # feature-normalization running mean
    var: np.ndarray                  # feature-normalization running var
    norm_initialized: bool
    step: int                        # cumulative train steps
    cfg: Any                         # AnomalyModelConfig

    def cfg_dict(self) -> Dict[str, Any]:
        return _cfg_to_dict(self.cfg)


# -- pytree <-> flat path map -------------------------------------------------


def _flatten_tree(tree: Any, prefix: str, out: Dict[str, np.ndarray]) -> None:
    if isinstance(tree, dict):
        for k in sorted(tree):
            _flatten_tree(tree[k], f"{prefix}.{k}" if prefix else str(k), out)
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            _flatten_tree(v, f"{prefix}.{i}" if prefix else str(i), out)
    else:
        out[prefix] = np.asarray(tree)


def _unflatten_tree(flat: Dict[str, np.ndarray]) -> Any:
    """Rebuild the nested dict/list pytree from dotted paths. Integer
    segments become list indices (contiguous from 0 by construction)."""
    root: Dict[str, Any] = {}
    for path, arr in flat.items():
        parts = path.split(".")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr

    def materialize(node: Any) -> Any:
        if not isinstance(node, dict):
            return node
        keys = list(node)
        if keys and all(k.isdigit() for k in keys):
            return [materialize(node[str(i)]) for i in range(len(keys))]
        return {k: materialize(v) for k, v in node.items()}

    return materialize(root)


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # jax's extended dtypes (bfloat16 etc.)

        return np.dtype(getattr(ml_dtypes, name))


# -- snapshot codec -----------------------------------------------------------


def encode_snapshot(snap: ModelSnapshot) -> bytes:
    arrays: Dict[str, np.ndarray] = {}
    _flatten_tree(snap.params, "params", arrays)
    for i, leaf in enumerate(snap.opt_leaves):
        arrays[f"opt.{i}"] = np.asarray(leaf)
    arrays["norm.mu"] = np.asarray(snap.mu, np.float32)
    arrays["norm.var"] = np.asarray(snap.var, np.float32)

    manifest = []
    chunks = []
    for key, arr in arrays.items():
        raw = np.ascontiguousarray(arr).tobytes()
        manifest.append({"key": key, "dtype": arr.dtype.name,
                         "shape": list(arr.shape), "nbytes": len(raw)})
        chunks.append(raw)
    header = json.dumps({
        "format": FORMAT,
        "step": int(snap.step),
        "norm_initialized": bool(snap.norm_initialized),
        "cfg": snap.cfg_dict(),
        "arrays": manifest,
    }).encode()
    body = MAGIC + struct.pack("<I", len(header)) + header + b"".join(chunks)
    return body + struct.pack("<I", zlib.crc32(body))


def decode_snapshot(data: bytes) -> ModelSnapshot:
    if len(data) < len(MAGIC) + 8 or not data.startswith(MAGIC):
        raise CheckpointCorruptError("bad checkpoint magic or truncated file")
    body, (crc,) = data[:-4], struct.unpack("<I", data[-4:])
    if zlib.crc32(body) != crc:
        raise CheckpointCorruptError(
            f"checkpoint CRC mismatch (stored {crc:#010x}, "
            f"computed {zlib.crc32(body):#010x})")
    (hlen,) = struct.unpack_from("<I", body, len(MAGIC))
    hoff = len(MAGIC) + 4
    header = json.loads(body[hoff:hoff + hlen].decode())
    if header.get("format") != FORMAT:
        raise CheckpointError(
            f"unsupported checkpoint format {header.get('format')!r}")
    off = hoff + hlen
    arrays: Dict[str, np.ndarray] = {}
    for m in header["arrays"]:
        dt = _np_dtype(m["dtype"])
        n = m["nbytes"]
        if off + n > len(body):
            raise CheckpointCorruptError("checkpoint payload truncated")
        arrays[m["key"]] = np.frombuffer(
            body, dt, n // dt.itemsize, off).reshape(m["shape"]).copy()
        off += n

    params_flat = {k[len("params."):]: v for k, v in arrays.items()
                   if k.startswith("params.")}
    opt_keys = sorted((k for k in arrays if k.startswith("opt.")),
                      key=lambda k: int(k.split(".", 1)[1]))
    return ModelSnapshot(
        params=_unflatten_tree(params_flat),
        opt_leaves=[arrays[k] for k in opt_keys],
        mu=arrays["norm.mu"],
        var=arrays["norm.var"],
        norm_initialized=header["norm_initialized"],
        step=header["step"],
        cfg=_cfg_from_dict(header["cfg"]),
    )


# -- versioned on-disk store --------------------------------------------------


@dataclass
class CheckpointMeta:
    version: int
    file: str
    crc: int
    step: int
    parent: Optional[int]
    status: str            # candidate | promoted | rejected | rolled_back
    created_at: float
    bytes: int
    # native weight-blob lineage (set when this version was exported to
    # the in-data-plane scorer): {crc, quant, bytes, ...} from
    # lifecycle.export.blob_meta — proves WHICH bits the engines served
    native_blob: Optional[Dict[str, Any]] = None


class CheckpointStore:
    """Directory of versioned ``.ckpt`` files plus an atomic manifest.

    Retention keeps the newest ``retain`` versions, but never prunes the
    serving (last-promoted) version — the rollback target must survive
    any churn of rejected candidates.
    """

    def __init__(self, directory: str, retain: int = 5):
        if retain < 1:
            raise ValueError("retain must be >= 1")
        self.directory = directory
        self.retain = retain
        os.makedirs(directory, exist_ok=True)
        self._manifest = self._load_manifest()

    # -- manifest ---------------------------------------------------------
    @property
    def _manifest_path(self) -> str:
        return os.path.join(self.directory, MANIFEST)

    def _load_manifest(self) -> Dict[str, Any]:
        try:
            with open(self._manifest_path, "r", encoding="utf-8") as f:
                m = json.load(f)
        except FileNotFoundError:
            return {"format": FORMAT, "next_version": 1, "serving": None,
                    "pruned": [], "versions": []}
        except (OSError, json.JSONDecodeError) as e:
            raise CheckpointCorruptError(f"unreadable manifest: {e}") from e
        if m.get("format") != FORMAT:
            raise CheckpointError(
                f"unsupported manifest format {m.get('format')!r}")
        return m

    def _write_manifest(self) -> None:
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self._manifest, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._manifest_path)

    def _entries(self) -> List[CheckpointMeta]:
        return [CheckpointMeta(**e) for e in self._manifest["versions"]]

    def _entry(self, version: int) -> CheckpointMeta:
        for e in self._entries():
            if e.version == version:
                return e
        raise CheckpointError(f"unknown checkpoint version {version}")

    # -- write path -------------------------------------------------------
    def save(self, snap: ModelSnapshot, status: str = "candidate",
             parent: Optional[int] = None) -> int:
        version = self._manifest["next_version"]
        data = encode_snapshot(snap)
        crc = struct.unpack("<I", data[-4:])[0]
        fname = f"v{version:06d}.ckpt"
        path = os.path.join(self.directory, fname)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._manifest["versions"].append(dataclasses.asdict(CheckpointMeta(
            version=version, file=fname, crc=crc, step=int(snap.step),
            parent=parent, status=status, created_at=time.time(),
            bytes=len(data))))
        self._manifest["next_version"] = version + 1
        if status == "promoted":
            self._manifest["serving"] = version
        self._apply_retention()
        self._write_manifest()
        return version

    def record_native_blob(self, version: int,
                           meta: Optional[Dict[str, Any]]) -> None:
        """Annotate a checkpoint's manifest entry with the native
        weight blob exported from it (lifecycle.export.blob_meta): the
        manifest then carries the full lineage from training state to
        the exact CRC'd bits the data-plane engines serve."""
        for e in self._manifest["versions"]:
            if e["version"] == version:
                e["native_blob"] = meta
                self._write_manifest()
                return
        raise CheckpointError(f"unknown checkpoint version {version}")

    def record_specialist(self, route_hash: int,
                          meta: Optional[Dict[str, Any]]) -> None:
        """Manifest lineage for one specialist head: ``meta`` records
        the dst path, head version, bank generation, the base
        checkpoint it was distilled from, and the published delta's
        CRC — or None to drop the entry (a single-route rollback).
        The manifest is then the full story of WHICH per-route bits
        the engines serve and where each head came from."""
        spec = self._manifest.setdefault("specialists", {})
        key = str(int(route_hash))
        if meta is None:
            if key not in spec:
                return
            del spec[key]
        else:
            spec[key] = meta
        self._write_manifest()

    def specialists(self) -> Dict[str, Any]:
        """{route_hash: head lineage meta} from the manifest."""
        return dict(self._manifest.get("specialists", {}))

    def mark(self, version: int, status: str) -> None:
        for e in self._manifest["versions"]:
            if e["version"] == version:
                e["status"] = status
                if status == "promoted":
                    self._manifest["serving"] = version
                self._write_manifest()
                return
        raise CheckpointError(f"unknown checkpoint version {version}")

    def _apply_retention(self) -> None:
        keep = self._manifest["serving"]
        entries = self._manifest["versions"]
        while len(entries) > self.retain:
            victim = next((e for e in entries if e["version"] != keep), None)
            if victim is None:
                return
            entries.remove(victim)
            self._manifest["pruned"].append(victim["version"])
            try:
                os.unlink(os.path.join(self.directory, victim["file"]))
            except FileNotFoundError:
                pass

    # -- read path --------------------------------------------------------
    def versions(self) -> List[CheckpointMeta]:
        return self._entries()

    def latest(self) -> Optional[int]:
        entries = self._entries()
        return max((e.version for e in entries), default=None)

    def latest_good(self) -> Optional[int]:
        """The serving (last-promoted) version; falls back to the newest
        checkpoint of any status when nothing was ever promoted."""
        serving = self._manifest["serving"]
        if serving is not None:
            return serving
        return self.latest()

    def load(self, version: Optional[int] = None) -> Tuple[int, ModelSnapshot]:
        if version is None:
            version = self.latest_good()
            if version is None:
                raise CheckpointError("empty checkpoint store")
        e = self._entry(version)
        path = os.path.join(self.directory, e.file)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            raise CheckpointCorruptError(
                f"checkpoint v{version} file missing: {e.file}") from None
        if len(data) >= 4 and struct.unpack("<I", data[-4:])[0] != e.crc:
            raise CheckpointCorruptError(
                f"checkpoint v{version}: file CRC does not match manifest")
        return version, decode_snapshot(data)

    # -- integrity --------------------------------------------------------
    def verify(self) -> List[str]:
        """Full-store integrity sweep: CRC of every file, manifest/file
        agreement, lineage, and orphaned files. Returns human-readable
        issues (empty = healthy); used by ``tools/validator.py ckpt``."""
        issues: List[str] = []
        known = {e.version for e in self._entries()}
        pruned = set(self._manifest["pruned"])
        listed_files = set()
        for e in self._entries():
            listed_files.add(e.file)
            path = os.path.join(self.directory, e.file)
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except FileNotFoundError:
                issues.append(f"v{e.version}: file {e.file} missing")
                continue
            if len(data) < 4:
                issues.append(f"v{e.version}: file {e.file} truncated")
                continue
            if struct.unpack("<I", data[-4:])[0] != e.crc:
                issues.append(
                    f"v{e.version}: manifest CRC {e.crc:#010x} does not "
                    f"match file")
                continue
            try:
                decode_snapshot(data)
            except CheckpointError as exc:
                issues.append(f"v{e.version}: {exc}")
            if e.parent is not None and e.parent not in known \
                    and e.parent not in pruned:
                issues.append(
                    f"v{e.version}: parent v{e.parent} unknown "
                    f"(lineage break)")
        serving = self._manifest["serving"]
        if serving is not None and serving not in known:
            issues.append(f"serving version v{serving} not in manifest")
        for fname in os.listdir(self.directory):
            if fname.endswith(".ckpt") and fname not in listed_files:
                issues.append(f"orphaned checkpoint file: {fname}")
        return issues

    def status(self) -> Dict[str, Any]:
        return {
            "directory": self.directory,
            "serving": self._manifest["serving"],
            "retain": self.retain,
            "versions": [dataclasses.asdict(e) for e in self._entries()],
            "pruned": list(self._manifest["pruned"]),
            "specialists": self.specialists(),
        }
