"""Population-drift detection against the serving checkpoint's stats.

A promoted checkpoint freezes the feature distribution the model was
judged against (its mu/var normalization stats). As live traffic evolves,
this monitor tracks EWMA feature means/variances and the score
distribution, and exports shift gauges through the telemetry metrics
registry (``anomaly/drift/...`` in /admin/metrics.json) and /model.json:

- ``feature_shift``  — mean |live_mu - ref_mu| / sqrt(ref_var), i.e. how
  many sigmas the average feature has wandered from the checkpoint.
- ``var_log_ratio``  — mean |log(live_var / ref_var)|: spread change.
- ``score_shift``    — |live score mean - reference score mean| in units
  of the reference score std.

High drift means the serving model is normalizing today's traffic with
yesterday's statistics — the operator signal to retrain/promote sooner
(or distrust scores), per Solyx-style telemetry-aware routing needing
trustworthy, refreshable models (arxiv 2606.15050).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

_VAR_FLOOR = 1e-2  # matches models.anomaly.normalize_features


class DriftMonitor:
    """Running feature/score population stats vs. a reference snapshot.

    ``node`` is a MetricsTree scope (gauges register under it); pass None
    for registry-less use (unit tests, standalone evaluation).
    """

    def __init__(self, node=None, momentum: float = 0.05):
        if not 0.0 < momentum <= 1.0:
            raise ValueError("momentum must be in (0, 1]")
        self.momentum = momentum
        self._ref_mu: Optional[np.ndarray] = None
        self._ref_var: Optional[np.ndarray] = None
        self._ref_score_mean: Optional[float] = None
        self._ref_score_std: Optional[float] = None
        self.reference_version: Optional[int] = None
        self.reference_step: Optional[int] = None
        self._live_mu: Optional[np.ndarray] = None
        self._live_var: Optional[np.ndarray] = None
        self._live_score_mean: Optional[float] = None
        self._live_score_std: Optional[float] = None
        self.batches_observed = 0
        self._gauges: Dict[str, Any] = {}
        if node is not None:
            for name in ("feature_shift", "var_log_ratio", "score_shift",
                         "score_mean"):
                self._gauges[name] = node.gauge(name)

    # -- reference --------------------------------------------------------
    def set_reference(self, mu: np.ndarray, var: np.ndarray,
                      version: Optional[int] = None,
                      step: Optional[int] = None) -> None:
        """Anchor drift to a checkpoint's normalization stats. The score
        reference re-anchors to the live score distribution at promotion
        time (scores immediately after a promotion are 'normal')."""
        self._ref_mu = np.asarray(mu, np.float32).copy()
        self._ref_var = np.asarray(var, np.float32).copy()
        self.reference_version = version
        self.reference_step = step
        self._ref_score_mean = self._live_score_mean
        self._ref_score_std = self._live_score_std
        self._publish()

    # -- observation ------------------------------------------------------
    def observe(self, x: np.ndarray, scores: Optional[np.ndarray] = None) -> None:
        """Fold one micro-batch of raw features (+ optional scores) into
        the live EWMA stats and refresh the gauges. O(batch * dim) numpy;
        called once per drained batch, not per request."""
        x = np.asarray(x, np.float32)
        if x.ndim != 2 or len(x) == 0:
            return
        mu = x.mean(axis=0)
        var = x.var(axis=0)
        m = self.momentum
        if self._live_mu is None:
            self._live_mu, self._live_var = mu, var
        else:
            self._live_mu = (1 - m) * self._live_mu + m * mu
            self._live_var = (1 - m) * self._live_var + m * var
        if scores is not None and len(scores):
            s = np.asarray(scores, np.float32)
            sm, ss = float(s.mean()), float(s.std())
            if self._live_score_mean is None:
                self._live_score_mean, self._live_score_std = sm, ss
            else:
                self._live_score_mean = \
                    (1 - m) * self._live_score_mean + m * sm
                self._live_score_std = \
                    (1 - m) * self._live_score_std + m * ss
        self.batches_observed += 1
        self._publish()

    # -- derived gauges ---------------------------------------------------
    def feature_shift(self) -> float:
        if self._ref_mu is None or self._live_mu is None:
            return 0.0
        z = np.abs(self._live_mu - self._ref_mu) \
            / np.sqrt(self._ref_var + _VAR_FLOOR)
        return float(z.mean())

    def var_log_ratio(self) -> float:
        if self._ref_var is None or self._live_var is None:
            return 0.0
        ratio = (self._live_var + _VAR_FLOOR) / (self._ref_var + _VAR_FLOOR)
        return float(np.abs(np.log(ratio)).mean())

    def score_shift(self) -> float:
        if self._ref_score_mean is None or self._live_score_mean is None:
            return 0.0
        denom = max(self._ref_score_std or 0.0, 1e-3)
        return abs(self._live_score_mean - self._ref_score_mean) / denom

    def _publish(self) -> None:
        if not self._gauges:
            return
        self._gauges["feature_shift"].set(self.feature_shift())
        self._gauges["var_log_ratio"].set(self.var_log_ratio())
        self._gauges["score_shift"].set(self.score_shift())
        self._gauges["score_mean"].set(self._live_score_mean or 0.0)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "feature_shift": self.feature_shift(),
            "var_log_ratio": self.var_log_ratio(),
            "score_shift": self.score_shift(),
            "score_mean": self._live_score_mean,
            "score_std": self._live_score_std,
            "batches_observed": self.batches_observed,
            "reference_version": self.reference_version,
            "reference_step": self.reference_step,
        }
