"""Model lifecycle subsystem for the anomaly scorer.

The model artifact is a first-class, versioned, gated object:

    capture -> train -> checkpoint -> shadow-eval -> promote -> hot-swap
                                          |
                                          +-> reject -> rollback

- ``store``   — atomic, CRC-checked, versioned snapshots with lineage
  and retention (``CheckpointStore``, ``ModelSnapshot``).
- ``promote`` — held-out replay window, shadow evaluation, promotion
  gate, and the ``ModelLifecycleManager`` orchestrating the loop.
- ``drift``   — population-stats shift vs. the serving checkpoint,
  exported through the metrics registry and /model.json.

Configured from YAML via the jaxAnomaly telemeter's ``lifecycle`` block
(``LifecycleConfig``); the scorers' ``snapshot()``/``restore()``/
``swap()`` hooks (in-process and gRPC sidecar) do the hot-swapping.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from linkerd_tpu.lifecycle.drift import DriftMonitor
from linkerd_tpu.lifecycle.export import (
    BANK_MAGIC, DELTA_MAGIC, WEIGHT_MAGIC, blob_meta, export_bank_blob,
    export_delta_blob, export_weight_blob, route_hash,
)
from linkerd_tpu.lifecycle.promote import (
    Decision, EvalReport, GatePolicy, ModelLifecycleManager, PromotionGate,
    ReplayWindow, evaluate_snapshot,
)
from linkerd_tpu.lifecycle.store import (
    CheckpointCorruptError, CheckpointError, CheckpointStore, ModelSnapshot,
    decode_snapshot, encode_snapshot,
)


@dataclass
class LifecycleConfig:
    """YAML ``lifecycle:`` block of the io.l5d.jaxAnomaly telemeter."""

    directory: str                   # checkpoint store root (required)
    checkpointEveryS: float = 300.0  # gating-cycle cadence; 0 = manual only
    retain: int = 5                  # versions kept (serving never pruned)
    aucTolerance: float = 0.02
    lossTolerance: float = 0.10
    minLabeled: int = 8
    replayCapacity: int = 4096       # held-out window, rows
    minReplayRows: int = 256         # gate only once the window is warm
    # every Nth drained batch is diverted to the replay window and
    # EXCLUDED from training — the shadow-eval set must be held out from
    # the candidate, or a poisoned training stream would evaluate best
    # on its own poison and sail through the gate
    holdoutEveryBatches: int = 4
    restoreOnStart: bool = True      # survive restarts from last-good

    def mk_manager(self, metrics_node=None) -> ModelLifecycleManager:
        store = CheckpointStore(self.directory, retain=self.retain)
        gate = PromotionGate(GatePolicy(
            aucTolerance=self.aucTolerance,
            lossTolerance=self.lossTolerance,
            minLabeled=self.minLabeled))
        replay = ReplayWindow(self.replayCapacity)
        drift = DriftMonitor(metrics_node)
        return ModelLifecycleManager(
            store, gate, replay, drift=drift,
            min_replay_rows=self.minReplayRows)


__all__ = [
    "BANK_MAGIC", "CheckpointCorruptError", "CheckpointError",
    "CheckpointStore", "DELTA_MAGIC", "Decision", "DriftMonitor",
    "EvalReport", "GatePolicy", "LifecycleConfig",
    "ModelLifecycleManager", "ModelSnapshot", "PromotionGate",
    "ReplayWindow", "WEIGHT_MAGIC", "blob_meta", "decode_snapshot",
    "encode_snapshot", "evaluate_snapshot", "export_bank_blob",
    "export_delta_blob", "export_weight_blob", "route_hash",
]
