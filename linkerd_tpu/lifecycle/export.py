"""Native weight-blob export: the trained scorer, flattened for C++.

``export_weight_blob`` turns a ``ModelSnapshot`` (the same host-side
capture the CheckpointStore persists) into the versioned flat blob the
native engines evaluate in-data-plane (``native/scorer.h``). The format
is the seam between the JAX training tier and the C++ serving tier —
keep it in lockstep with ``l5dscore::parse_blob``:

    magic "L5DWTS01" | u32 version | u32 quant (0=f32, 1=int8)
    | u32 in_dim | u32 n_enc | u32 n_dec | u32 n_cls | f32 recon_weight
    | f32 mu[in_dim] | f32 var[in_dim]
    | per layer (enc..., dec..., cls...):
        u32 rows | u32 cols | f32 b[cols]
        | quant 0: f32 w[rows*cols]   (row-major: w[i][j] = in i -> out j)
        | quant 1: f32 scale[cols] | i8 w[rows*cols]
    | u32 crc32 (zlib, over everything before it)

int8 quantization is symmetric per OUTPUT column — scale[j] =
max|w[:, j]| / 127 — with f32 biases and f32 accumulation on the C++
side, so the error stays a per-weight rounding effect. The trailing
CRC mirrors the CheckpointStore's integrity posture: a flipped bit is a
rejected publish, never silently-wrong scores.

Everything here is host-side numpy on an already-gathered snapshot: the
export path must never touch the device (it runs at promote/hot-swap
time next to the serving loop) — the l5dlint ``jax-hotpath`` rule roots
``export_weight_blob`` to keep it that way.
"""

from __future__ import annotations

import struct
import zlib
from typing import Any, Dict, List, Optional

import numpy as np

WEIGHT_MAGIC = b"L5DWTS01"
QUANT_F32 = 0
QUANT_INT8 = 1
_QUANTS = {"f32": QUANT_F32, "int8": QUANT_INT8}


def _f32(arr) -> np.ndarray:
    return np.ascontiguousarray(arr, dtype=np.float32)


def _layer_chunks(layer: Dict[str, Any], quant: int) -> List[bytes]:
    w = _f32(layer["w"])
    b = _f32(layer["b"])
    if w.ndim != 2 or b.ndim != 1 or w.shape[1] != b.shape[0]:
        raise ValueError(
            f"layer shapes do not form a dense layer: w {w.shape}, "
            f"b {b.shape}")
    rows, cols = w.shape
    out = [struct.pack("<II", rows, cols), b.tobytes()]
    if quant == QUANT_F32:
        out.append(w.tobytes())
    else:
        scale = np.abs(w).max(axis=0) / 127.0
        scale = np.where(scale > 0, scale, 1.0).astype(np.float32)
        wq = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
        out.append(_f32(scale).tobytes())
        out.append(np.ascontiguousarray(wq).tobytes())
    return out


def export_weight_blob(snap, version: int, quant: str = "f32") -> bytes:
    """``ModelSnapshot`` -> native weight blob (bytes, CRC'd).

    ``version`` stamps the blob (the checkpoint version on a lifecycle
    publish, the train step otherwise) so /model.json and the engine
    stats can prove WHICH model the data plane is serving.
    """
    if quant not in _QUANTS:
        raise ValueError(f"quant must be one of {sorted(_QUANTS)}, "
                         f"got {quant!r}")
    q = _QUANTS[quant]
    params = snap.params
    enc = list(params["enc"])
    dec = list(params["dec"])
    cls = list(params["cls"])
    if not enc or not dec or not cls:
        raise ValueError("snapshot params missing enc/dec/cls layers")
    mu = _f32(snap.mu)
    var = _f32(snap.var)
    in_dim = int(np.asarray(params["enc"][0]["w"]).shape[0])  # l5d: ignore[jax-hotpath] — snapshot params are host numpy already; shape probe, not a readback
    if mu.shape != (in_dim,) or var.shape != (in_dim,):
        raise ValueError(
            f"normalization stats ({mu.shape}/{var.shape}) do not match "
            f"in_dim {in_dim}")
    chunks = [
        WEIGHT_MAGIC,
        struct.pack("<IIIIII", int(version), q, in_dim,
                    len(enc), len(dec), len(cls)),
        struct.pack("<f", float(snap.cfg.recon_weight)),
        mu.tobytes(),
        var.tobytes(),
    ]
    for layer in enc + dec + cls:
        chunks.extend(_layer_chunks(layer, q))
    body = b"".join(chunks)
    return body + struct.pack("<I", zlib.crc32(body))


def blob_meta(blob: bytes) -> Optional[Dict[str, Any]]:
    """Header + CRC of an exported blob, without the native lib (the
    telemeter records this for /model.json). None on a malformed blob.
    """
    if len(blob) < len(WEIGHT_MAGIC) + 28 + 4 \
            or not blob.startswith(WEIGHT_MAGIC):
        return None
    body, (crc,) = blob[:-4], struct.unpack("<I", blob[-4:])
    if zlib.crc32(body) != crc:
        return None
    version, q, in_dim, n_enc, n_dec, n_cls = struct.unpack_from(
        "<IIIIII", blob, len(WEIGHT_MAGIC))
    return {
        "version": int(version),
        "crc": int(crc),
        "quant": "int8" if q == QUANT_INT8 else "f32",
        "in_dim": int(in_dim),
        "layers": int(n_enc + n_dec + n_cls),
        "bytes": len(blob),
    }
