"""Native weight-blob export: the trained scorer, flattened for C++.

``export_weight_blob`` turns a ``ModelSnapshot`` (the same host-side
capture the CheckpointStore persists) into the versioned flat blob the
native engines evaluate in-data-plane (``native/scorer.h``). The format
is the seam between the JAX training tier and the C++ serving tier —
keep it in lockstep with ``l5dscore::parse_bank_blob``. A "model
section" is the quant-tagged dense stack:

    u32 version | u32 quant (0=f32, 1=int8, 2=int4)
    | u32 in_dim | u32 n_enc | u32 n_dec | u32 n_cls | f32 recon_weight
    | f32 mu[in_dim] | f32 var[in_dim]
    | per layer (enc..., dec..., cls...):
        u32 rows | u32 cols | f32 b[cols]
        | quant 0: f32 w[rows*cols]   (row-major: w[i][j] = in i -> out j)
        | quant 1: f32 scale[cols] | i8 w[rows*cols]
        | quant 2: f32 scale[cols] | u8 packed[(rows*cols+1)//2]
                   (two 4-bit two's-complement weights per byte, low
                   nibble first, row-major, values in [-7, 7])

Three blob kinds share it, each tailed by u32 crc32 (zlib, over
everything before it):

    "L5DWTS01" | <model section> | crc          — one global model
    "L5DWTS02" | u32 generation | u32 n_heads
               | <model section>                — the base model
               | per head (route_hash ascending):
                   u32 route_hash | <model section>
               | crc                            — specialist bank
    "L5DWTD01" | u32 base_generation | u32 new_generation | u32 n_ops
               | per op: u32 op (0=upsert, 1=remove) | u32 route_hash
                         | upsert: <model section>
               | crc                            — per-route delta patch

int8/int4 quantization is symmetric per OUTPUT column — scale[j] =
max|w[:, j]| / 127 (or / 7) — with f32 biases and f32 accumulation on
the C++ side, so the error stays a per-weight rounding effect. The
trailing CRC mirrors the CheckpointStore's integrity posture: a flipped
bit is a rejected publish, never silently-wrong scores. Deltas carry a
generation fence: the engine refuses a patch whose base_generation is
not the generation of its ACTIVE bank.

Everything here is host-side numpy on an already-gathered snapshot: the
export path must never touch the device (it runs at promote/hot-swap
time next to the serving loop) — the l5dlint ``jax-hotpath`` rule roots
``export_weight_blob``/``export_bank_blob``/``export_delta_blob`` to
keep it that way.
"""

from __future__ import annotations

import struct
import zlib
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

WEIGHT_MAGIC = b"L5DWTS01"
BANK_MAGIC = b"L5DWTS02"
DELTA_MAGIC = b"L5DWTD01"
QUANT_F32 = 0
QUANT_INT8 = 1
QUANT_INT4 = 2
_QUANTS = {"f32": QUANT_F32, "int8": QUANT_INT8, "int4": QUANT_INT4}
_QUANT_NAMES = {v: k for k, v in _QUANTS.items()}
DELTA_OP_UPSERT = 0
DELTA_OP_REMOVE = 1
MAX_HEADS = 256      # must match l5dscore::MAX_HEADS
MAX_DELTA_OPS = 64   # must match l5dscore::MAX_DELTA_OPS


def route_hash(dst_path: str) -> int:
    """FNV-1a 32-bit of a dst path — the specialist-bank head key. The
    same function (and fold-0-to-1 rule) as the engines' tenant/route
    hashing (``l5dtg::tenant_hash``; parity-pinned): hash 0 means "no
    head pushed" in the engine, so a real path hashing to 0 folds to 1.
    """
    h = 2166136261
    for b in dst_path.encode("utf-8", "surrogateescape"):
        h ^= b
        h = (h * 16777619) & 0xFFFFFFFF
    return h if h != 0 else 1


def _f32(arr) -> np.ndarray:
    return np.ascontiguousarray(arr, dtype=np.float32)


def _layer_chunks(layer: Dict[str, Any], quant: int) -> List[bytes]:
    w = _f32(layer["w"])
    b = _f32(layer["b"])
    if w.ndim != 2 or b.ndim != 1 or w.shape[1] != b.shape[0]:
        raise ValueError(
            f"layer shapes do not form a dense layer: w {w.shape}, "
            f"b {b.shape}")
    rows, cols = w.shape
    out = [struct.pack("<II", rows, cols), b.tobytes()]
    if quant == QUANT_F32:
        out.append(w.tobytes())
    elif quant == QUANT_INT8:
        scale = np.abs(w).max(axis=0) / 127.0
        scale = np.where(scale > 0, scale, 1.0).astype(np.float32)
        wq = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
        out.append(_f32(scale).tobytes())
        out.append(np.ascontiguousarray(wq).tobytes())
    else:  # int4: two's-complement nibbles packed two per byte
        scale = np.abs(w).max(axis=0) / 7.0
        scale = np.where(scale > 0, scale, 1.0).astype(np.float32)
        wq = np.clip(np.round(w / scale), -7, 7).astype(np.int8)
        flat = wq.reshape(-1)
        if len(flat) % 2:
            flat = np.concatenate([flat, np.zeros(1, np.int8)])
        lo = flat[0::2].astype(np.uint8) & 0x0F
        hi = (flat[1::2].astype(np.uint8) & 0x0F) << 4
        out.append(_f32(scale).tobytes())
        out.append(np.ascontiguousarray(lo | hi).tobytes())
    return out


def _model_section(snap, version: int, quant: str) -> List[bytes]:
    """One model section (version through layers) as byte chunks."""
    if quant not in _QUANTS:
        raise ValueError(f"quant must be one of {sorted(_QUANTS)}, "
                         f"got {quant!r}")
    q = _QUANTS[quant]
    params = snap.params
    enc = list(params["enc"])
    dec = list(params["dec"])
    cls = list(params["cls"])
    if not enc or not dec or not cls:
        raise ValueError("snapshot params missing enc/dec/cls layers")
    mu = _f32(snap.mu)
    var = _f32(snap.var)
    in_dim = int(np.asarray(params["enc"][0]["w"]).shape[0])  # l5d: ignore[jax-hotpath] — snapshot params are host numpy already; shape probe, not a readback
    if mu.shape != (in_dim,) or var.shape != (in_dim,):
        raise ValueError(
            f"normalization stats ({mu.shape}/{var.shape}) do not match "
            f"in_dim {in_dim}")
    chunks = [
        struct.pack("<IIIIII", int(version), q, in_dim,
                    len(enc), len(dec), len(cls)),
        struct.pack("<f", float(snap.cfg.recon_weight)),
        mu.tobytes(),
        var.tobytes(),
    ]
    for layer in enc + dec + cls:
        chunks.extend(_layer_chunks(layer, q))
    return chunks


def _sealed(chunks: List[bytes]) -> bytes:
    body = b"".join(chunks)
    return body + struct.pack("<I", zlib.crc32(body))


def export_weight_blob(snap, version: int, quant: str = "f32") -> bytes:
    """``ModelSnapshot`` -> native v1 weight blob (bytes, CRC'd).

    ``version`` stamps the blob (the checkpoint version on a lifecycle
    publish, the train step otherwise) so /model.json and the engine
    stats can prove WHICH model the data plane is serving.
    """
    return _sealed([WEIGHT_MAGIC] + _model_section(snap, version, quant))


def export_bank_blob(base_snap, base_version: int, generation: int,
                     heads: Dict[int, Tuple[int, Any]],
                     quant: str = "f32") -> bytes:
    """Base model + specialist heads -> native v2 bank blob.

    ``heads`` maps route_hash -> (head_version, head ModelSnapshot);
    the wire format requires ascending hashes, so they are sorted here.
    ``generation`` is the bank's fence for later delta patches.
    """
    if len(heads) > MAX_HEADS:
        raise ValueError(
            f"bank carries {len(heads)} heads; the native evaluator "
            f"caps at {MAX_HEADS}")
    chunks = [BANK_MAGIC,
              struct.pack("<II", int(generation), len(heads))]
    chunks.extend(_model_section(base_snap, base_version, quant))
    for rh in sorted(heads):
        if not 0 < rh <= 0xFFFFFFFF:
            raise ValueError(f"route hash out of range: {rh}")
        head_version, head_snap = heads[rh]
        chunks.append(struct.pack("<I", rh))
        chunks.extend(_model_section(head_snap, head_version, quant))
    return _sealed(chunks)


def export_delta_blob(base_generation: int, new_generation: int,
                      upserts: Optional[Dict[int, Tuple[int, Any]]] = None,
                      removes: Iterable[int] = (),
                      quant: str = "f32") -> bytes:
    """Per-route delta patch -> native delta blob.

    ``upserts`` maps route_hash -> (head_version, head ModelSnapshot);
    ``removes`` names heads to drop (a single-route rollback). The
    engine applies the patch only when its active bank's generation is
    ``base_generation`` — a patch can never land on the wrong bank.
    """
    upserts = upserts or {}
    removes = list(removes)
    n_ops = len(upserts) + len(removes)
    if n_ops < 1:
        raise ValueError("delta blob needs at least one op")
    if n_ops > MAX_DELTA_OPS:
        raise ValueError(
            f"delta carries {n_ops} ops; the native evaluator caps at "
            f"{MAX_DELTA_OPS}")
    if int(new_generation) <= int(base_generation):
        raise ValueError(
            f"new_generation ({new_generation}) must exceed "
            f"base_generation ({base_generation})")
    chunks = [DELTA_MAGIC,
              struct.pack("<III", int(base_generation),
                          int(new_generation), n_ops)]
    for rh in sorted(upserts):
        head_version, head_snap = upserts[rh]
        chunks.append(struct.pack("<II", DELTA_OP_UPSERT, rh))
        chunks.extend(_model_section(head_snap, head_version, quant))
    for rh in removes:
        chunks.append(struct.pack("<II", DELTA_OP_REMOVE, int(rh)))
    return _sealed(chunks)


def blob_meta(blob: bytes) -> Optional[Dict[str, Any]]:
    """Header + CRC of an exported blob (v1 model, v2 bank, or delta),
    without the native lib (the telemeter records this for
    /model.json). None on a malformed blob.
    """
    if len(blob) < 8 + 4:
        return None
    body, (crc,) = blob[:-4], struct.unpack("<I", blob[-4:])
    if zlib.crc32(body) != crc:
        return None
    if blob.startswith(DELTA_MAGIC):
        if len(blob) < 8 + 12 + 4:
            return None
        base_gen, new_gen, n_ops = struct.unpack_from("<III", blob, 8)
        return {
            "format": "delta",
            "base_generation": int(base_gen),
            "new_generation": int(new_gen),
            "ops": int(n_ops),
            "crc": int(crc),
            "bytes": len(blob),
        }
    if blob.startswith(BANK_MAGIC):
        if len(blob) < 8 + 8 + 28 + 4:
            return None
        generation, n_heads = struct.unpack_from("<II", blob, 8)
        version, q, in_dim, n_enc, n_dec, n_cls = struct.unpack_from(
            "<IIIIII", blob, 16)
        return {
            "format": "bank",
            "generation": int(generation),
            "heads": int(n_heads),
            "version": int(version),
            "crc": int(crc),
            "quant": _QUANT_NAMES.get(int(q), "?"),
            "in_dim": int(in_dim),
            "layers": int(n_enc + n_dec + n_cls),
            "bytes": len(blob),
        }
    if len(blob) < len(WEIGHT_MAGIC) + 28 + 4 \
            or not blob.startswith(WEIGHT_MAGIC):
        return None
    version, q, in_dim, n_enc, n_dec, n_cls = struct.unpack_from(
        "<IIIIII", blob, len(WEIGHT_MAGIC))
    return {
        "format": "model",
        "version": int(version),
        "crc": int(crc),
        "quant": _QUANT_NAMES.get(int(q), "?"),
        "in_dim": int(in_dim),
        "layers": int(n_enc + n_dec + n_cls),
        "bytes": len(blob),
    }
