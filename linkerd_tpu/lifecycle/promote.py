"""Shadow evaluation + promotion gating for the anomaly scorer.

Online training makes the live model the *candidate*: it drifts with
every ``fit()`` and nothing guarantees the drift was good. The lifecycle
manager periodically shadow-evaluates the live parameters against a
held-out replay window (recent feature batches captured by the
telemeter) and compares them with the last promoted checkpoint:

    capture -> train -> shadow-eval -> promote | rollback -> hot-swap

A candidate is promoted only if its loss/AUC on the replay window does
not regress beyond configured tolerances; a rejected candidate triggers
an automatic rollback — the scorer hot-swaps back to the last-good
version and keeps serving (Taurus-style gated model updates,
arxiv 2002.08987).
"""

from __future__ import annotations

import asyncio
import collections
import time
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

import numpy as np

from linkerd_tpu.lifecycle.store import CheckpointStore, ModelSnapshot

# -- held-out replay window ---------------------------------------------------


class ReplayWindow:
    """Recent feature micro-batches, capped by total rows. The window is
    the shadow-evaluation set: it reflects what the mesh looks like NOW,
    so a candidate that regressed on current traffic fails the gate even
    if it once fit older traffic well."""

    def __init__(self, capacity_rows: int = 4096):
        if capacity_rows < 1:
            raise ValueError("capacity_rows must be >= 1")
        self.capacity_rows = capacity_rows
        self._batches: Deque[Tuple[np.ndarray, np.ndarray, np.ndarray]] = \
            collections.deque()
        self._rows = 0

    def __len__(self) -> int:
        return self._rows

    @property
    def labeled_rows(self) -> int:
        return int(sum(float(m.sum()) for _, _, m in self._batches))

    def add_batch(self, x: np.ndarray, labels: np.ndarray,
                  mask: np.ndarray) -> None:
        x = np.asarray(x, np.float32)
        self._batches.append((x.copy(),
                              np.asarray(labels, np.float32).copy(),
                              np.asarray(mask, np.float32).copy()))
        self._rows += len(x)
        while self._batches and self._rows - len(self._batches[0][0]) \
                >= self.capacity_rows:
            old, _, _ = self._batches.popleft()
            self._rows -= len(old)

    def sample(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        if not self._batches:
            raise ValueError("empty replay window")
        xs, ls, ms = zip(*self._batches)
        return np.concatenate(xs), np.concatenate(ls), np.concatenate(ms)


# -- shadow evaluation --------------------------------------------------------


@dataclass(frozen=True)
class EvalReport:
    loss: float
    auc: float            # nan when the window has too few labeled rows
    score_mean: float
    score_std: float
    n_rows: int
    n_labeled: int

    def as_dict(self) -> Dict[str, Any]:
        return {
            "loss": self.loss,
            "auc": None if np.isnan(self.auc) else self.auc,
            "score_mean": self.score_mean,
            "score_std": self.score_std,
            "n_rows": self.n_rows,
            "n_labeled": self.n_labeled,
        }


def evaluate_snapshot(snap: ModelSnapshot, x: np.ndarray,
                      labels: np.ndarray, mask: np.ndarray) -> EvalReport:
    """Score a snapshot's params over the replay window on the host
    process's default device. Normalization uses the SNAPSHOT's mu/var —
    a candidate is judged with the stats it would serve with."""
    from linkerd_tpu.models.anomaly import (
        anomaly_scores, loss_fn, normalize_features,
    )
    from linkerd_tpu.testing.faults import auc as auc_of

    import jax.numpy as jnp

    z = np.asarray(normalize_features(
        jnp.asarray(x, jnp.float32), jnp.asarray(snap.mu),
        jnp.asarray(snap.var)))
    scores = np.asarray(
        anomaly_scores(snap.params, jnp.asarray(z), snap.cfg), np.float32)
    loss = float(loss_fn(snap.params, jnp.asarray(z),
                         jnp.asarray(labels, jnp.float32),
                         jnp.asarray(mask, jnp.float32), snap.cfg))
    labeled = mask > 0.5
    n_labeled = int(labeled.sum())
    a = float("nan")
    if n_labeled:
        a = auc_of(labels[labeled].tolist(), scores[labeled].tolist())
    return EvalReport(
        loss=loss, auc=a,
        score_mean=float(scores.mean()) if len(scores) else 0.0,
        score_std=float(scores.std()) if len(scores) else 0.0,
        n_rows=len(x), n_labeled=n_labeled)


# -- promotion gate -----------------------------------------------------------


@dataclass(frozen=True)
class GatePolicy:
    aucTolerance: float = 0.02    # candidate AUC may trail serving by this
    lossTolerance: float = 0.10   # candidate loss may exceed serving by 10%
    minLabeled: int = 8           # below this, AUC is noise — gate on loss


@dataclass(frozen=True)
class Decision:
    accepted: bool
    reason: str
    candidate: EvalReport
    serving: Optional[EvalReport]

    def as_dict(self) -> Dict[str, Any]:
        return {
            "accepted": self.accepted,
            "reason": self.reason,
            "candidate": self.candidate.as_dict(),
            "serving": self.serving.as_dict() if self.serving else None,
        }


class PromotionGate:
    def __init__(self, policy: GatePolicy = GatePolicy()):
        self.policy = policy

    def decide(self, candidate: EvalReport,
               serving: Optional[EvalReport]) -> Decision:
        p = self.policy
        if serving is None:
            return Decision(True, "bootstrap (no serving version)",
                            candidate, None)
        if not np.isfinite(candidate.loss):
            return Decision(False, "candidate loss not finite",
                            candidate, serving)
        if candidate.loss > serving.loss * (1.0 + p.lossTolerance):
            return Decision(
                False,
                f"loss regressed: {candidate.loss:.4f} > "
                f"{serving.loss:.4f} * (1 + {p.lossTolerance})",
                candidate, serving)
        both_auc = (candidate.n_labeled >= p.minLabeled
                    and serving.n_labeled >= p.minLabeled
                    and np.isfinite(candidate.auc)
                    and np.isfinite(serving.auc))
        if both_auc and candidate.auc < serving.auc - p.aucTolerance:
            return Decision(
                False,
                f"AUC regressed: {candidate.auc:.4f} < "
                f"{serving.auc:.4f} - {p.aucTolerance}",
                candidate, serving)
        return Decision(True, "within tolerance", candidate, serving)


# -- lifecycle manager --------------------------------------------------------


async def _call_scorer(fn, *args):
    """Invoke a scorer snapshot/restore hook that may be sync (in-process:
    device transfers off the event loop) or async (gRPC sidecar)."""
    if asyncio.iscoroutinefunction(fn):
        return await fn(*args)
    return await asyncio.to_thread(fn, *args)


class ModelLifecycleManager:
    """Ties the checkpoint store, replay window, promotion gate, and
    drift monitor into the capture -> train -> shadow-eval -> promote ->
    hot-swap loop. One instance per jaxAnomaly telemeter."""

    def __init__(self, store: CheckpointStore, gate: PromotionGate,
                 replay: ReplayWindow, drift=None,
                 min_replay_rows: int = 256):
        self.store = store
        self.gate = gate
        self.replay = replay
        self.drift = drift
        self.min_replay_rows = min_replay_rows
        self.serving_version: Optional[int] = store.latest_good()
        self.promotions = 0
        self.rollbacks = 0
        self.rejections = 0
        self.last_promotion: Optional[Dict[str, Any]] = None
        self.last_rollback: Optional[Dict[str, Any]] = None
        self.last_decision: Optional[Dict[str, Any]] = None
        self._lock = asyncio.Lock()

    # -- startup ----------------------------------------------------------
    async def bootstrap(self, scorer) -> Optional[int]:
        """Restore the last-good checkpoint into the scorer, surviving a
        router/sidecar restart (the seed motivation: params must not
        silently reset to random init). No-op on an empty store.

        Holds the cycle lock: a gate cycle promoting v(N+1) while the
        restore await is in flight would otherwise be clobbered — the
        scorer would serve vN with serving_version rolled back under a
        store whose latest promotion is newer."""
        async with self._lock:
            version = self.store.latest_good()
            if version is None:
                return None
            v, snap = self.store.load(version)
            await _call_scorer(scorer.restore, snap)
            self.serving_version = v
            if self.drift is not None:
                self.drift.set_reference(snap.mu, snap.var, version=v,
                                         step=snap.step)
            return v

    # -- the gating cycle -------------------------------------------------
    async def checkpoint(self, scorer, status: str = "candidate") -> int:
        # locked so the parent lineage is the serving version at SAVE
        # time: a promotion completing during the snapshot await would
        # otherwise leave this checkpoint claiming a stale parent
        async with self._lock:
            snap = await _call_scorer(scorer.snapshot)
            return self.store.save(snap, status=status,
                                   parent=self.serving_version)

    async def run_cycle(self, scorer) -> Dict[str, Any]:
        """One checkpoint/shadow-eval/promote-or-rollback pass over the
        live scorer. Returns an outcome dict (also kept as
        ``last_decision`` for /model.json)."""
        async with self._lock:
            snap = await _call_scorer(scorer.snapshot)
            if self.serving_version is None:
                # first ever checkpoint: promote unconditionally so there
                # is a rollback target from now on
                version = self.store.save(snap, status="promoted")
                self.serving_version = version
                self.promotions += 1
                self.last_promotion = {"version": version, "at": time.time(),
                                       "reason": "bootstrap"}
                if self.drift is not None:
                    self.drift.set_reference(snap.mu, snap.var,
                                             version=version, step=snap.step)
                outcome = {"action": "promoted", "version": version,
                           "reason": "bootstrap (no serving version)"}
                self.last_decision = outcome
                return outcome
            if len(self.replay) < self.min_replay_rows:
                outcome = {"action": "skipped",
                           "reason": f"replay window {len(self.replay)} < "
                                     f"{self.min_replay_rows} rows"}
                self.last_decision = outcome
                return outcome

            x, labels, mask = self.replay.sample()
            _, serving_snap = self.store.load(self.serving_version)
            cand_report = await asyncio.to_thread(
                evaluate_snapshot, snap, x, labels, mask)
            serv_report = await asyncio.to_thread(
                evaluate_snapshot, serving_snap, x, labels, mask)
            decision = self.gate.decide(cand_report, serv_report)

            if decision.accepted:
                version = self.store.save(snap, status="promoted",
                                          parent=self.serving_version)
                self.serving_version = version
                self.promotions += 1
                self.last_promotion = {
                    "version": version, "at": time.time(),
                    "reason": decision.reason,
                    "candidate": cand_report.as_dict(),
                }
                if self.drift is not None:
                    self.drift.set_reference(snap.mu, snap.var,
                                             version=version, step=snap.step)
                outcome = {"action": "promoted", "version": version,
                           "decision": decision.as_dict()}
            else:
                # record the rejected candidate for forensics, then
                # hot-swap the scorer back to the last-good version
                rejected = self.store.save(snap, status="rejected",
                                           parent=self.serving_version)
                self.rejections += 1
                await _call_scorer(scorer.restore, serving_snap)
                self.rollbacks += 1
                self.last_rollback = {
                    "to_version": self.serving_version,
                    "rejected_version": rejected,
                    "at": time.time(),
                    "reason": decision.reason,
                }
                outcome = {"action": "rolled_back",
                           "to_version": self.serving_version,
                           "rejected_version": rejected,
                           "decision": decision.as_dict()}
            self.last_decision = outcome
            return outcome

    async def rollback(self, scorer) -> Optional[int]:
        """Explicit rollback to the last-good version (admin-triggered)."""
        async with self._lock:
            version = self.store.latest_good()
            if version is None:
                return None
            v, snap = self.store.load(version)
            await _call_scorer(scorer.restore, snap)
            self.serving_version = v
            self.rollbacks += 1
            self.last_rollback = {"to_version": v, "at": time.time(),
                                  "reason": "manual"}
            return v

    # -- observability ----------------------------------------------------
    def status(self) -> Dict[str, Any]:
        out = {
            "serving_version": self.serving_version,
            "promotions": self.promotions,
            "rollbacks": self.rollbacks,
            "rejections": self.rejections,
            "replay_rows": len(self.replay),
            "replay_labeled_rows": self.replay.labeled_rows,
            "last_promotion": self.last_promotion,
            "last_rollback": self.last_rollback,
            "last_decision": self.last_decision,
            "store": self.store.status(),
        }
        if self.drift is not None:
            out["drift"] = self.drift.snapshot()
        return out
