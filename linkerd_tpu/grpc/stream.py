"""Typed gRPC message streams over h2 frame streams.

Ref: grpc/runtime/.../Stream.scala:162 (pull-based typed stream),
DecodingStream.scala (h2 DATA -> messages), ServerDispatcher's
``Stream.Provider`` side. Pull semantics are preserved: consumers ``recv()``
one message at a time. Note: producer-side frames buffer in-process
unbounded (H2Stream queue); h2 flow control throttles only the socket
drain, not the application producer.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Generic, List, Optional, TypeVar

from linkerd_tpu.grpc.codec import Codec, GrpcFramer
from linkerd_tpu.grpc.status import GrpcError, GrpcStatus, INTERNAL, OK
from linkerd_tpu.protocol.h2.stream import (
    DataFrame, H2Stream, StreamReset, Trailers,
)

T = TypeVar("T")

_END = object()


class GrpcStream(Generic[T]):
    """In-memory typed stream: producer send()/close()/fail(), consumer recv().

    recv() raises ``StopAsyncIteration`` at end-of-stream and ``GrpcError``
    on failure — mirroring Stream.recv's Releasable/end semantics.
    """

    def __init__(self, maxsize: int = 0):
        self._q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)
        self._err: Optional[GrpcError] = None
        self._done = False

    async def send(self, item: T) -> None:
        if self._done:
            raise RuntimeError("send on closed stream")
        await self._q.put(item)

    def close(self) -> None:
        if not self._done:
            self._done = True
            self._q.put_nowait(_END)

    def fail(self, err: GrpcError) -> None:
        if not self._done:
            self._done = True
            self._err = err
            self._q.put_nowait(_END)

    async def recv(self) -> T:
        item = await self._q.get()
        if item is _END:
            self._q.put_nowait(_END)  # keep terminal state observable
            if self._err is not None:
                raise self._err
            raise StopAsyncIteration
        return item

    def __aiter__(self) -> AsyncIterator[T]:
        return self

    async def __anext__(self) -> T:
        return await self.recv()

    @staticmethod
    def of(items: List[T]) -> "GrpcStream[T]":
        s: GrpcStream[T] = GrpcStream()
        for it in items:
            s._q.put_nowait(it)
        s.close()
        return s


class DecodingStream(Generic[T]):
    """Pull typed messages out of an h2 frame stream.

    Reads DATA frames, re-frames gRPC messages across frame boundaries
    (ref: DecodingStream.scala:95), releases h2 frames as they are consumed
    (restoring flow-control window), and resolves the terminal GrpcStatus
    from trailers or reset.
    """

    def __init__(self, h2: H2Stream, codec: Codec):
        self._h2 = h2
        self._codec = codec
        self._framer = GrpcFramer()
        self._ready: List[tuple] = []
        self._status: Optional[GrpcStatus] = None

    @property
    def status(self) -> Optional[GrpcStatus]:
        """Terminal status; None until the stream completes."""
        return self._status

    def resolve_status(self, status: GrpcStatus) -> None:
        """Pre-resolve the terminal status (Trailers-Only responses,
        transport-level failures mapped by the caller)."""
        if self._status is None:
            self._status = status

    async def recv(self) -> T:
        while True:
            if self._ready:
                flag, payload = self._ready.pop(0)
                return self._codec.decode_payload(flag, payload)
            if self._status is not None:
                if not self._status.ok:
                    raise GrpcError(self._status)
                raise StopAsyncIteration
            try:
                frame = await self._h2.read()
            except StreamReset as rst:
                self._status = GrpcStatus.from_reset(rst)
                continue
            if isinstance(frame, DataFrame):
                # release on the exception edge too: a malformed gRPC
                # frame raising out of the re-framer must not strand the
                # h2 flow credit this DATA frame holds
                try:
                    self._ready.extend(self._framer.feed(frame.data))
                    eos = frame.eos
                finally:
                    frame.release()
                if eos and self._status is None:
                    # end without trailers: OK iff no partial message
                    if self._framer.pending_bytes:
                        self._status = GrpcStatus(
                            INTERNAL, "stream ended mid-message "
                            f"({self._framer.pending_bytes}B partial)")
                    else:
                        self._status = GrpcStatus(OK)
            elif isinstance(frame, Trailers):
                try:
                    self._status = GrpcStatus.from_trailers(frame)
                finally:
                    frame.release()
            else:  # pragma: no cover - unknown frame kind
                raise GrpcError.of(13, f"unexpected frame {frame!r}")

    def __aiter__(self) -> AsyncIterator[T]:
        return self

    async def __anext__(self) -> T:
        return await self.recv()

    async def collect(self) -> List[T]:
        out: List[T] = []
        async for m in self:
            out.append(m)
        return out


class EncodingStream:
    """Push typed messages into an h2 frame stream as gRPC frames."""

    def __init__(self, h2: H2Stream, codec: Codec):
        self._h2 = h2
        self._codec = codec

    @property
    def is_broken(self) -> bool:
        """True once the consumer is gone (stream reset) — long-lived
        producers should stop emitting."""
        return self._h2.is_reset

    def send(self, msg) -> None:
        self._h2.offer(DataFrame(self._codec.encode_frame(msg)))

    def close(self, status: GrpcStatus) -> None:
        self._h2.offer(status.to_trailers())

    def close_eos(self) -> None:
        """End with a bare END_STREAM (no trailers) — the wire shape of a
        finished gRPC *request* stream; only responses carry status
        trailers."""
        self._h2.offer(DataFrame(b"", eos=True))

    def fail(self, status: GrpcStatus) -> None:
        self.close(status)
