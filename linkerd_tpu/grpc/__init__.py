"""gRPC stack over the hand-written h2 transport.

TPU-native reimagining of the reference's grpc modules
(ref: grpc/runtime/src/main/scala/io/buoyant/grpc/runtime/ and grpc/gen):
instead of a protoc plugin emitting Scala, messages are declared inline with
a field-descriptor DSL (`proto.py`) that speaks the protobuf wire format, so
service definitions live next to the code that uses them (mesh API, scorer).
"""

from linkerd_tpu.grpc.proto import Enum, Field, MapField, ProtoMessage
from linkerd_tpu.grpc.codec import Codec, GrpcFramer
from linkerd_tpu.grpc.status import GrpcStatus, GrpcError
from linkerd_tpu.grpc.stream import GrpcStream, DecodingStream, EncodingStream
from linkerd_tpu.grpc.dispatch import (
    ClientDispatcher, Rpc, ServerDispatcher, ServiceDef,
)
from linkerd_tpu.grpc.var_event import VarEventStream

__all__ = [
    "Enum", "Field", "MapField", "ProtoMessage", "Codec", "GrpcFramer",
    "GrpcStatus", "GrpcError", "GrpcStream", "DecodingStream",
    "EncodingStream", "ClientDispatcher", "Rpc", "ServerDispatcher",
    "ServiceDef", "VarEventStream",
]
