"""gRPC length-prefixed message framing.

Ref: grpc/runtime/src/main/scala/io/buoyant/grpc/runtime/Codec.scala:130 —
each gRPC message on the wire is a 1-byte compressed flag + 4-byte big-endian
length + payload, possibly split across / coalesced within h2 DATA frames.
``GrpcFramer`` is the incremental re-assembler (ref: DecodingStream.scala).
"""

from __future__ import annotations

import gzip
import struct
from typing import Callable, List, Optional, Type

from linkerd_tpu.grpc.proto import ProtoMessage

_HDR = struct.Struct(">BI")
HEADER_LEN = 5


class Codec:
    """Encode/decode one typed message to/from a gRPC frame."""

    def __init__(self, msg_cls: Type[ProtoMessage], compress: bool = False):
        self.msg_cls = msg_cls
        self.compress = compress

    def encode_frame(self, msg: ProtoMessage) -> bytes:
        payload = msg.encode()
        flag = 0
        if self.compress:
            payload = gzip.compress(payload)
            flag = 1
        return _HDR.pack(flag, len(payload)) + payload

    def decode_payload(self, flag: int, payload: bytes) -> ProtoMessage:
        if flag == 1:
            payload = gzip.decompress(payload)
        elif flag != 0:
            raise ValueError(f"bad gRPC compression flag {flag}")
        return self.msg_cls.decode(payload)


class GrpcFramer:
    """Stateful splitter: feed h2 DATA bytes, emit complete (flag, payload).

    Handles messages spanning multiple DATA frames and multiple messages in
    one DATA frame (ref: DecodingStream.scala:95 incremental re-framing).
    """

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[tuple]:
        self._buf += data
        out = []
        while True:
            if len(self._buf) < HEADER_LEN:
                return out
            flag, length = _HDR.unpack_from(self._buf, 0)
            if len(self._buf) < HEADER_LEN + length:
                return out
            payload = bytes(self._buf[HEADER_LEN:HEADER_LEN + length])
            del self._buf[:HEADER_LEN + length]
            out.append((flag, payload))

    @property
    def pending_bytes(self) -> int:
        return len(self._buf)
