"""VarEventStream: reactive state -> gRPC stream for watch APIs.

Ref: grpc/runtime/.../VarEventStream.scala:150 — serves the *latest* state:
if the consumer is slower than the producer, intermediate states are
coalesced (only the most recent unobserved value is delivered), which is
exactly the semantics namerd's mesh interface needs when pumping
``Activity[NameTree]`` / ``Var[Addr]`` churn to many linkerds.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Callable, Generic, Optional, TypeVar

from linkerd_tpu.core.var import Closable, Var

T = TypeVar("T")
U = TypeVar("U")

_TOMBSTONE = object()


class VarEventStream(Generic[T, U]):
    """Async iterator over ``var``'s states, mapped through ``to_msg``.

    Never buffers more than one pending state. ``close()`` ends iteration
    after any pending value is delivered.
    """

    def __init__(self, var: Var[T],
                 to_msg: Optional[Callable[[T], U]] = None):
        self._to_msg = to_msg or (lambda v: v)
        self._latest: object = _TOMBSTONE
        self._wake = asyncio.Event()
        self._closed = False
        self._obs: Closable = var.observe(self._on_state)

    def _on_state(self, value: T) -> None:
        self._latest = value
        self._wake.set()

    def close(self) -> None:
        self._closed = True
        self._obs.close()
        self._wake.set()

    def __aiter__(self) -> AsyncIterator[U]:
        return self

    async def __anext__(self) -> U:
        while True:
            if self._latest is not _TOMBSTONE:
                value = self._latest
                self._latest = _TOMBSTONE
                self._wake.clear()
                return self._to_msg(value)
            if self._closed:
                raise StopAsyncIteration
            await self._wake.wait()
