"""Protobuf wire-format codec with a declarative message DSL.

The reference generates Scala case classes + codecs from .proto via its own
protoc plugin (ref: grpc/gen/src/main/scala/io/buoyant/grpc/gen/Generator.scala:73-794).
Python needs no codegen: a message is a class with a ``FIELDS`` table; this
module supplies proto3-semantics encode/decode over the standard wire format
(varint / 64-bit / len-delimited / 32-bit), so our messages interoperate with
any protobuf peer (e.g. the reference's mesh API,
mesh/core/src/main/protobuf/interpreter.proto).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple, Type

# wire types
_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5

_SCALAR_WIRE = {
    "int32": _VARINT, "int64": _VARINT, "uint32": _VARINT, "uint64": _VARINT,
    "sint32": _VARINT, "sint64": _VARINT, "bool": _VARINT, "enum": _VARINT,
    "fixed64": _I64, "sfixed64": _I64, "double": _I64,
    "fixed32": _I32, "sfixed32": _I32, "float": _I32,
    "string": _LEN, "bytes": _LEN, "message": _LEN,
}


def encode_varint(value: int) -> bytes:
    if value < 0:  # proto int32/int64 negatives are 10-byte twos-complement
        value &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decode_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def _unzigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _to_signed(v: int, bits: int) -> int:
    if v >= 1 << (bits - 1):
        v -= 1 << bits
    return v


class Field:
    """One field descriptor: wire number, scalar kind, optional nesting."""

    __slots__ = ("number", "kind", "message", "repeated", "packed", "default")

    def __init__(self, number: int, kind: str,
                 message: Optional[type] = None,
                 repeated: bool = False,
                 packed: Optional[bool] = None,
                 default: Any = None):
        if kind not in _SCALAR_WIRE:
            raise ValueError(f"unknown field kind {kind!r}")
        if kind == "message" and message is None:
            raise ValueError("message fields need a message class")
        self.number = number
        self.kind = kind
        self.message = message
        self.repeated = repeated
        # proto3 packs repeated numeric scalars by default
        if packed is None:
            packed = repeated and _SCALAR_WIRE[kind] != _LEN
        self.packed = packed
        if default is None:
            default = [] if repeated else _PROTO_DEFAULTS.get(kind)
        self.default = default


_PROTO_DEFAULTS: Dict[str, Any] = {
    "int32": 0, "int64": 0, "uint32": 0, "uint64": 0, "sint32": 0,
    "sint64": 0, "bool": False, "enum": 0, "fixed64": 0, "sfixed64": 0,
    "double": 0.0, "fixed32": 0, "sfixed32": 0, "float": 0.0,
    "string": "", "bytes": b"", "message": None,
}


class MapField:
    """A proto3 ``map<K, V>`` field: a dict on the message, encoded as
    repeated entry submessages {1: key, 2: value} per the spec."""

    __slots__ = ("number", "key_kind", "val_kind", "val_message")

    def __init__(self, number: int, key_kind: str, val_kind: str,
                 val_message: Optional[type] = None):
        if key_kind not in _SCALAR_WIRE or key_kind in ("message", "bytes",
                                                        "float", "double"):
            raise ValueError(f"invalid map key kind {key_kind!r}")
        if val_kind not in _SCALAR_WIRE:
            raise ValueError(f"unknown map value kind {val_kind!r}")
        if val_kind == "message" and val_message is None:
            raise ValueError("message-valued maps need a message class")
        self.number = number
        self.key_kind = key_kind
        self.val_kind = val_kind
        self.val_message = val_message

    def encode_entries(self, d: Dict[Any, Any]) -> bytes:
        out = bytearray()
        tag = encode_varint((self.number << 3) | _LEN)
        ktag = encode_varint((1 << 3) | _SCALAR_WIRE[self.key_kind])
        vtag = encode_varint((2 << 3) | _SCALAR_WIRE[self.val_kind])
        for k, v in d.items():
            payload = ktag + _encode_scalar(self.key_kind, k)
            if not (self.val_kind != "message" and
                    v == _PROTO_DEFAULTS.get(self.val_kind)):
                payload += vtag + _encode_scalar(self.val_kind, v)
            out += tag
            out += encode_varint(len(payload))
            out += payload
        return bytes(out)

    def decode_entry(self, chunk: bytes) -> Tuple[Any, Any]:
        key = _PROTO_DEFAULTS.get(self.key_kind)
        val = (self.val_message() if self.val_kind == "message"
               else _PROTO_DEFAULTS.get(self.val_kind))
        pos = 0
        while pos < len(chunk):
            k, pos = decode_varint(chunk, pos)
            number, wire = k >> 3, k & 0x7
            if number == 1:
                key, pos = _decode_scalar(self.key_kind, None, chunk, pos,
                                          wire)
            elif number == 2:
                val, pos = _decode_scalar(self.val_kind, self.val_message,
                                          chunk, pos, wire)
            else:
                pos = _skip(chunk, pos, wire)
        return key, val


def _encode_scalar(kind: str, value: Any) -> bytes:
    if kind in ("int32", "int64", "uint32", "uint64", "enum"):
        return encode_varint(int(value))
    if kind in ("sint32", "sint64"):
        return encode_varint(_zigzag(int(value)))
    if kind == "bool":
        return encode_varint(1 if value else 0)
    if kind in ("fixed64", "sfixed64"):
        return struct.pack("<q" if kind == "sfixed64" else "<Q", int(value))
    if kind == "double":
        return struct.pack("<d", float(value))
    if kind in ("fixed32", "sfixed32"):
        return struct.pack("<i" if kind == "sfixed32" else "<I", int(value))
    if kind == "float":
        return struct.pack("<f", float(value))
    if kind == "string":
        b = value.encode("utf-8")
        return encode_varint(len(b)) + b
    if kind == "bytes":
        b = bytes(value)
        return encode_varint(len(b)) + b
    if kind == "message":
        b = value.encode()
        return encode_varint(len(b)) + b
    raise AssertionError(kind)


def _decode_scalar(kind: str, message: Optional[type],
                   data: bytes, pos: int, wire: int) -> Tuple[Any, int]:
    if wire == _VARINT:
        raw, pos = decode_varint(data, pos)
        if kind in ("sint32", "sint64"):
            return _unzigzag(raw), pos
        if kind == "bool":
            return bool(raw), pos
        if kind == "int32":
            return _to_signed(raw & 0xFFFFFFFFFFFFFFFF, 64), pos
        if kind == "int64":
            return _to_signed(raw, 64), pos
        return raw, pos
    if wire == _I64:
        chunk = data[pos:pos + 8]
        pos += 8
        if kind == "double":
            return struct.unpack("<d", chunk)[0], pos
        if kind == "sfixed64":
            return struct.unpack("<q", chunk)[0], pos
        return struct.unpack("<Q", chunk)[0], pos
    if wire == _I32:
        chunk = data[pos:pos + 4]
        pos += 4
        if kind == "float":
            return struct.unpack("<f", chunk)[0], pos
        if kind == "sfixed32":
            return struct.unpack("<i", chunk)[0], pos
        return struct.unpack("<I", chunk)[0], pos
    if wire == _LEN:
        ln, pos = decode_varint(data, pos)
        chunk = data[pos:pos + ln]
        if len(chunk) != ln:
            raise ValueError("truncated length-delimited field")
        pos += ln
        if kind == "string":
            return chunk.decode("utf-8"), pos
        if kind == "bytes":
            return chunk, pos
        if kind == "message":
            return message.decode(chunk), pos
        raise ValueError(f"{kind} cannot be length-delimited")
    raise ValueError(f"unsupported wire type {wire}")


def _skip(data: bytes, pos: int, wire: int) -> int:
    if wire == _VARINT:
        _, pos = decode_varint(data, pos)
        return pos
    if wire == _I64:
        return pos + 8
    if wire == _I32:
        return pos + 4
    if wire == _LEN:
        ln, pos = decode_varint(data, pos)
        return pos + ln
    raise ValueError(f"cannot skip wire type {wire}")


class ProtoMessage:
    """Base class; subclasses declare ``FIELDS: Dict[str, Field]``."""

    FIELDS: Dict[str, Field] = {}

    def __init__(self, **kwargs: Any):
        for name, fd in self.FIELDS.items():
            if name in kwargs:
                v = kwargs.pop(name)
            elif isinstance(fd, MapField):
                v = {}
            elif fd.repeated:
                v = []
            else:
                v = fd.default
            setattr(self, name, v)
        if kwargs:
            raise TypeError(f"unknown fields {sorted(kwargs)} "
                            f"for {type(self).__name__}")

    def encode(self) -> bytes:
        out = bytearray()
        for name, fd in self.FIELDS.items():
            value = getattr(self, name)
            if isinstance(fd, MapField):
                if value:
                    out += fd.encode_entries(value)
                continue
            wire = _SCALAR_WIRE[fd.kind]
            tag = encode_varint((fd.number << 3) | wire)
            if fd.repeated:
                if not value:
                    continue
                if fd.packed:
                    payload = b"".join(
                        _encode_scalar(fd.kind, v) for v in value)
                    out += encode_varint((fd.number << 3) | _LEN)
                    out += encode_varint(len(payload))
                    out += payload
                else:
                    for v in value:
                        out += tag
                        out += _encode_scalar(fd.kind, v)
            else:
                if value is None:
                    continue
                # proto3: zero-valued scalars are omitted (messages always
                # emitted when present/non-None so presence survives)
                if fd.kind != "message" and value == fd.default:
                    continue
                out += tag
                out += _encode_scalar(fd.kind, value)
        return bytes(out)

    @classmethod
    def decode(cls, data: bytes) -> "ProtoMessage":
        by_number = {fd.number: (name, fd) for name, fd in cls.FIELDS.items()}
        msg = cls()
        pos = 0
        while pos < len(data):
            key, pos = decode_varint(data, pos)
            number, wire = key >> 3, key & 0x7
            entry = by_number.get(number)
            if entry is None:
                pos = _skip(data, pos, wire)
                continue
            name, fd = entry
            if isinstance(fd, MapField):
                if wire != _LEN:
                    pos = _skip(data, pos, wire)
                    continue
                ln, pos = decode_varint(data, pos)
                chunk = data[pos:pos + ln]
                if len(chunk) != ln:
                    raise ValueError("truncated map entry")
                pos += ln
                k, v = fd.decode_entry(chunk)
                getattr(msg, name)[k] = v
                continue
            if fd.repeated and wire == _LEN and \
                    _SCALAR_WIRE[fd.kind] != _LEN:
                # packed numeric run
                ln, pos = decode_varint(data, pos)
                end = pos + ln
                vals = getattr(msg, name)
                while pos < end:
                    v, pos = _decode_scalar(
                        fd.kind, fd.message, data, pos, _SCALAR_WIRE[fd.kind])
                    vals.append(v)
                continue
            v, pos = _decode_scalar(fd.kind, fd.message, data, pos, wire)
            if fd.repeated:
                getattr(msg, name).append(v)
            else:
                setattr(msg, name, v)
        return msg

    def __eq__(self, other: Any) -> bool:
        if type(other) is not type(self):
            return NotImplemented
        return all(getattr(self, n) == getattr(other, n) for n in self.FIELDS)

    def __repr__(self) -> str:
        parts = []
        for name, fd in self.FIELDS.items():
            v = getattr(self, name)
            if v is None or (isinstance(fd, MapField) and not v) or (
                    not isinstance(fd, MapField) and fd.repeated and not v):
                continue
            parts.append(f"{name}={v!r}")
        return f"{type(self).__name__}({', '.join(parts)})"


class Enum:
    """Namespace helper for proto enums: class attrs are int values."""

    @classmethod
    def name_of(cls, value: int) -> str:
        for k, v in vars(cls).items():
            if not k.startswith("_") and v == value:
                return k
        return str(value)
