"""gRPC status model and its h2 mapping.

Ref: grpc/runtime/src/main/scala/io/buoyant/grpc/runtime/GrpcStatus.scala —
statuses surface either as trailers (``grpc-status``/``grpc-message``) or as
h2 RST codes; both directions are implemented here.
"""

from __future__ import annotations

from typing import List, Optional, Tuple
from urllib.parse import quote, unquote

from linkerd_tpu.protocol.h2.stream import StreamReset, Trailers

# canonical status codes
OK = 0
CANCELED = 1
UNKNOWN = 2
INVALID_ARGUMENT = 3
DEADLINE_EXCEEDED = 4
NOT_FOUND = 5
ALREADY_EXISTS = 6
PERMISSION_DENIED = 7
RESOURCE_EXHAUSTED = 8
FAILED_PRECONDITION = 9
ABORTED = 10
OUT_OF_RANGE = 11
UNIMPLEMENTED = 12
INTERNAL = 13
UNAVAILABLE = 14
DATA_LOSS = 15
UNAUTHENTICATED = 16

_NAMES = {
    0: "OK", 1: "CANCELED", 2: "UNKNOWN", 3: "INVALID_ARGUMENT",
    4: "DEADLINE_EXCEEDED", 5: "NOT_FOUND", 6: "ALREADY_EXISTS",
    7: "PERMISSION_DENIED", 8: "RESOURCE_EXHAUSTED", 9: "FAILED_PRECONDITION",
    10: "ABORTED", 11: "OUT_OF_RANGE", 12: "UNIMPLEMENTED", 13: "INTERNAL",
    14: "UNAVAILABLE", 15: "DATA_LOSS", 16: "UNAUTHENTICATED",
}

# h2 RST code <-> grpc status (GrpcStatus.scala fromReset/toReset)
from linkerd_tpu.protocol.h2.frames import (  # noqa: E402
    CANCEL as _RST_CANCEL,
    ENHANCE_YOUR_CALM as _RST_ENHANCE_YOUR_CALM,
    INTERNAL_ERROR as _RST_INTERNAL_ERROR,
    NO_ERROR as _RST_NO_ERROR,
    PROTOCOL_ERROR as _RST_PROTOCOL_ERROR,
    REFUSED_STREAM as _RST_REFUSED,
)


class GrpcStatus:
    __slots__ = ("code", "message")

    def __init__(self, code: int = OK, message: str = ""):
        self.code = code
        self.message = message

    @property
    def ok(self) -> bool:
        return self.code == OK

    @property
    def name(self) -> str:
        return _NAMES.get(self.code, str(self.code))

    def to_trailers(self) -> Trailers:
        items: List[Tuple[str, str]] = [("grpc-status", str(self.code))]
        if self.message:
            items.append(("grpc-message", quote(self.message)))
        return Trailers(items)

    def to_headers(self) -> List[Tuple[str, str]]:
        items = [("grpc-status", str(self.code))]
        if self.message:
            items.append(("grpc-message", quote(self.message)))
        return items

    @staticmethod
    def from_trailers(trailers: Optional[Trailers]) -> "GrpcStatus":
        if trailers is None:
            return GrpcStatus(UNKNOWN, "missing grpc-status trailers")
        code_s = None
        msg = ""
        for k, v in trailers.headers:
            if k == "grpc-status":
                code_s = v
            elif k == "grpc-message":
                msg = unquote(v)
        if code_s is None:
            return GrpcStatus(UNKNOWN, "missing grpc-status")
        try:
            return GrpcStatus(int(code_s), msg)
        except ValueError:
            return GrpcStatus(UNKNOWN, f"bad grpc-status {code_s!r}")

    @staticmethod
    def from_reset(reset: StreamReset) -> "GrpcStatus":
        code = {
            _RST_NO_ERROR: UNAVAILABLE,
            _RST_PROTOCOL_ERROR: INTERNAL,
            _RST_INTERNAL_ERROR: INTERNAL,
            _RST_REFUSED: UNAVAILABLE,
            _RST_CANCEL: CANCELED,
            _RST_ENHANCE_YOUR_CALM: RESOURCE_EXHAUSTED,
        }.get(reset.error_code, UNKNOWN)
        return GrpcStatus(code, reset.message or f"rst={reset.error_code}")

    def to_reset_code(self) -> int:
        return {
            CANCELED: _RST_CANCEL,
            RESOURCE_EXHAUSTED: _RST_ENHANCE_YOUR_CALM,
            UNAVAILABLE: _RST_REFUSED,
        }.get(self.code, _RST_INTERNAL_ERROR)

    def __repr__(self) -> str:
        return f"GrpcStatus({self.name}, {self.message!r})"


class GrpcError(Exception):
    """Raised client-side for non-OK statuses; carries the status."""

    def __init__(self, status: GrpcStatus):
        super().__init__(f"{status.name}: {status.message}")
        self.status = status

    @staticmethod
    def of(code: int, message: str = "") -> "GrpcError":
        return GrpcError(GrpcStatus(code, message))
