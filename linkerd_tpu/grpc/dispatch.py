"""gRPC client/server dispatchers over h2 request/response.

Ref: grpc/runtime/.../ServerDispatcher.scala:8-170 (the four rpc shapes:
Unary/Stream request x Unary/Stream response) and ClientDispatcher.scala:131.
A service is declared as a ``ServiceDef`` of ``Rpc``s; the server side is a
plain ``Service[H2Request, H2Response]`` so it can sit behind the h2 server
or the h2 router unchanged.
"""

from __future__ import annotations

import asyncio
import inspect
from typing import Any, AsyncIterator, Callable, Dict, List, Optional, Type

from linkerd_tpu.grpc.codec import Codec
from linkerd_tpu.grpc.status import (
    GrpcError, GrpcStatus, INTERNAL, OK, UNAVAILABLE, UNIMPLEMENTED, UNKNOWN,
)
from linkerd_tpu.grpc.stream import DecodingStream, EncodingStream, GrpcStream
from linkerd_tpu.grpc.proto import ProtoMessage
from linkerd_tpu.protocol.h2.messages import H2Request, H2Response, Headers
from linkerd_tpu.protocol.h2.stream import H2Stream
from linkerd_tpu.router.service import Service

CONTENT_TYPE = "application/grpc+proto"


class Rpc:
    """One method of a gRPC service."""

    __slots__ = ("name", "req_cls", "rep_cls", "client_streaming",
                 "server_streaming")

    def __init__(self, name: str, req_cls: Type[ProtoMessage],
                 rep_cls: Type[ProtoMessage],
                 client_streaming: bool = False,
                 server_streaming: bool = False):
        self.name = name
        self.req_cls = req_cls
        self.rep_cls = rep_cls
        self.client_streaming = client_streaming
        self.server_streaming = server_streaming


class ServiceDef:
    """A named gRPC service: ``full_name`` like ``io.linkerd.mesh.Interpreter``."""

    def __init__(self, full_name: str, rpcs: List[Rpc]):
        self.full_name = full_name
        self.rpcs = {r.name: r for r in rpcs}

    def path_of(self, rpc: str) -> str:
        return f"/{self.full_name}/{rpc}"


async def _drain_into(result: Any, enc: EncodingStream) -> None:
    """Pump a handler's streaming result (GrpcStream / async iterator /
    plain iterable) into the response encoder, then close with a status."""
    try:
        if hasattr(result, "__aiter__"):
            async for msg in result:
                enc.send(msg)
                if enc.is_broken:
                    break
        else:
            for msg in result:
                enc.send(msg)
                if enc.is_broken:
                    break
        enc.close(GrpcStatus(OK))
    except GrpcError as e:
        enc.close(e.status)
    except asyncio.CancelledError:
        enc.close(GrpcStatus(INTERNAL, "canceled"))
        raise
    except Exception as e:  # noqa: BLE001 - handler faults become INTERNAL
        enc.close(GrpcStatus(INTERNAL, f"{type(e).__name__}: {e}"))


class ServerDispatcher(Service[H2Request, H2Response]):
    """Routes ``/<service>/<rpc>`` h2 requests to registered handlers.

    Handler signatures by rpc shape:
      unary-unary    async (req) -> rep
      unary-stream   async (req) -> async-iter[rep]   (or GrpcStream)
      stream-unary   async (DecodingStream) -> rep
      stream-stream  async (DecodingStream) -> async-iter[rep]
    """

    def __init__(self) -> None:
        self._routes: Dict[str, tuple] = {}
        self._tasks: set = set()

    def register(self, svc: ServiceDef, rpc_name: str,
                 handler: Callable) -> None:
        rpc = svc.rpcs[rpc_name]
        self._routes[svc.path_of(rpc_name)] = (rpc, handler)

    def register_all(self, svc: ServiceDef,
                     handlers: Dict[str, Callable]) -> None:
        for name, h in handlers.items():
            self.register(svc, name, h)

    def _spawn(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def __call__(self, req: H2Request) -> H2Response:
        route = self._routes.get(req.path)
        rsp_stream = H2Stream()
        rsp = H2Response(status=200,
                         headers=Headers([("content-type", CONTENT_TYPE)]),
                         stream=rsp_stream)
        if route is None:
            enc = EncodingStream(rsp_stream, None)
            enc.close(GrpcStatus(UNIMPLEMENTED, f"unknown rpc {req.path}"))
            return rsp
        rpc, handler = route
        enc = EncodingStream(rsp_stream, Codec(rpc.rep_cls))

        async def run() -> None:
            try:
                reqs = DecodingStream(req.stream, Codec(rpc.req_cls))
                if rpc.client_streaming:
                    arg: Any = reqs
                else:
                    try:
                        arg = await reqs.recv()
                    except StopAsyncIteration:
                        raise GrpcError.of(INTERNAL, "missing request message")
                result = handler(arg)
                if inspect.isawaitable(result):
                    result = await result
                if rpc.server_streaming:
                    await _drain_into(result, enc)
                else:
                    enc.send(result)
                    enc.close(GrpcStatus(OK))
            except GrpcError as e:
                enc.close(e.status)
            except asyncio.CancelledError:
                enc.close(GrpcStatus(INTERNAL, "canceled"))
                raise
            except Exception as e:  # noqa: BLE001
                enc.close(GrpcStatus(INTERNAL, f"{type(e).__name__}: {e}"))

        self._spawn(run())
        return rsp

    async def close(self) -> None:
        for t in list(self._tasks):
            t.cancel()


class ClientDispatcher:
    """Typed client stub machinery over any h2 ``Service``.

    ``svc`` may be a raw ``H2Client`` or a full router client stack — the
    dispatcher only shapes requests (ref: ClientDispatcher.scala).
    """

    def __init__(self, svc: Service[H2Request, H2Response],
                 authority: str = ""):
        self._svc = svc
        self._authority = authority

    def _mk_request(self, path: str, stream: H2Stream) -> H2Request:
        return H2Request(
            method="POST", path=path, scheme="http",
            authority=self._authority,
            headers=Headers([("content-type", CONTENT_TYPE), ("te", "trailers")]),
            stream=stream,
        )

    async def call_stream(self, svc_def: ServiceDef, rpc_name: str,
                          req_msgs: "GrpcStream | List[ProtoMessage]",
                          ) -> DecodingStream:
        """Generic entry: send request message(s), return response stream."""
        rpc = svc_def.rpcs[rpc_name]
        req_stream = H2Stream()
        enc = EncodingStream(req_stream, Codec(rpc.req_cls))
        req = self._mk_request(svc_def.path_of(rpc_name), req_stream)

        if isinstance(req_msgs, list):
            # Unary/known request set: encode synchronously so the h2
            # engine sees a fully-buffered body (const-body fast path — no
            # pump task, headers+data+eos coalesce into one write).
            for m in req_msgs:
                enc.send(m)
            enc.close_eos()
            pump = None
        else:
            async def pump_reqs() -> None:
                try:
                    async for m in req_msgs:
                        enc.send(m)
                    enc.close_eos()
                except Exception:  # noqa: BLE001 - reset request side
                    req_stream.reset()

            pump = asyncio.ensure_future(pump_reqs())
        try:
            rsp = await self._svc(req)
        except Exception:
            if pump is not None:
                pump.cancel()
            raise
        reps = DecodingStream(rsp.stream, Codec(rpc.rep_cls))
        # Trailers-Only responses (single HEADERS + END_STREAM carrying
        # grpc-status — how conformant servers send immediate errors) and
        # non-200 proxy responses resolve the status up front.
        if rsp.status != 200:
            reps.resolve_status(GrpcStatus(
                UNAVAILABLE, f"non-200 response: {rsp.status}"))
        else:
            code_s = rsp.headers.get("grpc-status")
            if code_s is not None:
                from urllib.parse import unquote
                try:
                    code = int(code_s)
                except ValueError:
                    code = UNKNOWN
                reps.resolve_status(GrpcStatus(
                    code, unquote(rsp.headers.get("grpc-message") or "")))
        return reps

    async def unary(self, svc_def: ServiceDef, rpc_name: str,
                    req_msg: ProtoMessage) -> ProtoMessage:
        reps = await self.call_stream(svc_def, rpc_name, [req_msg])
        try:
            rep = await reps.recv()
        except StopAsyncIteration:
            raise GrpcError.of(INTERNAL, "empty unary response")
        # Drain trailers so the terminal status resolves; a non-OK status
        # after the reply is authoritative (the rpc FAILED) and propagates.
        try:
            while True:
                await reps.recv()
        except StopAsyncIteration:
            pass
        return rep

    async def server_stream(self, svc_def: ServiceDef, rpc_name: str,
                            req_msg: ProtoMessage) -> DecodingStream:
        return await self.call_stream(svc_def, rpc_name, [req_msg])
