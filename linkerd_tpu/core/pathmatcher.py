"""PathMatcher: prefix matching with variable capture over Paths.

Reference parity: finagle/buoyant/src/main/scala/com/twitter/finagle/buoyant/
PathMatcher.scala:1-92 — matches a path against a segment pattern where
``{var}`` captures one segment and ``*`` matches any one segment; captured
variables substitute into templated strings (e.g. a TLS commonName of
``{service}.example.com``). Used by per-prefix client/svc configuration
(linkerd/core/.../Client.scala, Svc.scala; StackRouter.Client.PerClientParams
router/core/.../Router.scala:271-303).
"""

from __future__ import annotations

import re
from typing import Dict, Optional

from linkerd_tpu.core.path import Path

_VAR_RE = re.compile(r"\{([^}/]+)\}")


class PathMatcher:
    """A segment-pattern prefix matcher with ``{var}`` captures."""

    def __init__(self, expr: str):
        self.expr = expr
        self._segments = tuple(Path.read(expr))

    def extract(self, path: Path) -> Optional[Dict[str, str]]:
        """Variables captured if ``path`` starts with this pattern, else None.

        A literal segment must equal the path segment; ``*`` matches any one
        segment; ``{name}`` matches any one segment and captures it.
        """
        if len(path) < len(self._segments):
            return None
        vars_: Dict[str, str] = {}
        for pat, seg in zip(self._segments, path):
            if pat == "*":
                continue
            m = _VAR_RE.fullmatch(pat)
            if m is not None:
                vars_[m.group(1)] = seg
            elif pat != seg:
                return None
        return vars_

    def matches(self, path: Path) -> bool:
        return self.extract(path) is not None

    @property
    def var_names(self) -> frozenset:
        """Names this pattern captures (for load-time template checks)."""
        out = set()
        for seg in self._segments:
            m = _VAR_RE.fullmatch(seg)
            if m is not None:
                out.add(m.group(1))
        return frozenset(out)

    def substitute(self, path: Path, template: str) -> Optional[str]:
        """``template`` with ``{var}`` replaced by captures from ``path``;
        None if the path doesn't match or a referenced var wasn't captured.
        """
        vars_ = self.extract(path)
        if vars_ is None:
            return None
        return self.substitute_vars(vars_, template)

    @staticmethod
    def substitute_vars(vars_: Dict[str, str], template: str) -> Optional[str]:
        missing = False

        def repl(m: "re.Match[str]") -> str:
            nonlocal missing
            if m.group(1) not in vars_:
                missing = True
                return m.group(0)
            return vars_[m.group(1)]

        out = _VAR_RE.sub(repl, template)
        return None if missing else out

    def __repr__(self) -> str:
        return f"PathMatcher({self.expr!r})"
