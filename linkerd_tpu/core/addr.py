"""Addr — concrete replica-set states, and bound names.

Reference parity: ``com.twitter.finagle.Addr`` (Bound/Failed/Pending/Neg)
carried in ``Var[Addr]`` from namers to balancers
(/root/reference/namer/consul/.../SvcAddr.scala, k8s EndpointsNamer), and
``Name.Bound`` (/root/reference/router/core/.../Dst.scala:42).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, FrozenSet, Tuple

from linkerd_tpu.core.path import Path
from linkerd_tpu.core.var import Var


@dataclass(frozen=True)
class Address:
    """A weighted endpoint address (host, port, weight, metadata)."""

    host: str
    port: int
    weight: float = 1.0
    meta: Tuple[Tuple[str, Any], ...] = ()

    @staticmethod
    def mk(host: str, port: int, weight: float = 1.0, **meta: Any) -> "Address":
        return Address(host, port, weight, tuple(sorted(meta.items())))

    @property
    def hostport(self) -> str:
        return f"{self.host}:{self.port}"


class Addr:
    """Replica-set state ADT."""

    __slots__ = ()


@dataclass(frozen=True)
class Bound(Addr):
    addresses: FrozenSet[Address]
    meta: Tuple[Tuple[str, Any], ...] = ()

    @staticmethod
    def of(*addresses: Address) -> "Bound":
        return Bound(frozenset(addresses))


@dataclass(frozen=True)
class AddrFailed(Addr):
    why: str


@dataclass(frozen=True)
class AddrPending(Addr):
    pass


@dataclass(frozen=True)
class AddrNeg(Addr):
    pass


ADDR_PENDING: Addr = AddrPending()
ADDR_NEG: Addr = AddrNeg()


@dataclass(frozen=True, eq=False)
class BoundName:
    """A bound name: a stable id, a live Var[Addr], and a residual path.

    Identity (hash/eq) is the ``id_`` path + residual, NOT the address state —
    the binding caches key on this (ref: Dst.Bound,
    router/core/.../DstBindingFactory.scala boundCache keying).
    """

    id_: Path
    addr: Var[Addr]
    residual: Path = Path()

    def __eq__(self, other: Any) -> bool:
        return (
            isinstance(other, BoundName)
            and other.id_ == self.id_
            and other.residual == self.residual
        )

    def __hash__(self) -> int:
        return hash((self.id_, self.residual))

    def __repr__(self) -> str:
        return f"BoundName(id={self.id_.show}, residual={self.residual.show})"
