"""Slash-delimited hierarchical paths.

Reference parity: ``com.twitter.finagle.Path`` as used for logical names
(``/svc/users``) throughout /root/reference/router/core (e.g. Dst.scala:14) and
the dtab machinery. Paths are immutable tuples of UTF-8 segments.
"""

from __future__ import annotations

from typing import Iterable, Tuple


class Path(Tuple[str, ...]):
    """An immutable, slash-rendered sequence of name segments.

    ``Path.read("/svc/users")`` -> ``Path(("svc", "users"))``;
    ``path.show`` -> ``"/svc/users"``. The empty path shows as ``"/"``.
    """

    __slots__ = ()

    def __new__(cls, segments: Iterable[str] = ()) -> "Path":
        segs = tuple(segments)
        for s in segs:
            if not isinstance(s, str):
                raise TypeError(f"path segment must be str, got {type(s).__name__}")
            if "/" in s or s == "":
                raise ValueError(f"invalid path segment: {s!r}")
        return super().__new__(cls, segs)

    @staticmethod
    def read(s: str) -> "Path":
        s = s.strip()
        if s in ("", "/"):
            return Path()
        if not s.startswith("/"):
            raise ValueError(f"path must start with '/': {s!r}")
        return Path(seg for seg in s.split("/")[1:] if seg != "")

    @staticmethod
    def of(*segments: str) -> "Path":
        return Path(segments)

    @property
    def show(self) -> str:
        return "/" + "/".join(self) if self else "/"

    @property
    def is_empty(self) -> bool:
        return len(self) == 0

    def starts_with(self, prefix: "Path") -> bool:
        return len(prefix) <= len(self) and tuple(self[: len(prefix)]) == tuple(prefix)

    def drop(self, n: int) -> "Path":
        return Path(tuple.__getitem__(self, slice(n, None)))

    def take(self, n: int) -> "Path":
        return Path(tuple.__getitem__(self, slice(None, n)))

    def concat(self, other: "Path") -> "Path":
        return Path(tuple(self) + tuple(other))

    def child(self, seg: str) -> "Path":
        return Path(tuple(self) + (seg,))

    def __add__(self, other) -> "Path":  # type: ignore[override]
        if isinstance(other, str):
            # A bare str would iterate char-by-char through Path(iterable);
            # require an explicit Path.read/child instead.
            raise TypeError("use path.child(seg) or path + Path.read(...) for str")
        return self.concat(Path(other))

    def __repr__(self) -> str:
        return f"Path({self.show!r})"

    def __str__(self) -> str:
        return self.show


EMPTY = Path()
