"""NameTree — the algebra of name resolution results.

Reference parity: ``com.twitter.finagle.NameTree`` (used pervasively:
router/core/.../Dst.scala:75 ``Dst.BoundTree``, namer/core delegation).

A NameTree[T] is one of:

- ``Leaf(value)``            — a concrete destination
- ``Alt(trees...)``          — ordered failover: first usable subtree wins
- ``Union(Weighted(w, t)..)``— weighted traffic split across usable subtrees
- ``Neg``                    — negative resolution (no binding)
- ``Empty``                  — bound, but to an empty replica set
- ``Fail``                   — resolution failed permanently

``simplified`` and ``eval`` implement the same collapse rules the reference
relies on for alt-fallback and weighted unions. The dtab text syntax
(``/a | /b``, ``0.7 * /a & 0.3 * /b``, ``~``, ``!``, ``$``) is parsed by
:func:`parse` for Leaf values of type Path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Generic, Optional, Tuple, TypeVar

from linkerd_tpu.core.path import Path

T = TypeVar("T")
U = TypeVar("U")


class NameTree(Generic[T]):
    """Base class; nodes are immutable dataclasses below."""

    __slots__ = ()

    # -- combinators ------------------------------------------------------
    def map(self, fn: Callable[[T], U]) -> "NameTree[U]":
        if isinstance(self, Leaf):
            return Leaf(fn(self.value))
        if isinstance(self, Alt):
            return Alt(*[t.map(fn) for t in self.trees])
        if isinstance(self, Union):
            return Union(*[Weighted(w.weight, w.tree.map(fn)) for w in self.weighted])
        return self  # Neg / Empty / Fail

    def flat_map(self, fn: Callable[[T], "NameTree[U]"]) -> "NameTree[U]":
        if isinstance(self, Leaf):
            return fn(self.value)
        if isinstance(self, Alt):
            return Alt(*[t.flat_map(fn) for t in self.trees])
        if isinstance(self, Union):
            return Union(*[Weighted(w.weight, w.tree.flat_map(fn)) for w in self.weighted])
        return self

    @property
    def simplified(self) -> "NameTree[T]":
        """Collapse the tree per finagle's NameTree.simplify rules:
        Alt drops Neg branches and short-circuits at Fail; Union filters
        only Neg and Fail (Empty is kept — an empty replica set is a
        binding, not a non-binding) and collapses a single surviving
        branch regardless of weight."""
        if isinstance(self, Alt):
            out = []
            for t in self.trees:
                s = t.simplified
                if isinstance(s, Fail):
                    # Fail short-circuits everything after it in an Alt.
                    out.append(s)
                    break
                if isinstance(s, Neg):
                    continue  # skip negs; later branches may bind
                out.append(s)
            if not out:
                return NEG
            if len(out) == 1:
                return out[0]
            return Alt(*out)
        if isinstance(self, Union):
            ws = []
            for w in self.weighted:
                s = w.tree.simplified
                if isinstance(s, (Neg, Fail)):
                    continue
                ws.append(Weighted(w.weight, s))
            if not ws:
                return NEG
            if len(ws) == 1:
                return ws[0].tree
            return Union(*ws)
        return self

    def eval(self) -> Optional[frozenset]:
        """Evaluate to a set of leaf values (finagle ``NameTree.eval``).

        Neg and Fail evaluate to ``None`` (no binding); Empty to the empty
        frozenset (bound to zero replicas).
        """
        return _eval(self.simplified)

    @property
    def show(self) -> str:
        return _show(self)

    def __repr__(self) -> str:
        return f"NameTree({_show(self)})"


def _eval(s: "NameTree[T]") -> Optional[frozenset]:
    """Evaluate an already-simplified tree (avoids re-simplifying subtrees)."""
    if isinstance(s, Leaf):
        return frozenset([s.value])
    if isinstance(s, Empty):
        return frozenset()
    if isinstance(s, (Neg, Fail)):
        return None
    if isinstance(s, Alt):
        for t in s.trees:
            e = _eval(t)
            if e is not None:
                return e
        return None
    if isinstance(s, Union):
        acc: set = set()
        any_ok = False
        for w in s.weighted:
            e = _eval(w.tree)
            if e is not None:
                any_ok = True
                acc |= e
        return frozenset(acc) if any_ok else None
    raise AssertionError(f"unreachable: {s!r}")


@dataclass(frozen=True, repr=False)
class Leaf(NameTree[T]):
    value: T


@dataclass(frozen=True, repr=False, init=False)
class Alt(NameTree[T]):
    trees: Tuple[NameTree[T], ...]

    def __init__(self, *trees: NameTree[T]):
        object.__setattr__(self, "trees", tuple(trees))


@dataclass(frozen=True)
class Weighted(Generic[T]):
    weight: float
    tree: NameTree[T]


@dataclass(frozen=True, repr=False, init=False)
class Union(NameTree[T]):
    weighted: Tuple[Weighted[T], ...]

    def __init__(self, *weighted: Weighted[T]):
        object.__setattr__(self, "weighted", tuple(weighted))


@dataclass(frozen=True, repr=False)
class Neg(NameTree[T]):
    pass


@dataclass(frozen=True, repr=False)
class Empty(NameTree[T]):
    pass


@dataclass(frozen=True, repr=False)
class Fail(NameTree[T]):
    pass


NEG: NameTree = Neg()
EMPTY: NameTree = Empty()
FAIL: NameTree = Fail()


def _show(t: NameTree) -> str:
    if isinstance(t, Leaf):
        v = t.value
        return v.show if isinstance(v, Path) else repr(v)
    if isinstance(t, Alt):
        return "(" + " | ".join(_show(x) for x in t.trees) + ")"
    if isinstance(t, Union):
        return "(" + " & ".join(
            (f"{w.weight} * {_show(w.tree)}" if w.weight != 1.0 else _show(w.tree))
            for w in t.weighted
        ) + ")"
    if isinstance(t, Neg):
        return "~"
    if isinstance(t, Empty):
        return "$"
    if isinstance(t, Fail):
        return "!"
    raise AssertionError(t)


# -- dtab destination text syntax -------------------------------------------
#
# Grammar matches finagle NameTreeParsers precedence: Alt ('|') binds
# loosest, Union ('&') next, and a weight attaches to a single simple tree.
#
# tree     := union ('|' union)*
# union    := weighted ('&' weighted)*
# weighted := ['<float> *'] simple
# simple   := path | '~' | '$' | '!' | '(' tree ')'


class _P:
    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def ws(self):
        while self.i < len(self.s) and self.s[self.i].isspace():
            self.i += 1

    def peek(self) -> str:
        self.ws()
        return self.s[self.i] if self.i < len(self.s) else ""

    def eat(self, ch: str):
        self.ws()
        if self.peek() != ch:
            raise ValueError(f"expected {ch!r} at {self.i} in {self.s!r}")
        self.i += 1

    def number(self) -> Optional[float]:
        self.ws()
        j = self.i
        while j < len(self.s) and (self.s[j].isdigit() or self.s[j] == "."):
            j += 1
        if j == self.i:
            return None
        # Only a weight if followed by '*'
        k = j
        while k < len(self.s) and self.s[k].isspace():
            k += 1
        if k < len(self.s) and self.s[k] == "*":
            val = float(self.s[self.i:j])
            self.i = k + 1
            return val
        return None

    def path(self) -> Path:
        self.ws()
        if self.peek() != "/":
            raise ValueError(f"expected path at {self.i} in {self.s!r}")
        j = self.i
        while j < len(self.s) and not self.s[j].isspace() and self.s[j] not in "|&()":
            j += 1
        p = Path.read(self.s[self.i:j])
        self.i = j
        return p

    def simple(self) -> NameTree[Path]:
        c = self.peek()
        if c == "~":
            self.i += 1
            return NEG
        if c == "$":
            self.i += 1
            return EMPTY
        if c == "!":
            self.i += 1
            return FAIL
        if c == "(":
            self.i += 1
            t = self.tree()
            self.eat(")")
            return t
        return Leaf(self.path())

    def weighted(self) -> Weighted[Path]:
        w = self.number()
        t = self.simple()
        return Weighted(1.0 if w is None else w, t)

    def union(self) -> NameTree[Path]:
        ws = [self.weighted()]
        while self.peek() == "&":
            self.i += 1
            ws.append(self.weighted())
        if len(ws) == 1 and ws[0].weight == 1.0:
            return ws[0].tree
        return Union(*ws)

    def tree(self) -> NameTree[Path]:
        trees = [self.union()]
        while self.peek() == "|":
            self.i += 1
            trees.append(self.union())
        return trees[0] if len(trees) == 1 else Alt(*trees)

    def parse(self) -> NameTree[Path]:
        t = self.tree()
        self.ws()
        if self.i != len(self.s):
            raise ValueError(f"trailing garbage at {self.i} in {self.s!r}")
        return t


def parse(s: str) -> NameTree[Path]:
    """Parse dtab destination syntax into a NameTree[Path]."""
    return _P(s).parse()
