"""Dtab — delegation tables.

Reference parity: ``com.twitter.finagle.Dtab`` / ``Dentry`` as used by
ConfiguredDtabNamer (/root/reference/namer/core/.../ConfiguredDtabNamer.scala:14-42)
and the namerd control plane. A dtab is an ordered list of delegation rules
``prefix => dst``; lookup rewrites a path by the *last* matching rules first
(later entries take precedence), combining alternatives with Alt.

Prefix segments may be the wildcard ``*`` which matches any single segment.

Text syntax::

    /svc => /host ;
    /host/web => /srv/web-v1 | /srv/web-v0 ;
    /srv => 0.9 * /#/io.l5d.fs & 0.1 * /#/canary
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Iterable, List, Tuple

from linkerd_tpu.core.path import Path
from linkerd_tpu.core.nametree import Alt, Leaf, NameTree, NEG, parse as parse_tree


WILDCARD = "*"

# ``#`` at line start or after whitespace opens a to-end-of-line comment
# (so l5dcheck suppressions and operator notes survive inside YAML block
# scalars and fs dtab files); ``#`` directly after ``/`` is the
# configured-namer path segment (``/#/io.l5d.fs``) and is never a
# comment, nor is ``#/`` (a comment can't shadow a path continuation).
_COMMENT_RE = re.compile(r"(?:^|(?<=\s))#(?!/).*")


@dataclass(frozen=True)
class Prefix:
    """A dentry prefix: path segments, each a literal or ``*`` wildcard."""

    segments: Tuple[str, ...]

    @staticmethod
    def read(s: str) -> "Prefix":
        # '*' is a valid Path segment, so prefix syntax is plain path syntax.
        return Prefix(tuple(Path.read(s)))

    def matches(self, path: Path) -> bool:
        if len(self.segments) > len(path):
            return False
        return all(
            pseg == WILDCARD or pseg == seg
            for pseg, seg in zip(self.segments, path)
        )

    def __len__(self) -> int:
        return len(self.segments)

    @property
    def show(self) -> str:
        return Path(self.segments).show


@dataclass(frozen=True)
class Dentry:
    prefix: Prefix
    dst: NameTree[Path]

    @staticmethod
    def read(s: str) -> "Dentry":
        if "=>" not in s:
            raise ValueError(f"dentry must contain '=>': {s!r}")
        pfx, dst = s.split("=>", 1)
        return Dentry(Prefix.read(pfx), parse_tree(dst.strip()))

    @property
    def show(self) -> str:
        return f"{self.prefix.show} => {self.dst.show}"


class Dtab(Tuple[Dentry, ...]):
    __slots__ = ()

    def __new__(cls, dentries: Iterable[Dentry] = ()) -> "Dtab":
        return super().__new__(cls, tuple(dentries))

    @staticmethod
    def read(s: str) -> "Dtab":
        """Parse ``;``-separated dentries (trailing ``;`` allowed).
        ``#``-to-end-of-line comments are stripped first (see
        ``_COMMENT_RE``)."""
        s = "\n".join(_COMMENT_RE.sub("", line) for line in s.splitlines())
        dentries = []
        for part in s.split(";"):
            part = part.strip()
            if part:
                dentries.append(Dentry.read(part))
        return Dtab(dentries)

    @staticmethod
    def empty() -> "Dtab":
        return Dtab()

    def concat(self, other: "Dtab") -> "Dtab":
        return Dtab(tuple(self) + tuple(other))

    def __add__(self, other) -> "Dtab":  # type: ignore[override]
        return self.concat(other)

    def lookup(self, path: Path) -> NameTree[Path]:
        """Rewrite ``path`` by all matching dentries, later entries first.

        Matching entries' dst trees (leaves extended with the residual path)
        are combined into an Alt; no match yields Neg.
        (ref: finagle Dtab.lookup semantics relied on by
        ConfiguredDtabNamer.scala:19-23)
        """
        matches: List[NameTree[Path]] = []
        for dentry in reversed(self):
            if dentry.prefix.matches(path):
                residual = path.drop(len(dentry.prefix))
                matches.append(dentry.dst.map(lambda p, r=residual: p.concat(r)))
        if not matches:
            return NEG
        if len(matches) == 1:
            return matches[0]
        return Alt(*matches)

    @property
    def show(self) -> str:
        return ";".join(d.show for d in self)

    def __repr__(self) -> str:
        return f"Dtab({self.show!r})"
