"""Activity — a Var of Pending / Ok / Failed states.

Reference parity: ``com.twitter.util.Activity`` — the tri-state reactive
wrapper every namer lookup and interpreter bind returns
(/root/reference/namer/core/.../ConfiguredDtabNamer.scala returns
Activity[NameTree[Name]]; mesh/Client.scala:105-165 pumps gRPC streams into
Activities with backoff-resume). Getting Pending-vs-Failed and dedup right
here is what makes live re-routing work (SURVEY.md §7 hard part 2).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, AsyncIterator, Callable, Generic, List, TypeVar

from linkerd_tpu.core.var import Closable, Var

T = TypeVar("T")
U = TypeVar("U")


class State(Generic[T]):
    __slots__ = ()


@dataclass(frozen=True)
class Pending(State[T]):
    pass


@dataclass(frozen=True)
class Ok(State[T]):
    value: T


@dataclass(frozen=True)
class Failed(State[T]):
    exc: Exception

    def __eq__(self, other: Any) -> bool:
        # Exceptions don't compare structurally; dedup on type + args.
        return (
            isinstance(other, Failed)
            and type(other.exc) is type(self.exc)
            and other.exc.args == self.exc.args
        )

    def __hash__(self) -> int:
        return hash((type(self.exc), self.exc.args))


PENDING: State = Pending()


class Activity(Generic[T]):
    """A reactive computation that is pending, has a value, or has failed."""

    def __init__(self, states: Var[State[T]]):
        self.states = states

    # -- constructors -----------------------------------------------------
    @staticmethod
    def pending() -> "Activity[T]":
        return Activity(Var(PENDING))

    @staticmethod
    def value(v: T) -> "Activity[T]":
        return Activity(Var(Ok(v)))

    @staticmethod
    def exception(e: Exception) -> "Activity[T]":
        return Activity(Var(Failed(e)))

    @staticmethod
    def mutable(initial: State[T] = PENDING) -> "Activity[T]":
        """An Activity whose state is driven externally via ``.update()``."""
        return Activity(Var(initial))

    # -- state access -----------------------------------------------------
    def sample(self) -> T:
        """Return the current value; raise if pending or failed."""
        st = self.states.sample()
        if isinstance(st, Ok):
            return st.value
        if isinstance(st, Failed):
            raise st.exc
        raise RuntimeError("Activity is pending")

    @property
    def current(self) -> State[T]:
        return self.states.sample()

    def update(self, state: State[T]) -> bool:
        return self.states.update(state)

    def set_value(self, v: T) -> bool:
        return self.states.update(Ok(v))

    def set_exception(self, e: Exception) -> bool:
        return self.states.update(Failed(e))

    # -- combinators ------------------------------------------------------
    def map(self, fn: Callable[[T], U]) -> "Activity[U]":
        def lift(st: State[T]) -> State[U]:
            if isinstance(st, Ok):
                try:
                    return Ok(fn(st.value))
                except Exception as e:  # noqa: BLE001 - map failure becomes Failed
                    return Failed(e)
            return st  # Pending / Failed pass through

        return Activity(self.states.map(lift))

    def close(self) -> None:
        """Detach this (derived) Activity from its upstreams."""
        self.states.close()

    def flat_map(self, fn: Callable[[T], "Activity[U]"]) -> "Activity[U]":
        """Chain a dependent Activity; re-subscribes on every upstream change.
        Detach the result via ``.close()``."""
        out: Var[State[U]] = Var(PENDING)
        inner_handle: List[Closable] = []

        def close_inner() -> None:
            for h in inner_handle:
                h.close()
            inner_handle.clear()

        def on_state(st: State[T]) -> None:
            close_inner()
            if isinstance(st, Ok):
                try:
                    inner = fn(st.value)
                except Exception as e:  # noqa: BLE001
                    out.update(Failed(e))
                    return
                inner_handle.append(inner.states.observe(out.update))
            elif isinstance(st, Failed):
                out.update(st)
            else:
                out.update(PENDING)

        outer = self.states.observe(on_state)
        out._upstream.append(outer)
        out._upstream.append(Closable(close_inner))
        return Activity(out)

    @staticmethod
    def collect(acts: List["Activity[T]"]) -> "Activity[tuple]":
        """All-or-nothing combination: Ok iff every input is Ok (ordered),
        Failed if any failed, else Pending."""
        def combine(states: tuple) -> State[tuple]:
            vals = []
            for st in states:
                if isinstance(st, Failed):
                    return st
                if not isinstance(st, Ok):
                    return PENDING
                vals.append(st.value)
            return Ok(tuple(vals))

        joined = Var.collect([a.states for a in acts])
        out = joined.map(combine)
        # joined is owned exclusively by this chain: cascade close so
        # Activity.collect(...).close() fully detaches from every input.
        out._upstream.append(Closable(joined.close))
        return Activity(out)

    # -- watching ---------------------------------------------------------
    async def changes(self) -> AsyncIterator[State[T]]:
        async for st in self.states.changes():
            yield st

    async def to_future(self) -> T:
        """Wait for the first non-pending state; return value or raise."""
        async for st in self.states.changes():
            if isinstance(st, Ok):
                return st.value
            if isinstance(st, Failed):
                raise st.exc
        raise RuntimeError("activity stream ended while pending")
