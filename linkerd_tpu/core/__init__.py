"""Core naming algebra and reactive cells.

Reference parity: finagle's ``Path``/``Dtab``/``NameTree``/``Name`` and
``com.twitter.util.{Var, Activity}`` as used throughout
``/root/reference/namer/core`` and ``/root/reference/router/core``.
"""

from linkerd_tpu.core.path import Path
from linkerd_tpu.core.nametree import NameTree, Leaf, Alt, Union, Neg, Empty, Fail, Weighted
from linkerd_tpu.core.dtab import Dentry, Dtab
from linkerd_tpu.core.var import Var, Closable
from linkerd_tpu.core.activity import Activity, Pending, Ok, Failed
from linkerd_tpu.core.addr import Addr, Address

__all__ = [
    "Path", "NameTree", "Leaf", "Alt", "Union", "Neg", "Empty", "Fail",
    "Weighted", "Dentry", "Dtab", "Var", "Closable", "Activity", "Pending",
    "Ok", "Failed", "Addr", "Address",
]
