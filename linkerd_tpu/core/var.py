"""Var — a watchable state cell.

Reference parity: ``com.twitter.util.Var`` — the reactive primitive that
carries live address sets from namers into load balancers
(/root/reference/namer/consul/.../SvcAddr.scala:30-95 produces Var[Addr];
router/core NameTreeFactory observes them). Design here is synchronous
callback observation plus an asyncio ``changes()`` stream for watch-style
consumers (the namerd control-plane streams ride this).

Updates are deduplicated on equality, matching the reference's behavior of
not waking observers for identical states.
"""

from __future__ import annotations

import asyncio
import logging
from typing import AsyncIterator, Callable, Generic, List, Optional, TypeVar

T = TypeVar("T")
U = TypeVar("U")

log = logging.getLogger(__name__)


class Closable:
    """A handle that detaches an observation when closed."""

    def __init__(self, fn: Callable[[], None]):
        self._fn: Optional[Callable[[], None]] = fn

    def close(self) -> None:
        fn, self._fn = self._fn, None
        if fn is not None:
            fn()

    def __enter__(self) -> "Closable":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def closable_all(*closables: "Closable") -> "Closable":
    def close_all() -> None:
        for c in closables:
            c.close()
    return Closable(close_all)


class Var(Generic[T]):
    """A mutable cell whose observers are notified on (deduplicated) change."""

    def __init__(self, initial: T):
        self._value = initial
        self._observers: List[Callable[[T], None]] = []
        self._version = 0  # monotonic; bumps on every accepted update
        # Subscriptions this Var holds on upstream Vars (for derived cells
        # built by map/collect). close() detaches them so derived cells are
        # evictable — the binding caches rely on this (SURVEY.md §7 hard
        # part 3: eviction vs in-flight observation).
        self._upstream: List[Closable] = []

    # -- reads ------------------------------------------------------------
    def sample(self) -> T:
        return self._value

    @property
    def version(self) -> int:
        return self._version

    # -- writes -----------------------------------------------------------
    def update(self, value: T) -> bool:
        """Set a new value; returns False if deduplicated (no change)."""
        try:
            if value == self._value:
                return False
        except Exception:
            pass  # incomparable values: treat as changed
        self._value = value
        self._version += 1
        for obs in list(self._observers):
            try:
                obs(value)
            except Exception:  # noqa: BLE001 — one bad observer must not
                # starve the rest or unwind into the writer (a namer watch
                # loop updating Var[Addr] must keep running).
                log.exception("Var observer raised; continuing")
        return True

    def close(self) -> None:
        """Detach this Var from its upstreams (derived cells only)."""
        ups, self._upstream = self._upstream, []
        for h in ups:
            h.close()

    # -- observation ------------------------------------------------------
    def observe(self, fn: Callable[[T], None], run_now: bool = True) -> Closable:
        """Register ``fn`` for every change; by default also run immediately
        with the current value (matching Var.changes first-event semantics)."""
        self._observers.append(fn)
        if run_now:
            fn(self._value)

        def detach() -> None:
            try:
                self._observers.remove(fn)
            except ValueError:
                pass

        return Closable(detach)

    @property
    def observer_count(self) -> int:
        return len(self._observers)

    async def changes(self) -> AsyncIterator[T]:
        """Async stream of states, starting with the current one.

        Intermediate states may be conflated (only the latest unseen state is
        yielded), matching the reference's Var semantics where observers see
        the current state, not every historical one.
        """
        loop = asyncio.get_running_loop()
        event = asyncio.Event()

        def wake(_: T) -> None:
            if loop.is_running():
                loop.call_soon_threadsafe(event.set)

        handle = self.observe(wake, run_now=False)
        try:
            # Track versions, not values: values may not support bool(==)
            # (e.g. numpy/JAX arrays), and update() already deduplicated.
            last_version = -1
            while True:
                if self._version != last_version:
                    last_version = self._version
                    yield self._value
                    continue
                event.clear()
                if self._version != last_version:
                    continue
                await event.wait()
        finally:
            handle.close()

    # -- combinators ------------------------------------------------------
    def map(self, fn: Callable[[T], U]) -> "Var[U]":
        """A derived Var; detach it from this one via ``derived.close()``."""
        derived: Var[U] = Var(fn(self._value))
        h = self.observe(lambda v: derived.update(fn(v)), run_now=False)
        derived._upstream.append(h)
        return derived

    @staticmethod
    def collect(vars_: List["Var[T]"]) -> "Var[tuple]":
        """A Var of the tuple of current values of ``vars_``;
        ``derived.close()`` detaches it from all inputs."""
        derived: Var[tuple] = Var(tuple(v.sample() for v in vars_))

        def recompute(_: T) -> None:
            derived.update(tuple(v.sample() for v in vars_))

        for v in vars_:
            derived._upstream.append(v.observe(recompute, run_now=False))
        return derived
