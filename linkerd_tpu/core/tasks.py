"""Owned background tasks: spawn-with-reference + failure logging.

``asyncio.create_task`` holds only a weak reference to the task — a
fire-and-forget spawn can be garbage-collected mid-flight, and an
exception inside it surfaces only at GC time through the loop's
exception handler (i.e. never, in practice). On the data plane that
turns a dead h2 window pump or a failed channel close into a silent
wedge. The l5dlint ``task-leak`` rule (tools/analysis) rejects dropped
spawn results; this module is the sanctioned fix:

- ``spawn(coro, what=...)``  — create the task, hold a strong reference
  in a module-level registry until it completes, and log non-cancelled
  exceptions with the ``what`` label.
- ``monitor(task, what=...)`` — attach the same failure logging to a
  task whose reference the caller already holds (long-lived loops whose
  crash should be loud even though close() cancels them).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Coroutine, Optional, Set

log = logging.getLogger(__name__)

# Strong references to in-flight fire-and-forget tasks (the event loop
# only keeps weak ones). Bounded by liveness: tasks remove themselves on
# completion.
_BACKGROUND: Set["asyncio.Task"] = set()


def _on_done(what: str):
    def cb(task: "asyncio.Task") -> None:
        _BACKGROUND.discard(task)
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            log.warning("background task %s failed: %r", what, exc)
    return cb


def spawn(coro: Coroutine, *, what: str,
          name: Optional[str] = None) -> "asyncio.Task":
    """Fire-and-forget with ownership: the returned task is also held in
    a module registry until done, and failures are logged (never
    silent). Must be called from a running event loop."""
    task = asyncio.get_running_loop().create_task(coro, name=name or what)
    _BACKGROUND.add(task)
    task.add_done_callback(_on_done(what))
    return task


def monitor(task: "asyncio.Task", *, what: str) -> "asyncio.Task":
    """Attach failure logging to an already-owned task and return it."""
    task.add_done_callback(_on_done(what))
    return task


def pending_count() -> int:
    """Registry depth (observability / test hook)."""
    return len(_BACKGROUND)
