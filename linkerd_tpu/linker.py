"""Linker: parse config -> namers -> routers -> servers.

Reference parity: linkerd/core/.../Linker.scala:101-196 (LinkerConfig.mk:
metrics tree, telemeters, namers, per-router interpreter + binding params,
port-conflict checks) and linkerd/core/.../Router.scala / Server.scala /
ProtocolInitializer for the per-router assembly; Main wiring per
linkerd/main/.../Main.scala:25-49.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from linkerd_tpu.config import (
    ConfigError, instantiate, instantiate_list, parse_config,
)
from linkerd_tpu.config.parser import instantiate_as
from linkerd_tpu.core import Activity, Dtab, Path
from linkerd_tpu.core.addr import Address, BoundName
from linkerd_tpu.namer import ConfiguredDtabNamer, Namer
from linkerd_tpu.protocol.http.client import HttpClient
from linkerd_tpu.protocol.http.identifiers import compose_identifiers
from linkerd_tpu.protocol.http.message import Request, Response
from linkerd_tpu.protocol.http.server import HttpServer
from linkerd_tpu.router.balancer import mk_balancer
from linkerd_tpu.router.binding import DstBindingFactory, DstPath
from linkerd_tpu.router.routing import (
    ErrorResponder, PerDstPathStatsFilter, RoutingService, StatsFilter,
    StatusCodeStatsFilter,
)
from linkerd_tpu.router.service import Service, filters_to_service
from linkerd_tpu.telemetry.metrics import MetricsTree

# Ensure built-in plugin registrations are loaded.
import linkerd_tpu.namer.fs  # noqa: F401
import linkerd_tpu.protocol.http.identifiers  # noqa: F401

DEFAULT_ADMIN_PORT = 9990  # ref: Linker.scala:37
DEFAULT_HTTP_PORT = 4140   # ref: linkerd http router default


@dataclass
class ServerSpec:
    port: int = 0
    ip: str = "127.0.0.1"
    maxConcurrentRequests: Optional[int] = None


@dataclass
class BalancerSpec:
    kind: str = "p2c"


@dataclass
class ClientSpec:
    loadBalancer: Optional[BalancerSpec] = None
    hostConnectionPool: int = 64
    connectTimeoutMs: int = 3000


@dataclass
class RouterSpec:
    protocol: str = "http"
    label: Optional[str] = None
    dtab: str = ""
    dstPrefix: str = "/svc"
    identifier: Optional[Any] = None      # kind-discriminated mapping(s)
    servers: Optional[List[ServerSpec]] = None
    client: Optional[ClientSpec] = None
    bindingTimeoutMs: int = 10000
    bindingCache: Optional[Dict[str, Any]] = None


@dataclass
class AdminSpec:
    port: int = DEFAULT_ADMIN_PORT
    ip: str = "127.0.0.1"


@dataclass
class LinkerSpec:
    routers: List[RouterSpec] = field(default_factory=list)
    namers: Optional[List[Any]] = None     # kind-discriminated mappings
    telemetry: Optional[List[Any]] = None  # kind-discriminated mappings
    admin: Optional[AdminSpec] = None


def parse_linker_spec(text: str) -> LinkerSpec:
    data = parse_config(text)
    if not isinstance(data, dict):
        raise ConfigError("linker config must be a mapping")
    spec = instantiate_as(LinkerSpec, data)
    if not spec.routers:
        raise ConfigError("config needs at least one router")
    return spec


class Router:
    """One configured router: routing service + its servers."""

    def __init__(self, spec: RouterSpec, label: str, service: Service,
                 binding: DstBindingFactory, servers: List[HttpServer]):
        self.spec = spec
        self.label = label
        self.service = service
        self.binding = binding
        self.servers = servers

    @property
    def server_ports(self) -> List[int]:
        return [s.bound_port for s in self.servers]

    async def start(self) -> None:
        for s in self.servers:
            await s.start()

    async def close(self) -> None:
        for s in self.servers:
            await s.close()
        await self.service.close()


class Linker:
    def __init__(self, spec: LinkerSpec, config_dict: Any = None):
        self.spec = spec
        self.config_dict = config_dict
        self.metrics = MetricsTree()
        self.namers: List[Tuple[Path, Namer]] = []
        self.routers: List[Router] = []
        self.telemeters: List[Any] = []
        self._build()

    # -- assembly ---------------------------------------------------------
    def _build(self) -> None:
        for ncfg in instantiate_list("namer", self.spec.namers, "namers"):
            prefix = Path.read(getattr(ncfg, "prefix", f"/{ncfg.kind}"))
            self.namers.append((prefix, ncfg.mk()))

        for tcfg in instantiate_list("telemeter", self.spec.telemetry, "telemetry"):
            self.telemeters.append(tcfg.mk(self.metrics))

        labels_seen: Dict[str, int] = {}
        for rspec in self.spec.routers:
            if rspec.protocol != "http":
                raise ConfigError(
                    f"protocol {rspec.protocol!r} not yet supported")
            label = rspec.label or rspec.protocol
            n = labels_seen.get(label, 0)
            labels_seen[label] = n + 1
            if n:
                label = f"{label}-{n}"
            self.routers.append(self._mk_http_router(rspec, label))

        # port-conflict check (ref: Linker.scala:189-195)
        ports = [
            (s.ip, s.port)
            for r in self.routers for s in (r.spec.servers or [])
            if s.port
        ]
        if len(ports) != len(set(ports)):
            raise ConfigError(f"server port conflict: {ports}")

    def _mk_http_router(self, rspec: RouterSpec, label: str) -> Router:
        base_dtab = Dtab.read(rspec.dtab) if rspec.dtab else Dtab.empty()
        prefix = Path.read(rspec.dstPrefix)

        # identifier(s)
        id_cfgs = rspec.identifier
        if id_cfgs is None:
            id_cfgs = [{"kind": "io.l5d.header.token"}]
        elif isinstance(id_cfgs, dict):
            id_cfgs = [id_cfgs]
        identifiers = [
            instantiate("identifier", c, f"{label}.identifier").mk(prefix, base_dtab)
            for c in id_cfgs
        ]
        identifier = compose_identifiers(identifiers)

        interpreter = ConfiguredDtabNamer(self.namers)

        cspec = rspec.client or ClientSpec()
        bal_kind = (cspec.loadBalancer or BalancerSpec()).kind
        metrics = self.metrics

        def endpoint_factory(addr: Address) -> Service:
            return HttpClient(
                addr.host, addr.port,
                max_connections=cspec.hostConnectionPool,
                connect_timeout=cspec.connectTimeoutMs / 1e3)

        def client_factory(bound: BoundName) -> Service:
            cid = bound.id_.show.lstrip("/").replace("/", ".") or "client"
            bal = mk_balancer(bal_kind, bound.addr, endpoint_factory)
            stats = StatsFilter(metrics, "rt", label, "client", cid)
            metrics.scope("rt", label, "client", cid).gauge(
                "endpoints", fn=lambda b=bal: b.size)
            return stats.and_then(bal)

        def path_filters(dst: DstPath, svc: Service) -> Service:
            name = dst.path.show.lstrip("/").replace("/", ".") or "root"
            return StatsFilter(metrics, "rt", label, "service", name).and_then(svc)

        cache_cfg = rspec.bindingCache or {}
        binding = DstBindingFactory(
            interpreter, client_factory, path_filters=path_filters,
            capacity=int(cache_cfg.get("capacity", 1000)),
            idle_ttl=float(cache_cfg.get("idleTtlSecs", 600.0)),
            bind_timeout=rspec.bindingTimeoutMs / 1e3)

        routing = RoutingService(identifier, binding)
        # Stats outermost so they observe ErrorResponder's mapped statuses.
        server_stack = filters_to_service([
            StatsFilter(metrics, "rt", label, "server"),
            StatusCodeStatsFilter(metrics, "rt", label, "server"),
            ErrorResponder(),
        ], routing)

        servers = [
            HttpServer(server_stack, s.ip, s.port,
                       max_concurrency=s.maxConcurrentRequests)
            for s in (rspec.servers or [ServerSpec()])
        ]
        return Router(rspec, label, server_stack, binding, servers)

    # -- lifecycle --------------------------------------------------------
    async def start(self) -> "Linker":
        for r in self.routers:
            await r.start()
        return self

    async def close(self) -> None:
        for r in self.routers:
            await r.close()
        for _, namer in self.namers:
            namer.close()
        for t in self.telemeters:
            t.close()


def load_linker(text: str) -> Linker:
    """Parse a YAML/JSON config into an (unstarted) Linker."""
    return Linker(parse_linker_spec(text), parse_config(text))
